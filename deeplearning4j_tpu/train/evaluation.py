"""Evaluation suite.

Reference: org.nd4j.evaluation.classification.{Evaluation, ROC,
EvaluationBinary, EvaluationCalibration} and regression.RegressionEvaluation
(SURVEY.md §2.2). Host-side numpy accumulation over batches — evaluation is
not a device bottleneck; the forward passes feeding it are jitted.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


def _to_class_indices(arr: np.ndarray) -> np.ndarray:
    """one-hot / prob [n, k] -> argmax indices; already-int [n] passes through."""
    arr = np.asarray(arr)
    if arr.ndim >= 2 and arr.shape[-1] > 1:
        return np.argmax(arr, axis=-1)
    return arr.astype(np.int64).reshape(-1)


def _flatten_time(labels: np.ndarray, preds: np.ndarray, mask: Optional[np.ndarray]):
    """[b, k, t] sequence outputs -> [b*t, k] with mask filtering."""
    if labels.ndim == 3:
        b, k, t = labels.shape
        labels = labels.transpose(0, 2, 1).reshape(b * t, k)
        preds = preds.transpose(0, 2, 1).reshape(b * t, k)
        if mask is not None:
            keep = mask.reshape(b * t) > 0
            labels, preds = labels[keep], preds[keep]
    return labels, preds


class Evaluation:
    """Multiclass classification metrics (reference: Evaluation)."""

    def __init__(self, num_classes: Optional[int] = None, labels_names: Optional[List[str]] = None,
                 top_n: int = 1) -> None:
        self.num_classes = num_classes
        self.labels_names = labels_names
        self.confusion: Optional[np.ndarray] = None
        self.top_n = int(top_n)  # reference: Evaluation(int topN)
        self._topn_correct = 0
        self._topn_total = 0

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        labels, predictions = _flatten_time(labels, predictions, mask)
        truth = _to_class_indices(labels)
        guess = _to_class_indices(predictions)
        if self.top_n > 1 and predictions.ndim >= 2 and predictions.shape[-1] > 1:
            topk = np.argsort(-predictions, axis=-1)[:, : self.top_n]
            self._topn_correct += int((topk == truth[:, None]).any(axis=1).sum())
            self._topn_total += len(truth)
        n = self.num_classes
        if n is None:
            n = int(max(truth.max(initial=0), guess.max(initial=0))) + 1
            self.num_classes = n
        if self.confusion is None:
            self.confusion = np.zeros((n, n), dtype=np.int64)
        elif self.confusion.shape[0] < n:
            grown = np.zeros((n, n), dtype=np.int64)
            grown[: self.confusion.shape[0], : self.confusion.shape[1]] = self.confusion
            self.confusion = grown
        np.add.at(self.confusion, (truth, guess), 1)

    # ---- metrics ----------------------------------------------------------
    def _check(self) -> np.ndarray:
        if self.confusion is None:
            raise ValueError("No data evaluated")
        return self.confusion

    def accuracy(self) -> float:
        c = self._check()
        total = c.sum()
        return float(np.trace(c) / total) if total else 0.0

    def _tp(self) -> np.ndarray:
        return np.diag(self._check()).astype(np.float64)

    def precision(self, cls: Optional[int] = None) -> float:
        c = self._check()
        tp = self._tp()
        denom = c.sum(axis=0).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(denom > 0, tp / denom, np.nan)
        if cls is not None:
            return float(per[cls])
        return float(np.nanmean(per))

    def recall(self, cls: Optional[int] = None) -> float:
        c = self._check()
        tp = self._tp()
        denom = c.sum(axis=1).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(denom > 0, tp / denom, np.nan)
        if cls is not None:
            return float(per[cls])
        return float(np.nanmean(per))

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def top_n_accuracy(self) -> float:
        """Top-N accuracy (reference: Evaluation(topN).topNAccuracy()) —
        only populated when probability outputs were evaluated."""
        if self.top_n <= 1:
            return self.accuracy()
        if self._topn_total == 0:
            raise ValueError("No probability predictions evaluated for top-N")
        return self._topn_correct / self._topn_total

    def false_positive_rate(self, cls: int) -> float:
        c = self._check()
        fp = c[:, cls].sum() - c[cls, cls]
        tn = c.sum() - c[cls, :].sum() - c[:, cls].sum() + c[cls, cls]
        return float(fp / (fp + tn)) if (fp + tn) > 0 else 0.0

    def matthews_correlation(self) -> float:
        c = self._check().astype(np.float64)
        t = c.sum(axis=1)
        p = c.sum(axis=0)
        s = c.sum()
        num = np.trace(c) * s - t @ p
        den = np.sqrt(s * s - p @ p) * np.sqrt(s * s - t @ t)
        return float(num / den) if den > 0 else 0.0

    def stats(self) -> str:
        c = self._check()
        name = lambda i: (self.labels_names[i] if self.labels_names else str(i))
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {c.shape[0]}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
        ]
        header = "     " + " ".join(f"{name(j):>6}" for j in range(c.shape[0]))
        lines.append(header)
        for i in range(c.shape[0]):
            lines.append(f"{name(i):>4} " + " ".join(f"{c[i, j]:>6}" for j in range(c.shape[1])))
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output binary metrics for multi-label outputs (reference:
    EvaluationBinary). Threshold 0.5."""

    def __init__(self, threshold: float = 0.5) -> None:
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = (np.asarray(predictions) >= self.threshold).astype(np.int64)
        labels_b = (labels >= 0.5).astype(np.int64)
        if mask is not None:
            keep = np.asarray(mask).astype(bool)
            labels_b = labels_b * keep
            preds = preds * keep
        tp = ((preds == 1) & (labels_b == 1)).sum(axis=0)
        fp = ((preds == 1) & (labels_b == 0)).sum(axis=0)
        tn = ((preds == 0) & (labels_b == 0)).sum(axis=0)
        fn = ((preds == 0) & (labels_b == 1)).sum(axis=0)
        if self.tp is None:
            self.tp, self.fp, self.tn, self.fn = tp, fp, tn, fn
        else:
            self.tp += tp
            self.fp += fp
            self.tn += tn
            self.fn += fn

    def accuracy(self, i: int) -> float:
        total = self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i]
        return float((self.tp[i] + self.tn[i]) / total) if total else 0.0

    def precision(self, i: int) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0


class ROC:
    """Binary ROC / AUC via threshold sweep (reference: ROC with
    thresholdSteps; exact AUC when steps=0 — here always exact)."""

    def __init__(self) -> None:
        self._scores: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels).reshape(-1)
        preds = np.asarray(predictions)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
            labels_2 = np.asarray(labels).reshape(-1, 2) if labels.size == preds.size * 2 else None
            if labels_2 is not None:
                labels = labels_2[:, 1]
        preds = preds.reshape(-1)
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, preds = labels[keep], preds[keep]
        self._labels.append(labels)
        self._scores.append(preds)

    def calculate_auc(self) -> float:
        y = np.concatenate(self._labels)
        s = np.concatenate(self._scores)
        order = np.argsort(s)
        y = y[order]
        n_pos = y.sum()
        n_neg = len(y) - n_pos
        if n_pos == 0 or n_neg == 0:
            return float("nan")
        # rank-sum (Mann-Whitney U) AUC with tie correction
        ranks = np.empty(len(s), dtype=np.float64)
        s_sorted = s[order]
        i = 0
        while i < len(s_sorted):
            j = i
            while j + 1 < len(s_sorted) and s_sorted[j + 1] == s_sorted[i]:
                j += 1
            ranks[i : j + 1] = 0.5 * (i + j) + 1.0
            i = j + 1
        pos_ranks = ranks[y > 0.5].sum()
        return float((pos_ranks - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))

    def calculate_auprc(self) -> float:
        y = np.concatenate(self._labels)
        s = np.concatenate(self._scores)
        order = np.argsort(-s)
        y = y[order]
        tp = np.cumsum(y)
        fp = np.cumsum(1 - y)
        precision = tp / np.maximum(tp + fp, 1)
        recall = tp / max(y.sum(), 1)
        # trapezoid over recall
        return float(np.trapezoid(precision, recall))


class RegressionEvaluation:
    """Per-column regression metrics (reference: RegressionEvaluation)."""

    def __init__(self) -> None:
        self._labels: List[np.ndarray] = []
        self._preds: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels, dtype=np.float64)
        preds = np.asarray(predictions, dtype=np.float64)
        labels, preds = _flatten_time(labels, preds, mask)
        if labels.ndim == 1:
            labels = labels[:, None]
            preds = preds[:, None]
        self._labels.append(labels)
        self._preds.append(preds)

    def _cat(self):
        return np.concatenate(self._labels), np.concatenate(self._preds)

    def mean_squared_error(self, col: int = 0) -> float:
        y, p = self._cat()
        return float(np.mean((y[:, col] - p[:, col]) ** 2))

    def mean_absolute_error(self, col: int = 0) -> float:
        y, p = self._cat()
        return float(np.mean(np.abs(y[:, col] - p[:, col])))

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        y, p = self._cat()
        ss_res = np.sum((y[:, col] - p[:, col]) ** 2)
        ss_tot = np.sum((y[:, col] - y[:, col].mean()) ** 2)
        return float(1.0 - ss_res / ss_tot) if ss_tot > 0 else 0.0

    def pearson_correlation(self, col: int = 0) -> float:
        y, p = self._cat()
        return float(np.corrcoef(y[:, col], p[:, col])[0, 1])

    def stats(self) -> str:
        y, _ = self._cat()
        cols = y.shape[1]
        lines = ["Column    MSE            MAE            RMSE           R^2"]
        for c in range(cols):
            lines.append(
                f"{c:<9} {self.mean_squared_error(c):<14.6f} {self.mean_absolute_error(c):<14.6f} "
                f"{self.root_mean_squared_error(c):<14.6f} {self.r_squared(c):<14.6f}"
            )
        return "\n".join(lines)


class ROCBinary:
    """Per-output binary ROC for multi-label sigmoid outputs (reference:
    ROCBinary): one exact-AUC ROC per output column."""

    def __init__(self) -> None:
        self._rocs: List[ROC] = []

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        if labels.ndim == 1:
            labels = labels[:, None]
            preds = preds[:, None]
        if mask is not None:
            mask = np.asarray(mask)
            if mask.ndim == 1:  # per-example mask applies to every output
                mask = np.broadcast_to(mask[:, None], labels.shape)
        while len(self._rocs) < labels.shape[1]:
            self._rocs.append(ROC())
        for i in range(labels.shape[1]):
            m = None if mask is None else mask[:, i]
            self._rocs[i].eval(labels[:, i], preds[:, i], mask=m)

    def calculate_auc(self, output: int) -> float:
        return self._rocs[output].calculate_auc()

    def calculate_average_auc(self) -> float:
        aucs = [r.calculate_auc() for r in self._rocs]
        return float(np.nanmean(aucs)) if aucs else float("nan")


class ROCMultiClass:
    """One-vs-all ROC per class for softmax outputs (reference:
    ROCMultiClass). ``eval`` takes one-hot (or index) labels and class
    probabilities [n, k]; AUC per class is exact (rank-sum)."""

    def __init__(self) -> None:
        self._rocs: List[ROC] = []

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        labels, preds = _flatten_time(labels, preds, mask)
        k = preds.shape[1]
        truth = _to_class_indices(labels)
        while len(self._rocs) < k:
            self._rocs.append(ROC())
        for c in range(k):
            self._rocs[c].eval((truth == c).astype(np.float64), preds[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_auprc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auprc()

    def calculate_average_auc(self) -> float:
        aucs = [r.calculate_auc() for r in self._rocs]
        return float(np.nanmean(aucs)) if aucs else float("nan")


class EvaluationCalibration:
    """Probability-calibration diagnostics (reference: EvaluationCalibration):
    reliability diagram (mean predicted probability vs observed frequency per
    confidence bin), expected calibration error, per-class probability
    histograms, and the residual-plot histogram |label - p|."""

    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 50) -> None:
        self.reliability_bins = int(reliability_bins)
        self.histogram_bins = int(histogram_bins)
        self._probs: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None) -> None:
        labels = np.asarray(labels, dtype=np.float64)
        preds = np.asarray(predictions, dtype=np.float64)
        labels, preds = _flatten_time(labels, preds, mask)
        if preds.ndim == 1:  # binary sigmoid output: one probability column
            preds = preds[:, None]
            labels = labels.reshape(-1, 1)
        elif labels.ndim == 1 or (labels.ndim == 2 and labels.shape[1] == 1
                                  and preds.shape[1] > 1):
            idx = labels.reshape(-1).astype(np.int64)
            onehot = np.zeros_like(preds)
            onehot[np.arange(len(idx)), idx] = 1.0
            labels = onehot
        self._labels.append(labels)
        self._probs.append(preds)

    def _cat(self):
        if not self._probs:
            raise ValueError("No data evaluated")
        return np.concatenate(self._labels), np.concatenate(self._probs)

    def get_reliability_info(self, cls: Optional[int] = None):
        """(mean_predicted, observed_frequency, counts) per confidence bin.
        With ``cls`` the curve is for that class's probability column;
        without, all columns pool (the reference's aggregate diagram)."""
        y, p = self._cat()
        if cls is not None:
            y, p = y[:, cls], p[:, cls]
        y, p = y.reshape(-1), p.reshape(-1)
        edges = np.linspace(0.0, 1.0, self.reliability_bins + 1)
        idx = np.clip(np.digitize(p, edges) - 1, 0, self.reliability_bins - 1)
        counts = np.bincount(idx, minlength=self.reliability_bins)
        sum_p = np.bincount(idx, weights=p, minlength=self.reliability_bins)
        sum_y = np.bincount(idx, weights=y, minlength=self.reliability_bins)
        with np.errstate(invalid="ignore"):
            mean_p = np.where(counts > 0, sum_p / counts, np.nan)
            freq = np.where(counts > 0, sum_y / counts, np.nan)
        return mean_p, freq, counts

    def expected_calibration_error(self, cls: Optional[int] = None) -> float:
        mean_p, freq, counts = self.get_reliability_info(cls)
        total = counts.sum()
        if total == 0:
            return float("nan")
        valid = counts > 0
        return float(np.sum(counts[valid] * np.abs(mean_p[valid] - freq[valid])) / total)

    def get_probability_histogram(self, cls: int):
        """(bin_edges, counts) of predicted probabilities for ``cls``."""
        _, p = self._cat()
        counts, edges = np.histogram(p[:, cls], bins=self.histogram_bins,
                                     range=(0.0, 1.0))
        return edges, counts

    def get_residual_plot(self, cls: Optional[int] = None):
        """(bin_edges, counts) of |label - p| residuals (reference:
        getResidualPlot)."""
        y, p = self._cat()
        if cls is not None:
            y, p = y[:, cls], p[:, cls]
        res = np.abs(y.reshape(-1) - p.reshape(-1))
        counts, edges = np.histogram(res, bins=self.histogram_bins,
                                     range=(0.0, 1.0))
        return edges, counts

    def stats(self) -> str:
        y, p = self._cat()
        lines = [
            "==================Calibration Evaluation==================",
            f" examples:  {len(y)}",
            f" classes:   {y.shape[1]}",
            f" ECE:       {self.expected_calibration_error():.4f}",
        ]
        mean_p, freq, counts = self.get_reliability_info()
        lines.append(" bin  mean_p  obs_freq  count")
        for i in range(self.reliability_bins):
            if counts[i]:
                lines.append(f" {i:>3}  {mean_p[i]:.4f}  {freq[i]:.4f}    {counts[i]}")
        return "\n".join(lines)
