"""GraphSolver — training machinery for ComputationGraph.

Reference: ComputationGraph.fit() shares the Solver/StochasticGradientDescent
machinery with MultiLayerNetwork (SURVEY.md §3.2 "same skeleton"). Here the
GraphSolver reuses LayerOptimizers + gradient normalization from solver.py;
the jitted step takes tuples of inputs/labels (MultiDataSet).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import DataSet, MultiDataSet
from .solver import LayerOptimizers, _normalize_gradients


class GraphSolver:
    def __init__(self, model, *, optimize=None, profiler=None,
                 donate_inputs: bool = False) -> None:
        """``optimize=`` applies training-safe graph rewrite passes at
        step-build time (see Solver.__init__ / nn/rewrite). ``profiler=``
        attaches a :class:`~deeplearning4j_tpu.obs.step_profiler.
        StepProfiler` for per-phase step attribution (see Solver).
        ``donate_inputs=True`` donates the batch buffers (xs/ys) so XLA
        reuses input HBM across steps — see Solver.__init__ for the
        freshness contract."""
        self.model = model
        self.donate_inputs = bool(donate_inputs)
        if hasattr(model, "migrate_state"):
            model.migrate_state()
        self.applied_rewrites = []
        if optimize:
            from ..nn.rewrite import rewrite_model_inplace

            self.applied_rewrites = rewrite_model_inplace(
                model, optimize, context="training")
        self.profiler = profiler
        self.optim = LayerOptimizers(model)
        self.opt_state = self.optim.init(model.params)
        self._step_cache: Dict[Any, Any] = {}

    def _step_fn(self, n_in: int, n_out: int, return_grads: bool = False):
        key = ("step", n_in, n_out, return_grads)
        if key not in self._step_cache:
            model = self.model
            conf = model.conf

            def step(params, opt_state, state, xs, ys, rng):
                def loss_fn(p):
                    return model.loss_pure(p, state, xs, ys, rng=rng, train=True)

                (score, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
                grads = _normalize_gradients(
                    grads, conf.gradient_normalization, conf.gradient_normalization_threshold
                )
                new_params, new_opt = self.optim.update(grads, opt_state, params)
                if return_grads:  # array-hungry listeners (StatsListener)
                    return new_params, new_opt, new_state, score, grads
                return new_params, new_opt, new_state, score

            donate = (0, 1, 2) + ((3, 4) if self.donate_inputs else ())
            self._step_cache[key] = jax.jit(step, donate_argnums=donate)
        return self._step_cache[key]

    def _scan_fn(self):
        key = ("scan",)
        if key not in self._step_cache:
            model = self.model
            conf = model.conf

            def one_step(carry, batch):
                params, opt_state, state, rng = carry
                xs, ys = batch
                rng, step_key = jax.random.split(rng)

                def loss_fn(p):
                    return model.loss_pure(p, state, xs, ys, rng=step_key, train=True)

                (score, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
                grads = _normalize_gradients(
                    grads, conf.gradient_normalization, conf.gradient_normalization_threshold
                )
                new_params, new_opt = self.optim.update(grads, opt_state, params)
                return (new_params, new_opt, new_state, rng), score

            def epoch(params, opt_state, state, xs, ys, rng):
                (params, opt_state, state, _), scores = jax.lax.scan(
                    one_step, (params, opt_state, state, rng), (xs, ys)
                )
                return params, opt_state, state, scores[-1]

            self._step_cache[key] = jax.jit(epoch, donate_argnums=(0, 1, 2))
        return self._step_cache[key]

    def fit_batch(self, xs: Tuple, ys: Tuple):
        model = self.model
        # StepProfiler phase attribution; mirrors Solver.fit_batch (device
        # phases fenced only on sampled steps). prof=None costs nothing.
        prof = self.profiler
        fence = prof.begin_step() if prof is not None else False
        t0 = time.perf_counter() if prof is not None else 0.0
        xs = model._as_inputs(xs)
        ys = tuple(jnp.asarray(y) for y in ys)
        if prof is not None and (fence or prof.sync_every == 0):
            if fence:
                jax.block_until_ready((xs, ys))
            prof.record("h2d", time.perf_counter() - t0, sampled=fence)
        want_grads = model.listeners.requires_arrays
        fn = self._step_fn(len(xs), len(ys), want_grads)
        rng = model._rng.next_key()
        tc = time.perf_counter() if prof is not None else 0.0
        out = fn(
            model.params, self.opt_state, model.state, xs, ys, rng
        )
        if prof is not None and (fence or prof.sync_every == 0):
            if fence:
                jax.block_until_ready(out)
            prof.record("compute", time.perf_counter() - tc, sampled=fence)
        th = time.perf_counter() if prof is not None else 0.0
        grads = None
        if want_grads:
            params, opt_state, state, score, grads = out
        else:
            params, opt_state, state, score = out
        model.params = params
        model.state = state
        self.opt_state = opt_state
        model.last_batch_size = int(xs[0].shape[0])
        if grads is not None:
            # after reassignment: pre-step buffers were donated to the step
            model.listeners.gradient_calculation(model, grads)
        if prof is not None:
            # sampled: post-fence host time is honest (see Solver)
            prof.record("host", time.perf_counter() - th, sampled=fence)
            prof.end_step()
        return score

    def fit_iterator(self, iterator, *, epochs: int = 1) -> float:
        """DataSet/MultiDataSet iterator training with exact mid-epoch
        resume semantics — see :meth:`Solver.fit_iterator` (solver.py):
        consumption starts at the iterator's CURRENT position, reset()
        only when exhausted."""
        from ..data.dataset import MultiDataSet

        model = self.model
        sync = bool(model.listeners.listeners)
        last = None
        for _ in range(epochs):
            if not iterator.has_next():
                iterator.reset()
            model.listeners.epoch_start(model)
            while iterator.has_next():
                ds = iterator.next()
                if isinstance(ds, MultiDataSet):
                    xs, ys = tuple(ds.features), tuple(ds.labels)
                else:
                    xs, ys = (ds.features,), (ds.labels,)
                score = self.fit_batch(xs, ys)
                last = score
                model.iteration_count += 1
                if sync:
                    model.score_value = float(score)
                    model.listeners.iteration_done(
                        model, model.iteration_count, model.epoch_count,
                        model.score_value)
            model.listeners.epoch_end(model)
            model.epoch_count += 1
        if last is not None:
            model.score_value = float(last)
        return model.score_value

    def fit(self, data, labels=None, *, epochs: int = 1) -> None:
        model = self.model
        sync_every_iter = bool(model.listeners.listeners)
        batches = list(self._as_multi_batches(data, labels))
        # scan fast path: uniform shapes, no listeners
        shapes = {
            tuple(np.shape(a) for a in xs) + tuple(np.shape(a) for a in ys)
            for xs, ys in batches
        }
        if (not sync_every_iter and self.profiler is None
                and batches and len(shapes) == 1):
            xs_stack = tuple(
                np.stack([np.asarray(b[0][i]) for b in batches])
                for i in range(len(batches[0][0]))
            )
            ys_stack = tuple(
                np.stack([np.asarray(b[1][i]) for b in batches])
                for i in range(len(batches[0][1]))
            )
            fn = self._scan_fn()
            last = None
            for _ in range(epochs):
                model.listeners.epoch_start(model)
                rng = model._rng.next_key()
                params, opt_state, state, score = fn(
                    model.params, self.opt_state, model.state,
                    model._as_inputs(xs_stack),
                    tuple(jnp.asarray(y) for y in ys_stack), rng,
                )
                model.params = params
                model.state = state
                self.opt_state = opt_state
                model.iteration_count += len(batches)
                model.last_batch_size = int(xs_stack[0].shape[1])
                last = score
                model.listeners.epoch_end(model)
                model.epoch_count += 1
            if last is not None:
                model.score_value = float(last)
            return

        last_score = None
        for _ in range(epochs):
            model.listeners.epoch_start(model)
            for xs, ys in batches:
                score = self.fit_batch(xs, ys)
                last_score = score
                model.iteration_count += 1
                if sync_every_iter:
                    model.score_value = float(score)
                    model.listeners.iteration_done(
                        model, model.iteration_count, model.epoch_count, model.score_value
                    )
            model.listeners.epoch_end(model)
            model.epoch_count += 1
        if last_score is not None:
            model.score_value = float(last_score)

    def _as_multi_batches(self, data, labels):
        as_tuple = self.model._as_tuple
        if labels is not None:
            yield as_tuple(data), as_tuple(labels)
            return
        if isinstance(data, (DataSet, MultiDataSet)):
            data = [data]
        for item in data:
            if isinstance(item, MultiDataSet):
                yield tuple(item.features), tuple(item.labels)
            elif isinstance(item, DataSet):
                yield (item.features,), (item.labels,)
            else:
                yield as_tuple(item[0]), as_tuple(item[1])
