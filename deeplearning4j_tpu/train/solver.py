"""Solver — the training-step machinery.

Reference: org.deeplearning4j.optimize.{Solver, solvers.StochasticGradientDescent},
MultiLayerUpdater/UpdaterBlock, gradient normalization (SURVEY.md §3.1).

TPU design: one jitted, donated train step per (mask-signature) — forward +
loss + backward + gradient normalization + per-layer updater + param update
compile into a single XLA program. The reference's per-op JNI dispatch, its
flat-buffer updater views, and its workspace management all collapse into this
one compiled function. Params and optimizer state are donated so XLA updates
buffers in place (steady-state allocation: zero — the workspace property).

Per-layer updater overrides (reference: UpdaterBlock boundaries) are honored:
each layer gets its own optax transformation chain; frozen layers get
``set_to_zero``. Decoupled weight decay applies to weight params only,
mirroring the reference's weightDecay semantics.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..core.dtypes import as_input
from ..nn.conf import BackpropType, GradientNormalization
from ..nn.layers.base import Layer
from .updaters import IUpdater, NoOp, Sgd, updater_from_any


def _normalize_gradients(
    grads: Dict[str, Dict[str, jax.Array]],
    mode: GradientNormalization,
    threshold: float,
) -> Dict[str, Dict[str, jax.Array]]:
    """Reference: GradientNormalization applied before the updater."""
    eps = 1e-8
    if mode is GradientNormalization.NONE:
        return grads
    if mode is GradientNormalization.RENORMALIZE_L2_PER_LAYER:
        out = {}
        for lname, lg in grads.items():
            norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in lg.values()) + eps)
            out[lname] = {k: g / norm for k, g in lg.items()}
        return out
    if mode is GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE:
        return jax.tree_util.tree_map(
            lambda g: g / (jnp.linalg.norm(g.ravel()) + eps), grads
        )
    if mode is GradientNormalization.CLIP_ELEMENT_WISE_ABSOLUTE_VALUE:
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, -threshold, threshold), grads
        )
    if mode is GradientNormalization.CLIP_L2_PER_LAYER:
        out = {}
        for lname, lg in grads.items():
            norm = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in lg.values()) + eps)
            scale = jnp.minimum(1.0, threshold / norm)
            out[lname] = {k: g * scale for k, g in lg.items()}
        return out
    if mode is GradientNormalization.CLIP_L2_PER_PARAM_TYPE:
        def clip(g):
            norm = jnp.linalg.norm(g.ravel()) + eps
            return g * jnp.minimum(1.0, threshold / norm)

        return jax.tree_util.tree_map(clip, grads)
    raise ValueError(f"Unhandled normalization {mode}")


class LayerOptimizers:
    """Per-layer optax chains (reference: UpdaterBlock boundaries).

    ``zero1_axis``/``zero1_sliced`` select the explicit-path ZeRO-1
    spelling of each layer's transformation
    (:meth:`~deeplearning4j_tpu.train.updaters.IUpdater.to_optax_zero1`):
    updaters whose math includes cross-element reductions (LARS/LAMB
    trust-ratio norms) re-spell them as slice-local + psum over the data
    axis, so applying the chain to 1/N parameter slices stays exactly the
    replicated update. State trees are identical either way."""

    def __init__(self, model, *, zero1_axis: Optional[str] = None,
                 zero1_sliced: Optional[Dict[str, Dict[str, bool]]] = None) -> None:
        conf = model.conf
        self.txs: Dict[str, optax.GradientTransformation] = {}
        # per-layer: is the whole update chain elementwise per tensor
        # element? (The ZeRO-1 slicing contract — see IUpdater.elementwise.
        # The weight-decay prologue is elementwise, so the chain inherits
        # the updater's flag.)
        self.elementwise: Dict[str, bool] = {}
        global_updater = updater_from_any(conf.updater) if conf.updater is not None else Sgd()
        for name, layer in model.named_param_layers():
            if layer.frozen:
                self.txs[name] = optax.set_to_zero()
                self.elementwise[name] = True
                continue
            updater = updater_from_any(layer.updater) if layer.updater is not None else global_updater
            self.elementwise[name] = bool(getattr(updater, "elementwise", False))
            sliced = (zero1_sliced or {}).get(name)
            parts = []
            wd = layer.weight_decay
            if wd:
                weight_names = set(layer.weight_param_names())
                parts.append(
                    optax.masked(
                        optax.add_decayed_weights(wd),
                        {k: (k in weight_names) for k in layer.trainable_param_names()},
                    )
                )
            if zero1_axis is not None and sliced and any(sliced.values()):
                parts.append(updater.to_optax_zero1(zero1_axis, sliced))
            else:
                parts.append(updater.to_optax())
            self.txs[name] = optax.chain(*parts) if len(parts) > 1 else parts[0]

    def init(self, params) -> Dict[str, Any]:
        return {name: tx.init(params[name]) for name, tx in self.txs.items()}

    def update(self, grads, opt_state, params):
        new_params = {}
        new_opt = {}
        for name, p in params.items():
            if name in self.txs:
                updates, new_opt[name] = self.txs[name].update(grads[name], opt_state[name], p)
                new_params[name] = optax.apply_updates(p, updates)
            else:
                new_params[name] = p
        return new_params, new_opt


class Solver:
    def __init__(self, model, *, optimize=None, profiler=None,
                 donate_inputs: bool = False) -> None:
        """``optimize=`` applies training-safe graph rewrite passes at
        step-build time (``True``/``"training"`` -> the default set:
        space-to-depth stem + BN affine precompute; or an explicit pass
        list — inference-only passes are rejected). The model is rewritten
        in place to a numerically equivalent form; rewrites are in-memory
        only and never serialized (nn/rewrite).

        ``profiler=`` attaches a
        :class:`~deeplearning4j_tpu.obs.step_profiler.StepProfiler`: each
        ``fit_batch`` attributes its time to h2d / compute / host phases
        (device phases fenced on the profiler's sampling schedule), and
        ``fit`` skips the whole-epoch ``lax.scan`` fast path because one
        fused dispatch has no per-step structure to attribute.

        ``donate_inputs=True`` additionally donates the BATCH buffers
        (x/y) to the jitted step, so XLA reuses the input HBM across
        steps instead of allocating a fresh batch-sized block every step
        — the steady-state input footprint becomes the prefetch ring
        alone. Only safe when every step gets a FRESH batch array (the
        from-files pipeline: each prefetch ``device_put`` makes a new
        buffer); callers that re-feed the same device array every step
        (synthetic micro-benches) must leave it off. Numpy inputs are
        always safe — jit copies them to device first and donates its own
        copy."""
        self.model = model
        self.donate_inputs = bool(donate_inputs)
        if hasattr(model, "migrate_state"):
            model.migrate_state()
        self.applied_rewrites = []
        if optimize:
            from ..nn.rewrite import rewrite_model_inplace

            self.applied_rewrites = rewrite_model_inplace(
                model, optimize, context="training")
        self.profiler = profiler
        self.optim = LayerOptimizers(model)
        self.opt_state = self.optim.init(model.params)
        self._step_cache: Dict[Any, Any] = {}

    def _make_step(self, has_mask: bool, has_label_mask: bool, stateful: bool,
                   return_grads: bool = False):
        model = self.model
        conf = model.conf

        def step(params, opt_state, state, rnn_state, x, y, rng, mask, label_mask):
            def loss_fn(p):
                return model.loss_pure(
                    p, state, x, y, rng=rng, mask=mask, label_mask=label_mask,
                    rnn_state=rnn_state if stateful else None, train=True,
                )

            (score, (new_state, new_rnn)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            grads = _normalize_gradients(
                grads, conf.gradient_normalization, conf.gradient_normalization_threshold
            )
            new_params, new_opt = self.optim.update(grads, opt_state, params)
            if return_grads:  # array-hungry listeners (StatsListener)
                return new_params, new_opt, new_state, new_rnn, score, grads
            return new_params, new_opt, new_state, new_rnn, score

        donate = (0, 1, 2)
        if self.donate_inputs:
            donate += (4, 5)  # x, y (masks excluded: commonly reused)
        return jax.jit(step, donate_argnums=donate)

    def _step_fn(self, has_mask, has_label_mask, stateful, return_grads=False):
        key = (has_mask, has_label_mask, stateful, return_grads)
        if key not in self._step_cache:
            self._step_cache[key] = self._make_step(*key)
        return self._step_cache[key]

    def fit_batch(self, x, y, mask=None, label_mask=None, rnn_state=None) -> Tuple[float, Optional[dict]]:
        model = self.model
        # phase attribution (StepProfiler): h2d / compute measured under a
        # block_until_ready fence ONLY on the profiler's sampled steps so
        # steady-state async dispatch stays unperturbed; host time every
        # step. prof=None is the zero-overhead path.
        prof = self.profiler
        fence = prof.begin_step() if prof is not None else False
        t0 = time.perf_counter() if prof is not None else 0.0
        x = as_input(x, model.dtype, model.keeps_int_input())
        y = jnp.asarray(y)
        mask_a = None if mask is None else jnp.asarray(mask, model.dtype)
        lmask_a = None if label_mask is None else jnp.asarray(label_mask, model.dtype)
        if prof is not None and (fence or prof.sync_every == 0):
            if fence:
                jax.block_until_ready((x, y))
            prof.record("h2d", time.perf_counter() - t0, sampled=fence)
        stateful = rnn_state is not None
        want_grads = model.listeners.requires_arrays
        fn = self._step_fn(mask_a is not None, lmask_a is not None, stateful,
                           want_grads)
        rng = model._rng.next_key()
        tc = time.perf_counter() if prof is not None else 0.0
        out = fn(
            model.params, self.opt_state, model.state,
            rnn_state if stateful else {}, x, y, rng, mask_a, lmask_a,
        )
        if prof is not None and (fence or prof.sync_every == 0):
            if fence:
                jax.block_until_ready(out)
            prof.record("compute", time.perf_counter() - tc, sampled=fence)
        th = time.perf_counter() if prof is not None else 0.0
        grads = None
        if want_grads:
            params, opt_state, state, new_rnn, score, grads = out
        else:
            params, opt_state, state, new_rnn, score = out
        model.params = params
        model.state = state
        self.opt_state = opt_state
        model.last_batch_size = int(x.shape[0])
        if grads is not None:
            # after reassignment: the pre-step buffers were donated to the
            # jitted step, so listeners must see the NEW params
            model.listeners.gradient_calculation(model, grads)
        if prof is not None:
            # sampled: after the fence the device is idle, so this host
            # segment's wall time is honest (unfenced steps share the
            # core with the in-flight device computation)
            prof.record("host", time.perf_counter() - th, sampled=fence)
            prof.end_step()
        return score, new_rnn

    def fit_scan(self, features, labels, *, steps_per_call: Optional[int] = None) -> float:
        """Compiled multi-step training: ``lax.scan`` over a stack of batches
        so an entire epoch is ONE device dispatch.

        ``features``/``labels`` are [n_batches, batch, ...] stacks. This is the
        TPU-native answer to dispatch latency (SURVEY.md §7): where the
        reference amortizes JNI overhead with workspaces, we amortize dispatch
        with a compiled training loop. Semantics identical to calling
        fit_batch n_batches times with no listeners attached; returns the
        final score.
        """
        model = self.model
        x = as_input(features, model.dtype, model.keeps_int_input())
        y = jnp.asarray(labels)
        key = ("scan",)
        if key not in self._step_cache:
            conf = model.conf

            def one_step(carry, batch):
                params, opt_state, state, rng = carry
                xb, yb = batch
                rng, step_key = jax.random.split(rng)

                def loss_fn(p):
                    return model.loss_pure(p, state, xb, yb, rng=step_key, train=True)

                (score, (new_state, _)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
                grads = _normalize_gradients(
                    grads, conf.gradient_normalization, conf.gradient_normalization_threshold
                )
                new_params, new_opt = self.optim.update(grads, opt_state, params)
                return (new_params, new_opt, new_state, rng), score

            def epoch(params, opt_state, state, xs, ys, rng):
                (params, opt_state, state, _), scores = jax.lax.scan(
                    one_step, (params, opt_state, state, rng), (xs, ys)
                )
                return params, opt_state, state, scores[-1]

            self._step_cache[key] = jax.jit(epoch, donate_argnums=(0, 1, 2))
        fn = self._step_cache[key]
        rng = self.model._rng.next_key()
        params, opt_state, state, score = fn(
            model.params, self.opt_state, model.state, x, y, rng
        )
        model.params = params
        model.state = state
        self.opt_state = opt_state
        model.iteration_count += int(x.shape[0])
        model.last_batch_size = int(x.shape[1])
        return score

    def fit_iterator(self, iterator, *, epochs: int = 1) -> float:
        """Train from a ``DataSetIterator`` WITHOUT resetting away its
        current position: consumption starts wherever the iterator
        stands, so an iterator repositioned by ``load_state_dict()``
        (train/checkpoint.py sidecar) resumes EXACTLY mid-epoch —
        finishing the interrupted epoch counts as the first of
        ``epochs``. An exhausted iterator is reset() at each epoch top
        (the normal fresh-epoch path). Listeners fire per iteration and
        per epoch exactly as in :meth:`fit`."""
        model = self.model
        sync = bool(model.listeners.listeners)
        last = None
        for _ in range(epochs):
            if not iterator.has_next():
                iterator.reset()
            model.listeners.epoch_start(model)
            while iterator.has_next():
                ds = iterator.next()
                score, _ = self.fit_batch(ds.features, ds.labels,
                                          ds.features_mask, ds.labels_mask)
                last = score
                model.iteration_count += 1
                if sync:
                    model.score_value = float(score)
                    model.listeners.iteration_done(
                        model, model.iteration_count, model.epoch_count,
                        model.score_value)
            model.listeners.epoch_end(model)
            model.epoch_count += 1
        if last is not None:
            model.score_value = float(last)
        return model.score_value

    def fit(self, data, labels=None, *, epochs: int = 1, mask=None, label_mask=None) -> None:
        model = self.model
        from ..nn.sequential import _as_batches

        # Without listeners the per-iteration score stays a device scalar —
        # fetching it would force a host sync every step and stall the XLA
        # dispatch pipeline (the reference has the same async property on CUDA:
        # JITA syncs lazily, SURVEY.md §3.1).
        sync_every_iter = bool(model.listeners.listeners)

        # Fast path: no listeners, no masks, standard backprop -> stack uniform
        # batches and run the whole epoch as one compiled scan (one dispatch).
        # A step profiler needs per-step boundaries, so it opts out.
        if (
            not sync_every_iter
            and self.profiler is None
            and mask is None
            and label_mask is None
            and model.conf.backprop_type is not BackpropType.TRUNCATED_BPTT
        ):
            batches = [
                (f, l) for f, l, m, lm in _as_batches(data, labels, mask)
                if m is None and lm is None
            ]
            shapes = {(np.shape(f), np.shape(l)) for f, l in batches}
            if batches and len(shapes) == 1:
                xs = np.stack([np.asarray(f) for f, _ in batches])
                ys = np.stack([np.asarray(l) for _, l in batches])
                last = None
                for _ in range(epochs):
                    model.listeners.epoch_start(model)
                    last = self.fit_scan(xs, ys)
                    model.listeners.epoch_end(model)
                    model.epoch_count += 1
                if last is not None:
                    model.score_value = float(last)
                return

        last_score = None
        for _ in range(epochs):
            model.listeners.epoch_start(model)
            for feats, labs, msk, lmsk in _as_batches(data, labels, mask):
                if label_mask is not None:
                    lmsk = label_mask
                if (
                    model.conf.backprop_type is BackpropType.TRUNCATED_BPTT
                    and getattr(feats, "ndim", 0) == 3
                    and feats.shape[2] > model.conf.tbptt_fwd_length
                ):
                    score = self._fit_tbptt(feats, labs, msk, lmsk)
                else:
                    score, _ = self.fit_batch(feats, labs, msk, lmsk)
                last_score = score
                model.iteration_count += 1
                if sync_every_iter:
                    model.score_value = float(score)
                    model.listeners.iteration_done(
                        model, model.iteration_count, model.epoch_count, model.score_value
                    )
            model.listeners.epoch_end(model)
            model.epoch_count += 1
        if last_score is not None:
            model.score_value = float(last_score)

    def _fit_tbptt(self, feats, labs, msk, lmsk) -> float:
        """Truncated BPTT windowed loop (reference: doTruncatedBPTT): slide a
        window of tbptt_fwd_length steps, carry RNN state (h/c) across windows
        within the batch, reset between batches."""
        model = self.model
        t_total = feats.shape[2]
        length = model.conf.tbptt_fwd_length
        rnn_state: dict = {}
        last_score = 0.0
        for start in range(0, t_total, length):
            end = min(start + length, t_total)
            fw = feats[:, :, start:end]
            lw = labs[:, :, start:end] if getattr(labs, "ndim", 0) == 3 else labs
            mw = None if msk is None else msk[:, start:end]
            lmw = None if lmsk is None else lmsk[:, start:end]
            score, new_rnn = self.fit_batch(fw, lw, mw, lmw, rnn_state=rnn_state)
            rnn_state = jax.lax.stop_gradient(new_rnn) if new_rnn else {}
            last_score = score
        return last_score
