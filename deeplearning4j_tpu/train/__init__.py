from .evaluation import (ROC, Evaluation, EvaluationBinary,
                         EvaluationCalibration, ROCBinary, ROCMultiClass,
                         RegressionEvaluation)
from .schedules import (
    CycleSchedule,
    ExponentialSchedule,
    FixedSchedule,
    ISchedule,
    InverseSchedule,
    MapSchedule,
    PolySchedule,
    RampSchedule,
    ScheduleType,
    SigmoidSchedule,
    StepSchedule,
    WarmupSchedule,
)
from .checkpoint import CheckpointListener, restore_training_state
from .fault_tolerance import (PREEMPTED_EXIT_CODE, STALL_EXIT_CODE,
                              HeartbeatListener, PreemptionHandler,
                              Watchdog, elastic_fit, read_heartbeat)
from .solver import Solver
from .updaters import (
    AMSGrad,
    AdaDelta,
    AdaGrad,
    AdaMax,
    Adam,
    AdamW,
    IUpdater,
    Lamb,
    Lars,
    Nadam,
    Nesterovs,
    NoOp,
    RmsProp,
    Sgd,
    registered_updaters,
)

from .orbax_checkpoint import OrbaxCheckpointer  # orbax itself is lazy

__all__ = [n for n in dir() if not n.startswith("_")]
