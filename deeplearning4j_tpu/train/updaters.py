"""Updaters — optimizer configs with the reference's vocabulary, optax math.

Reference: org.nd4j.linalg.learning.config.{Sgd, Adam, AdamW, AMSGrad, Nadam,
Nesterovs, RmsProp, AdaGrad, AdaDelta, AdaMax, NoOp} + the DL4J-side
MultiLayerUpdater/UpdaterBlock machinery (SURVEY.md §2.2).

TPU design: each updater config builds an ``optax.GradientTransformation``;
per-layer updater overrides (reference: UpdaterBlock boundaries) compose via
``optax.multi_transform`` over the params pytree. The whole update runs inside
the jitted train step — there is no separate updater dispatch per block as in
the reference (XLA fuses the lot).

Learning rates accept either a float or an ISchedule (train/schedules.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import optax

from ..core.config import register_config
from .schedules import ISchedule

LR = Union[float, ISchedule]


def _lr_fn(lr: LR):
    if isinstance(lr, ISchedule):
        # optax schedules get a step count; epochs enter via ScheduleType at
        # the trainer level (iteration-based inside jit).
        return lambda count: lr.value_at(count, 0)
    return float(lr)


@dataclasses.dataclass(frozen=True)
class IUpdater:
    """Base updater config."""

    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError

    @property
    def has_state(self) -> bool:
        return True

    @property
    def elementwise(self) -> bool:
        """True when the update math is independent per tensor element
        (every built-in updater: Adam family moments, momentum traces,
        RMS accumulators are all elementwise in grads/params/state).

        This is the contract ZeRO-1 weight-update sharding relies on:
        an elementwise update applied to each replica's 1/N slice of
        (grads, params, state) followed by an all-gather of the param
        slices is exactly the replicated update. An updater whose state
        couples elements across the tensor (e.g. a factored second
        moment) must override this to ``False`` — the trainer then keeps
        that layer's updater state replicated."""
        return True

    def state_partition_spec(self, param_shape, n_shards: int, axis: str = "data",
                             base=None):
        """Partition spec for a param-shaped state leaf under ZeRO-1:
        dim 0 sharded over the data axis when divisible (see
        :func:`~deeplearning4j_tpu.parallel.mesh.zero1_partition_spec`),
        replicated otherwise. Non-elementwise updaters pin their state to
        ``base`` (replicated / TP-inherited)."""
        from ..parallel.mesh import zero1_partition_spec

        if not self.elementwise:
            import jax.sharding as _shd
            return base if base is not None else _shd.PartitionSpec()
        return zero1_partition_spec(tuple(param_shape), n_shards, axis, base)


@register_config
@dataclasses.dataclass(frozen=True)
class Sgd(IUpdater):
    learning_rate: LR = 1e-1

    def to_optax(self) -> optax.GradientTransformation:
        return optax.sgd(_lr_fn(self.learning_rate))

    @property
    def has_state(self) -> bool:
        return False


@register_config
@dataclasses.dataclass(frozen=True)
class Adam(IUpdater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adam(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                          eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class AdamW(IUpdater):
    """Decoupled weight decay Adam (reference: AdamW / the weightDecay option)."""

    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 1e-2

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adamw(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon, weight_decay=self.weight_decay)


@register_config
@dataclasses.dataclass(frozen=True)
class AMSGrad(IUpdater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.amsgrad(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                             eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class Nadam(IUpdater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.nadam(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class Nesterovs(IUpdater):
    learning_rate: LR = 1e-1
    momentum: float = 0.9

    def to_optax(self) -> optax.GradientTransformation:
        return optax.sgd(_lr_fn(self.learning_rate), momentum=self.momentum, nesterov=True)


@register_config
@dataclasses.dataclass(frozen=True)
class RmsProp(IUpdater):
    learning_rate: LR = 1e-1
    decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.rmsprop(_lr_fn(self.learning_rate), decay=self.decay, eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class AdaGrad(IUpdater):
    learning_rate: LR = 1e-1
    epsilon: float = 1e-6

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adagrad(_lr_fn(self.learning_rate), eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class AdaDelta(IUpdater):
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adadelta(rho=self.rho, eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class AdaMax(IUpdater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adamax(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                            eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class NoOp(IUpdater):
    """Applies raw gradients scaled by nothing (frozen params use this)."""

    def to_optax(self) -> optax.GradientTransformation:
        return optax.set_to_zero()

    @property
    def has_state(self) -> bool:
        return False


def updater_from_any(u: Any) -> IUpdater:
    if isinstance(u, IUpdater):
        return u
    if u is None:
        return Sgd()
    raise TypeError(f"Not an updater: {u!r}")
