"""Updaters — optimizer configs with the reference's vocabulary, optax math.

Reference: org.nd4j.linalg.learning.config.{Sgd, Adam, AdamW, AMSGrad, Nadam,
Nesterovs, RmsProp, AdaGrad, AdaDelta, AdaMax, NoOp} + the DL4J-side
MultiLayerUpdater/UpdaterBlock machinery (SURVEY.md §2.2).

TPU design: each updater config builds an ``optax.GradientTransformation``;
per-layer updater overrides (reference: UpdaterBlock boundaries) compose via
``optax.multi_transform`` over the params pytree. The whole update runs inside
the jitted train step — there is no separate updater dispatch per block as in
the reference (XLA fuses the lot).

Learning rates accept either a float or an ISchedule (train/schedules.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from ..core.config import register_config
from .schedules import ISchedule

LR = Union[float, ISchedule]


def _lr_fn(lr: LR):
    if isinstance(lr, ISchedule):
        # optax schedules get a step count; epochs enter via ScheduleType at
        # the trainer level (iteration-based inside jit).
        return lambda count: lr.value_at(count, 0)
    return float(lr)


def _lr_at(lr_fn, count):
    return lr_fn(count) if callable(lr_fn) else lr_fn


# ---- layer-wise trust-ratio machinery (LARS/LAMB) -------------------------

NormFn = Callable[[Any, jax.Array], jax.Array]


def _leaf_name(path) -> str:
    """Last key of a tree_map_with_path path (the param name inside a
    layer's {pname: array} dict)."""
    k = path[-1]
    return getattr(k, "key", str(k))


def make_norm_fn(axis: Optional[str] = None,
                 sliced: Optional[Dict[str, bool]] = None) -> NormFn:
    """Squared-norm reducer for trust-ratio updaters.

    Default (``axis=None``): identity — the leaf's local squared sum IS
    the global squared norm (replicated or GSPMD-global arrays).

    ZeRO-1 explicit path: each replica holds a 1/N slice of the leaves in
    ``sliced``, so the global norm is the slice-local squared sum psummed
    over the data axis. Norms are the ONLY cross-element coupling in
    LARS/LAMB and they reduce, so this spelling keeps the 1/N-slice
    update exactly the replicated update (``IUpdater.elementwise`` stays
    honest). Non-sliced leaves (non-divisible dim 0) are full-size on
    every replica — psumming them would count them N times.
    """
    if axis is None:
        return lambda path, s: s
    flags = dict(sliced or {})

    def fn(path, s):
        if flags.get(_leaf_name(path), False):
            return jax.lax.psum(s, axis)
        return s

    return fn


def _sq_sum(a: jax.Array) -> jax.Array:
    return jnp.sum(jnp.square(a.astype(jnp.float32)))


def _trust_ratio(wn: jax.Array, un: jax.Array, coeff: float,
                 eps: float) -> jax.Array:
    """phi(||w||)/||u|| with the standard guards: a zero-norm param (fresh
    bias) or a zero update falls back to ratio 1 (plain step)."""
    return jnp.where((wn > 0.0) & (un > 0.0), coeff * wn / (un + eps), 1.0)


def _lars_tx(lr: LR, momentum: float, weight_decay: float,
             trust_coefficient: float, eps: float,
             norm_fn: Optional[NormFn] = None) -> optax.GradientTransformation:
    lr_fn = _lr_fn(lr)
    norm_fn = norm_fn or make_norm_fn()

    def init_fn(params):
        return {
            "count": jnp.zeros((), jnp.int32),
            "trace": jax.tree_util.tree_map(jnp.zeros_like, params),
            "trust": jax.tree_util.tree_map(
                lambda p: jnp.zeros((), jnp.float32), params),
            "gnorm": jax.tree_util.tree_map(
                lambda p: jnp.zeros((), jnp.float32), params),
        }

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("Lars requires params")
        count = state["count"] + 1
        lr_t = _lr_at(lr_fn, count - 1)

        def one(path, g, m, p):
            u = g + weight_decay * p if weight_decay else g
            wn = jnp.sqrt(norm_fn(path, _sq_sum(p)))
            un = jnp.sqrt(norm_fn(path, _sq_sum(u)))
            trust = _trust_ratio(wn, un, trust_coefficient, eps)
            new_m = momentum * m + (trust * u.astype(jnp.float32)).astype(m.dtype)
            return (-lr_t * new_m).astype(g.dtype), new_m, trust, un

        mapped = jax.tree_util.tree_map_with_path(
            one, updates, state["trace"], params)
        outer = jax.tree_util.tree_structure(updates)
        upd, trace, trust, gnorm = jax.tree_util.tree_transpose(
            outer, jax.tree_util.tree_structure((0, 0, 0, 0)), mapped)
        return upd, {"count": count, "trace": trace, "trust": trust,
                     "gnorm": gnorm}

    return optax.GradientTransformation(init_fn, update_fn)


def _lamb_tx(lr: LR, b1: float, b2: float, eps: float, weight_decay: float,
             trust_coefficient: float,
             norm_fn: Optional[NormFn] = None) -> optax.GradientTransformation:
    lr_fn = _lr_fn(lr)
    norm_fn = norm_fn or make_norm_fn()

    def init_fn(params):
        return {
            "count": jnp.zeros((), jnp.int32),
            "mu": jax.tree_util.tree_map(jnp.zeros_like, params),
            "nu": jax.tree_util.tree_map(jnp.zeros_like, params),
            "trust": jax.tree_util.tree_map(
                lambda p: jnp.zeros((), jnp.float32), params),
            "gnorm": jax.tree_util.tree_map(
                lambda p: jnp.zeros((), jnp.float32), params),
        }

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("Lamb requires params")
        count = state["count"] + 1
        lr_t = _lr_at(lr_fn, count - 1)
        c1 = 1.0 - b1 ** count.astype(jnp.float32)
        c2 = 1.0 - b2 ** count.astype(jnp.float32)

        def one(path, g, m, v, p):
            new_m = b1 * m + (1.0 - b1) * g
            new_v = b2 * v + (1.0 - b2) * jnp.square(g)
            adam = (new_m / c1) / (jnp.sqrt(new_v / c2) + eps)
            u = adam + weight_decay * p if weight_decay else adam
            wn = jnp.sqrt(norm_fn(path, _sq_sum(p)))
            un = jnp.sqrt(norm_fn(path, _sq_sum(u)))
            trust = _trust_ratio(wn, un, trust_coefficient, 0.0)
            return (-lr_t * trust * u).astype(g.dtype), new_m, new_v, trust, un

        mapped = jax.tree_util.tree_map_with_path(
            one, updates, state["mu"], state["nu"], params)
        outer = jax.tree_util.tree_structure(updates)
        upd, mu, nu, trust, gnorm = jax.tree_util.tree_transpose(
            outer, jax.tree_util.tree_structure((0, 0, 0, 0, 0)), mapped)
        return upd, {"count": count, "mu": mu, "nu": nu, "trust": trust,
                     "gnorm": gnorm}

    return optax.GradientTransformation(init_fn, update_fn)


@dataclasses.dataclass(frozen=True)
class IUpdater:
    """Base updater config."""

    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError

    @property
    def has_state(self) -> bool:
        return True

    @property
    def elementwise(self) -> bool:
        """True when the update math is independent per tensor element
        (every built-in updater: Adam family moments, momentum traces,
        RMS accumulators are all elementwise in grads/params/state).

        This is the contract ZeRO-1 weight-update sharding relies on:
        an elementwise update applied to each replica's 1/N slice of
        (grads, params, state) followed by an all-gather of the param
        slices is exactly the replicated update. An updater whose only
        cross-element coupling is a REDUCTION (the LARS/LAMB layer
        norms) may keep ``True`` *if* it re-spells that reduction as
        slice-local + psum via :meth:`to_optax_zero1`; an updater whose
        state couples elements non-reducibly (e.g. a factored second
        moment) must override this to ``False`` — the trainer then keeps
        that layer's updater state replicated."""
        return True

    def to_optax_zero1(self, axis: str,
                       sliced: Dict[str, bool]) -> optax.GradientTransformation:
        """The transformation as applied to per-replica 1/N parameter
        slices on the explicit (shard_map) ZeRO-1 path. ``sliced`` maps
        param name -> whether that leaf arrives sliced over ``axis``.
        Fully elementwise updaters need no collectives — the default
        returns :meth:`to_optax` unchanged. Trust-ratio updaters
        (Lars/Lamb) override this to psum their slice-local squared
        norms (see :func:`make_norm_fn`); state trees are identical in
        both spellings, so checkpoints and the replicated-path init stay
        compatible."""
        return self.to_optax()

    def state_partition_spec(self, param_shape, n_shards: int, axis: str = "data",
                             base=None):
        """Partition spec for a param-shaped state leaf under ZeRO-1:
        dim 0 sharded over the data axis when divisible (see
        :func:`~deeplearning4j_tpu.parallel.mesh.zero1_partition_spec`),
        replicated otherwise. Non-elementwise updaters pin their state to
        ``base`` (replicated / TP-inherited)."""
        from ..parallel.mesh import zero1_partition_spec

        if not self.elementwise:
            import jax.sharding as _shd
            return base if base is not None else _shd.PartitionSpec()
        return zero1_partition_spec(tuple(param_shape), n_shards, axis, base)


@register_config
@dataclasses.dataclass(frozen=True)
class Sgd(IUpdater):
    learning_rate: LR = 1e-1

    def to_optax(self) -> optax.GradientTransformation:
        return optax.sgd(_lr_fn(self.learning_rate))

    @property
    def has_state(self) -> bool:
        return False


@register_config
@dataclasses.dataclass(frozen=True)
class Adam(IUpdater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adam(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                          eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class AdamW(IUpdater):
    """Decoupled weight decay Adam (reference: AdamW / the weightDecay option)."""

    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 1e-2

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adamw(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon, weight_decay=self.weight_decay)


@register_config
@dataclasses.dataclass(frozen=True)
class AMSGrad(IUpdater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.amsgrad(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                             eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class Nadam(IUpdater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.nadam(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class Nesterovs(IUpdater):
    learning_rate: LR = 1e-1
    momentum: float = 0.9

    def to_optax(self) -> optax.GradientTransformation:
        return optax.sgd(_lr_fn(self.learning_rate), momentum=self.momentum, nesterov=True)


@register_config
@dataclasses.dataclass(frozen=True)
class RmsProp(IUpdater):
    learning_rate: LR = 1e-1
    decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.rmsprop(_lr_fn(self.learning_rate), decay=self.decay, eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class AdaGrad(IUpdater):
    learning_rate: LR = 1e-1
    epsilon: float = 1e-6

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adagrad(_lr_fn(self.learning_rate), eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class AdaDelta(IUpdater):
    # AdaDelta is self-scaling; the reference exposes no LR and applies
    # the raw delta (lr == 1). Surfaced by the auto-discovered updater
    # sweep: optax.adadelta's learning_rate defaults to None, which
    # crashes at update time.
    learning_rate: LR = 1.0
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adadelta(_lr_fn(self.learning_rate), rho=self.rho,
                              eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class AdaMax(IUpdater):
    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self) -> optax.GradientTransformation:
        return optax.adamax(_lr_fn(self.learning_rate), b1=self.beta1, b2=self.beta2,
                            eps=self.epsilon)


@register_config
@dataclasses.dataclass(frozen=True)
class Lars(IUpdater):
    """Layer-wise Adaptive Rate Scaling (You et al. 2017) — the
    large-batch SGD recipe from the MLPerf TPU-pods paper (PAPERS.md,
    arxiv 1909.09756): each parameter tensor's LR is scaled by the trust
    ratio ``trust_coefficient * ||w|| / ||g + wd*w||`` before the
    momentum accumulation, so layers whose gradients are large relative
    to their weights (the instability source at huge global batch) take
    proportionally smaller steps. Pair with
    :class:`~deeplearning4j_tpu.train.schedules.WarmupSchedule` —
    large-batch recipes need LR warmup.

    State per leaf: momentum ``trace`` (param-shaped, ZeRO-1-shardable)
    plus ``trust``/``gnorm`` scalars (last step's trust ratio and update
    norm — the ``dl4j_tpu_training_trust_ratio{layer=}`` feed)."""

    learning_rate: LR = 1e-1
    momentum: float = 0.9
    weight_decay: float = 0.0
    trust_coefficient: float = 1e-3
    epsilon: float = 1e-9

    def to_optax(self) -> optax.GradientTransformation:
        return _lars_tx(self.learning_rate, self.momentum, self.weight_decay,
                        self.trust_coefficient, self.epsilon)

    def to_optax_zero1(self, axis, sliced) -> optax.GradientTransformation:
        return _lars_tx(self.learning_rate, self.momentum, self.weight_decay,
                        self.trust_coefficient, self.epsilon,
                        norm_fn=make_norm_fn(axis, sliced))


@register_config
@dataclasses.dataclass(frozen=True)
class Lamb(IUpdater):
    """Layer-wise adaptive Adam (LAMB, You et al. 2019) — LARS's trust
    ratio applied to the bias-corrected Adam direction plus decoupled
    weight decay: ``update = -lr * (||w||/||adam + wd*w||) * (adam +
    wd*w)``. The large-batch updater for attention/BERT-family models
    where plain Adam stops converging past ~8x the tuned global batch.

    Same state layout notes as :class:`Lars` (``mu``/``nu`` moments are
    ZeRO-1-shardable; ``trust``/``gnorm`` scalars feed the trust-ratio
    metric series)."""

    learning_rate: LR = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-6
    weight_decay: float = 0.0
    trust_coefficient: float = 1.0

    def to_optax(self) -> optax.GradientTransformation:
        return _lamb_tx(self.learning_rate, self.beta1, self.beta2,
                        self.epsilon, self.weight_decay,
                        self.trust_coefficient)

    def to_optax_zero1(self, axis, sliced) -> optax.GradientTransformation:
        return _lamb_tx(self.learning_rate, self.beta1, self.beta2,
                        self.epsilon, self.weight_decay,
                        self.trust_coefficient,
                        norm_fn=make_norm_fn(axis, sliced))


@register_config
@dataclasses.dataclass(frozen=True)
class NoOp(IUpdater):
    """Applies raw gradients scaled by nothing (frozen params use this)."""

    def to_optax(self) -> optax.GradientTransformation:
        return optax.set_to_zero()

    @property
    def has_state(self) -> bool:
        return False


def updater_from_any(u: Any) -> IUpdater:
    if isinstance(u, IUpdater):
        return u
    if u is None:
        return Sgd()
    raise TypeError(f"Not an updater: {u!r}")


def registered_updaters() -> Tuple[type, ...]:
    """Every ``@register_config``'d IUpdater subclass, sorted by name —
    the discovery feed for per-updater contract tests (a future updater
    automatically inherits e.g. the zero1==replicated trajectory
    check)."""
    from ..core.config import _CONFIG_REGISTRY

    return tuple(sorted(
        (c for c in _CONFIG_REGISTRY.values()
         if isinstance(c, type) and issubclass(c, IUpdater)
         and c is not IUpdater),
        key=lambda c: c.__name__))
