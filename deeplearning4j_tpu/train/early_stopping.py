"""Early stopping.

Reference: org.deeplearning4j.earlystopping.{EarlyStoppingConfiguration,
EarlyStoppingTrainer, termination conditions, score calculators, ModelSaver}
(SURVEY.md §2.2 "Core utilities").
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class ScoreCalculator:
    """Lower-is-better score on held-out data (reference: ScoreCalculator)."""

    def calculate_score(self, model) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over an iterator (reference: DataSetLossCalculator)."""

    def __init__(self, iterator) -> None:
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        for ds in self.iterator:
            s = model.score(ds.features, ds.labels, mask=ds.features_mask,
                            label_mask=ds.labels_mask)
            b = ds.num_examples()
            total += s * b
            n += b
        return total / max(n, 1)


class ClassificationScoreCalculator(ScoreCalculator):
    """negated accuracy so lower-is-better holds."""

    def __init__(self, iterator) -> None:
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        return -model.evaluate(self.iterator).accuracy()


class TerminationCondition:
    def terminate(self, *args: Any) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(TerminationCondition):
    def __init__(self, max_epochs: int) -> None:
        self.max_epochs = max_epochs

    def terminate(self, epoch: int, *_: Any) -> bool:
        return epoch >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(TerminationCondition):
    """Stop after N epochs without improvement (reference of the same name)."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0) -> None:
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best: Optional[float] = None
        self.stale = 0

    def terminate(self, epoch: int, score: float, *_: Any) -> bool:
        if self.best is None or score < self.best - self.min_improvement:
            self.best = score
            self.stale = 0
            return False
        self.stale += 1
        return self.stale >= self.patience


class MaxTimeTerminationCondition(TerminationCondition):
    def __init__(self, max_seconds: float) -> None:
        self.max_seconds = max_seconds
        self._start = time.time()

    def terminate(self, *_: Any) -> bool:
        return (time.time() - self._start) >= self.max_seconds


class MaxScoreIterationTerminationCondition(TerminationCondition):
    """Abort if the training score explodes (reference of the same name)."""

    def __init__(self, max_score: float) -> None:
        self.max_score = max_score

    def terminate(self, score: float) -> bool:
        return score > self.max_score or not np.isfinite(score)


@dataclasses.dataclass
class EarlyStoppingConfiguration:
    score_calculator: ScoreCalculator = None
    epoch_termination_conditions: List[TerminationCondition] = dataclasses.field(default_factory=list)
    iteration_termination_conditions: List[TerminationCondition] = dataclasses.field(default_factory=list)
    evaluate_every_n_epochs: int = 1
    model_saver_path: Optional[str] = None  # save best model here
    save_last_model: bool = False


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    score_vs_epoch: Dict[int, float]
    best_model_epoch: int
    best_model_score: float
    total_epochs: int
    best_model: Any


class EarlyStoppingTrainer:
    """Reference: EarlyStoppingTrainer.fit() loop."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_data) -> None:
        self.config = config
        self.model = model
        self.train_data = train_data

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        best_score = float("inf")
        best_epoch = -1
        best_model = None
        scores: Dict[int, float] = {}
        epoch = 0
        reason, details = "EpochTerminationCondition", ""
        while True:
            # one epoch of training, watching iteration conditions
            aborted = False
            for ds in self.train_data:
                self.model.fit(ds.features, ds.labels, mask=ds.features_mask,
                               label_mask=ds.labels_mask)
                for cond in cfg.iteration_termination_conditions:
                    if cond.terminate(self.model.score_value):
                        aborted = True
                        reason = "IterationTerminationCondition"
                        details = type(cond).__name__
                        break
                if aborted:
                    break
            if aborted:
                break
            if epoch % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.model)
                scores[epoch] = score
                if score < best_score:
                    best_score = score
                    best_epoch = epoch
                    best_model = self.model.clone()
                    if cfg.model_saver_path:
                        from ..model.serializer import write_model

                        write_model(self.model, cfg.model_saver_path)
            epoch += 1
            stop = False
            for cond in cfg.epoch_termination_conditions:
                if cond.terminate(epoch, scores.get(epoch - 1, best_score)):
                    stop = True
                    details = type(cond).__name__
                    break
            if stop:
                break
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            score_vs_epoch=scores,
            best_model_epoch=best_epoch,
            best_model_score=best_score,
            total_epochs=epoch,
            best_model=best_model if best_model is not None else self.model,
        )
