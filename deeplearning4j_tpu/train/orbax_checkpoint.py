"""Orbax-backed checkpointing — the async, sharded path.

Reference mapping (SURVEY.md §5.4): ``ModelSerializer`` +
``CheckpointListener`` cover the file-format parity path (zip with
config JSON + flat coefficients); THIS module is the survey's named
"TPU equivalent: orbax-checkpoint (async, sharded) + a config-JSON
sidecar". It checkpoints a :class:`~..parallel.trainer.DistributedTrainer`
(or any params/opt_state pytree) with:

* **sharded save/restore** — each host writes only its addressable
  shards; restore places arrays back onto the live mesh's
  ``NamedSharding``s (no gather through host memory);
* **async save** — training continues while the previous step's arrays
  stream to disk (``keep_period``/max-to-keep via CheckpointManager);
* **config sidecar** — the network's JSON config saved next to the
  arrays, preserving the framework's "config is data" property.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax

from ..core.config import to_json


def _ocp():
    """Lazy import (the codebase convention for heavy optional deps —
    see samediff.tf_import._tf): orbax is present in this environment but
    must not be a hard dependency of the train package."""
    import orbax.checkpoint as ocp
    return ocp


class OrbaxCheckpointer:
    """``OrbaxCheckpointer(dir).save(step, trainer)`` / ``restore(trainer)``.

    ``max_to_keep`` mirrors CheckpointListener's keep-last-K policy;
    ``async_save=True`` overlaps serialization with the next train steps
    (callers see save() return immediately; ``wait()`` joins).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_save: bool = True) -> None:
        ocp = _ocp()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=async_save),
        )

    # ---- save -------------------------------------------------------------
    def save(self, step: int, trainer: Any, *, extra: Optional[Dict] = None) -> None:
        """Checkpoint a DistributedTrainer-like object (``params``,
        ``opt_state``, ``state``, ``iteration``) or a bare pytree."""
        if hasattr(trainer, "params"):
            tree = {
                "params": trainer.params,
                "opt_state": trainer.opt_state,
                "state": trainer.state,
                # strategy state (adaptive thresholds, residuals) must
                # survive restart or compressed-sync resumes cold
                "strat_state": getattr(trainer, "strat_state", {}),
            }
            meta = {"iteration": int(getattr(trainer, "iteration", step))}
            model = getattr(trainer, "model", None)
            rng = getattr(model, "_rng", None)
            if rng is not None:  # resume the exact noise stream (dropout)
                meta["rng_seed"] = int(rng._seed)
                meta["rng_count"] = int(rng._count)
            conf = getattr(model, "conf", None)
        else:
            tree = {"params": trainer}
            meta, conf = {}, None
        if extra:
            meta.update(extra)
        ocp = _ocp()
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                arrays=ocp.args.StandardSave(tree),
                meta=ocp.args.JsonSave(meta),
            ),
        )
        if conf is not None and jax.process_index() == 0:
            # config-JSON sidecar; process 0 only (orbax's own convention
            # for shared-filesystem metadata — N hosts must not race it)
            with open(os.path.join(self.directory, "configuration.json"),
                      "w") as f:
                f.write(to_json(conf))

    def wait(self) -> None:
        """Join any in-flight async save."""
        self._mgr.wait_until_finished()

    # ---- restore ----------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, trainer: Any, step: Optional[int] = None) -> Dict:
        """Restore IN PLACE onto the trainer's live shardings: every leaf
        comes back as a jax.Array already placed per the trainer's current
        mesh (restore-to-sharding — no host-side gather)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        ocp = _ocp()
        if hasattr(trainer, "params"):
            template = {
                "params": trainer.params,
                "opt_state": trainer.opt_state,
                "state": trainer.state,
                "strat_state": getattr(trainer, "strat_state", {}),
            }
            restored = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    arrays=ocp.args.StandardRestore(template),
                    meta=ocp.args.JsonRestore(),
                ),
            )
            tree = restored["arrays"]
            trainer.params = tree["params"]
            trainer.opt_state = tree["opt_state"]
            trainer.state = tree["state"]
            if "strat_state" in tree:
                trainer.strat_state = tree["strat_state"]
            meta = restored["meta"] or {}
            if "iteration" in meta:
                trainer.iteration = int(meta["iteration"])
            model = getattr(trainer, "model", None)
            rng = getattr(model, "_rng", None)
            if rng is not None and "rng_count" in meta:
                # replay the stream to the saved position
                from ..core.rng import RngState
                fresh = RngState(int(meta.get("rng_seed", rng._seed)))
                for _ in range(int(meta["rng_count"])):
                    fresh.next_key()
                model._rng = fresh
            return meta
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                arrays=ocp.args.StandardRestore({"params": trainer}),
                meta=ocp.args.JsonRestore(),
            ),
        )
        return restored["arrays"]["params"]

    def close(self) -> None:
        self._mgr.close()
