"""Orbax-backed checkpointing — the async, sharded path.

Reference mapping (SURVEY.md §5.4): ``ModelSerializer`` +
``CheckpointListener`` cover the file-format parity path (zip with
config JSON + flat coefficients); THIS module is the survey's named
"TPU equivalent: orbax-checkpoint (async, sharded) + a config-JSON
sidecar". It checkpoints a :class:`~..parallel.trainer.DistributedTrainer`
(or any params/opt_state pytree) with:

* **sharded save/restore** — each host writes only its addressable
  shards; restore places arrays back onto the live mesh's
  ``NamedSharding``s (no gather through host memory);
* **async save** — training continues while the previous step's arrays
  stream to disk (``keep_period``/max-to-keep via CheckpointManager);
* **config sidecar** — the network's JSON config saved next to the
  arrays, preserving the framework's "config is data" property.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax

from ..core.config import to_json


def _ocp():
    """Lazy import (the codebase convention for heavy optional deps —
    see samediff.tf_import._tf): orbax is present in this environment but
    must not be a hard dependency of the train package."""
    import orbax.checkpoint as ocp
    return ocp


def _synth_from_metadata(node):
    """Zeros restore-template matching a SAVED subtree's structure, built
    from checkpoint item metadata (dict → dict, list/tuple → list,
    array metadata → replicated zeros of its shape/dtype). Used to read
    strategy-state entries the live trainer does not keep, so orbax's
    exact-structure restore succeeds and the extras can be discarded."""
    import jax.numpy as jnp

    if node is None:
        return None
    if isinstance(node, dict):
        return {k: _synth_from_metadata(v) for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        return [_synth_from_metadata(v) for v in node]
    shape = getattr(node, "shape", None)
    dtype = getattr(node, "dtype", None)
    return jnp.zeros(tuple(shape) if shape is not None else (), dtype)


def _leaf_shapes(tree):
    return [tuple(getattr(leaf, "shape", ()) or ())
            for leaf in jax.tree_util.tree_leaves(tree)]


def _check_section_compat(name: str, template, saved, step, meta_hint: str):
    """Clear incompatibility errors BEFORE orbax's opaque structural ones.
    Sharded↔replicated (ZeRO-1) layouts are interchangeable — every leaf
    is saved at its GLOBAL shape, so restore-to-template re-shards freely
    across data-axis widths. A leaf-count or global-shape mismatch
    therefore means the model / updater / strategy differs, which no
    reshard can fix."""
    t_shapes, s_shapes = _leaf_shapes(template), _leaf_shapes(saved)
    if len(t_shapes) != len(s_shapes):
        raise ValueError(
            f"checkpoint step {step} is incompatible with the live trainer: "
            f"'{name}' holds {len(s_shapes)} saved leaves vs {len(t_shapes)} "
            f"live ({meta_hint}). ZeRO-1 sharded and replicated layouts "
            f"interchange freely (leaves are saved at global shape), so this "
            f"is a different model, updater or strategy — rebuild the "
            f"trainer to match the checkpoint.")
    bad = [(i, s, t) for i, (s, t) in enumerate(zip(s_shapes, t_shapes))
           if s != t]
    if bad:
        i, s, t = bad[0]
        raise ValueError(
            f"checkpoint step {step} is incompatible with the live trainer: "
            f"'{name}' leaf {i} was saved with global shape {s} but the live "
            f"trainer expects {t} ({len(bad)} mismatched leaves total; "
            f"{meta_hint}). Global shapes are mesh-independent — sharded↔"
            f"replicated round trips never change them — so the model or "
            f"updater configuration differs from the one checkpointed.")


class OrbaxCheckpointer:
    """``OrbaxCheckpointer(dir).save(step, trainer)`` / ``restore(trainer)``.

    ``max_to_keep`` mirrors CheckpointListener's keep-last-K policy;
    ``async_save=True`` overlaps serialization with the next train steps
    (callers see save() return immediately; ``wait()`` joins).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 async_save: bool = True) -> None:
        ocp = _ocp()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=async_save),
        )

    # ---- save -------------------------------------------------------------
    def save(self, step: int, trainer: Any, *, extra: Optional[Dict] = None) -> None:
        """Checkpoint a DistributedTrainer-like object (``params``,
        ``opt_state``, ``state``, ``iteration``) or a bare pytree."""
        if hasattr(trainer, "params"):
            tree = {
                "params": trainer.params,
                "opt_state": trainer.opt_state,
                "state": trainer.state,
                # strategy state (adaptive thresholds, residuals) must
                # survive restart or compressed-sync resumes cold
                "strat_state": getattr(trainer, "strat_state", {}),
            }
            meta = {"iteration": int(getattr(trainer, "iteration", step))}
            # layout provenance: restores are layout-independent, but the
            # hints make incompatibility errors diagnosable
            if hasattr(trainer, "zero1"):
                meta["zero1"] = bool(trainer.zero1)
            if hasattr(trainer, "n_data_shards"):
                meta["data_axis"] = int(trainer.n_data_shards)
            if hasattr(trainer, "n_stages"):  # pipeline-parallel layout
                meta["pipeline_stages"] = int(trainer.n_stages)
                meta["pipeline_schedule"] = str(
                    getattr(trainer, "schedule", ""))
            model = getattr(trainer, "model", None)
            rng = getattr(model, "_rng", None)
            if rng is not None:  # resume the exact noise stream (dropout)
                meta["rng_seed"] = int(rng._seed)
                meta["rng_count"] = int(rng._count)
            conf = getattr(model, "conf", None)
        else:
            tree = {"params": trainer}
            meta, conf = {}, None
        if extra:
            meta.update(extra)
        ocp = _ocp()
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                arrays=ocp.args.StandardSave(tree),
                meta=ocp.args.JsonSave(meta),
            ),
        )
        if conf is not None and jax.process_index() == 0:
            # config-JSON sidecar; process 0 only (orbax's own convention
            # for shared-filesystem metadata — N hosts must not race it)
            with open(os.path.join(self.directory, "configuration.json"),
                      "w") as f:
                f.write(to_json(conf))

    def wait(self) -> None:
        """Join any in-flight async save."""
        self._mgr.wait_until_finished()

    # ---- restore ----------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, trainer: Any, step: Optional[int] = None) -> Dict:
        """Restore IN PLACE onto the trainer's live shardings: every leaf
        comes back as a jax.Array already placed per the trainer's current
        mesh (restore-to-sharding — no host-side gather).

        **Layout independence (ZeRO-1):** arrays are saved at their global
        shapes, so a checkpoint written by a ``zero1=True`` trainer
        restores into a replicated one and vice versa — the template's
        live shardings drive an explicit reshard/reassemble on read.
        Incompatible *structure* (different model/updater/strategy) fails
        with a clear :class:`ValueError` before orbax's opaque one.

        **Strategy-state migration:** ``strat_state`` dict keys are
        reconciled by name — saved keys the live strategy keeps are
        restored, keys the live strategy added since the save (e.g. the
        compression ``density`` introduced with ZeRO-1) keep their fresh
        values, and saved keys the live strategy lacks are read and
        discarded (so e.g. a threshold-compressed checkpoint resumes
        under top-k with its residuals intact)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        ocp = _ocp()
        if hasattr(trainer, "params"):
            live_ss = getattr(trainer, "strat_state", {})
            template = {
                "params": trainer.params,
                "opt_state": trainer.opt_state,
                "state": trainer.state,
                "strat_state": live_ss,
            }
            saved_struct = None
            try:
                saved_struct = getattr(self._mgr.item_metadata(step),
                                       "arrays", None)
            except Exception:
                pass  # metadata unavailable: fall through to plain restore
            if saved_struct is not None:
                meta_hint = self._meta_hint(step)
                for section in ("params", "opt_state", "state"):
                    if section in saved_struct:
                        _check_section_compat(
                            section, template[section],
                            saved_struct[section], step, meta_hint)
                if "strat_state" in saved_struct:
                    template["strat_state"] = self._reconcile_strat_state(
                        live_ss, saved_struct["strat_state"])
                else:  # pre-strat_state checkpoint: nothing to read
                    template.pop("strat_state", None)
            try:
                restored = self._mgr.restore(
                    step,
                    args=ocp.args.Composite(
                        arrays=ocp.args.StandardRestore(template),
                        meta=ocp.args.JsonRestore(),
                    ),
                )
            except (ValueError, KeyError, TypeError) as e:
                raise ValueError(
                    f"checkpoint step {step} under {self.directory} does not "
                    f"match the live trainer's structure "
                    f"({self._meta_hint(step)}): {e}") from e
            tree = restored["arrays"]
            trainer.params = tree["params"]
            trainer.opt_state = tree["opt_state"]
            trainer.state = tree["state"]
            if "strat_state" in tree:
                trainer.strat_state = self._merge_strat_state(
                    live_ss, tree["strat_state"])
            meta = restored["meta"] or {}
            if "iteration" in meta:
                trainer.iteration = int(meta["iteration"])
            model = getattr(trainer, "model", None)
            rng = getattr(model, "_rng", None)
            if rng is not None and "rng_count" in meta:
                # replay the stream to the saved position
                from ..core.rng import RngState
                fresh = RngState(int(meta.get("rng_seed", rng._seed)))
                for _ in range(int(meta["rng_count"])):
                    fresh.next_key()
                model._rng = fresh
            return meta
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                arrays=ocp.args.StandardRestore({"params": trainer}),
                meta=ocp.args.JsonRestore(),
            ),
        )
        return restored["arrays"]["params"]

    # ---- compatibility helpers --------------------------------------------
    def _meta_hint(self, step: int) -> str:
        """Provenance hint for error messages: the saved layout metadata."""
        try:
            ocp = _ocp()
            meta = self._mgr.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()),
            )["meta"] or {}
            hint = (f"saved zero1={meta.get('zero1')}, "
                    f"data_axis={meta.get('data_axis')}, "
                    f"iteration={meta.get('iteration')}")
            if meta.get("pipeline_stages"):
                hint += (f", pipeline_stages={meta['pipeline_stages']}"
                         f" ({meta.get('pipeline_schedule')})")
            return hint
        except Exception:
            return "saved layout metadata unavailable"

    def _reconcile_strat_state(self, live_ss, saved_md):
        """Restore template for strat_state matching the SAVED structure:
        keys both sides share use the live leaves (live shardings drive
        placement), saved-only keys are synthesized from metadata (read
        then discarded by :meth:`_merge_strat_state`), live-only keys are
        simply not read (they keep their fresh values)."""
        if isinstance(saved_md, dict) and isinstance(live_ss, dict):
            return {k: (live_ss[k] if k in live_ss
                        else _synth_from_metadata(v))
                    for k, v in saved_md.items()}
        if not jax.tree_util.tree_leaves(live_ss):
            # live strategy keeps no state (SyncAllReduce): read the saved
            # state into synthesized zeros and drop it
            return _synth_from_metadata(saved_md)
        if not _leaf_shapes(saved_md):
            return _synth_from_metadata(saved_md)  # saved empty container
        return live_ss  # same-structure fast path (orbax enforces)

    @staticmethod
    def _merge_strat_state(live_ss, restored_ss):
        """Post-restore merge: the live strategy's key set wins — restored
        values for keys it keeps, fresh values for keys the checkpoint
        predates, nothing for keys it no longer has."""
        if isinstance(live_ss, dict) and isinstance(restored_ss, dict):
            return {k: restored_ss.get(k, v) for k, v in live_ss.items()}
        if jax.tree_util.tree_leaves(live_ss) and not isinstance(
                restored_ss, type(live_ss)):
            return live_ss
        if not jax.tree_util.tree_leaves(live_ss):
            return live_ss  # stateless live strategy: discard restored
        return restored_ss

    def close(self) -> None:
        self._mgr.close()
