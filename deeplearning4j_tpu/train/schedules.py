"""Learning-rate (and generic hyperparameter) schedules.

Reference: org.nd4j.linalg.schedule.{ISchedule, StepSchedule,
ExponentialSchedule, InverseSchedule, PolySchedule, SigmoidSchedule,
MapSchedule, CycleSchedule, RampSchedule} with ScheduleType ITERATION/EPOCH.

Each schedule is a config dataclass callable as ``sched(iteration, epoch)``;
inside a jitted step the iteration counter is a traced scalar, so schedules are
written in jnp and compile into the update program.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from ..core.config import register_config


class ScheduleType(enum.Enum):
    ITERATION = "ITERATION"
    EPOCH = "EPOCH"


@dataclasses.dataclass(frozen=True)
class ISchedule:
    def value_at(self, iteration, epoch):
        raise NotImplementedError

    def __call__(self, iteration, epoch=0):
        return self.value_at(iteration, epoch)

    def _t(self, iteration, epoch):
        st = getattr(self, "schedule_type", ScheduleType.ITERATION)
        return epoch if st is ScheduleType.EPOCH else iteration


@register_config
@dataclasses.dataclass(frozen=True)
class FixedSchedule(ISchedule):
    value: float = 1e-3

    def value_at(self, iteration, epoch):
        return self.value


@register_config
@dataclasses.dataclass(frozen=True)
class StepSchedule(ISchedule):
    """lr = initial * decay^floor(t / step)."""

    schedule_type: ScheduleType = ScheduleType.ITERATION
    initial_value: float = 1e-3
    decay_rate: float = 0.5
    step: float = 1000.0

    def value_at(self, iteration, epoch):
        t = self._t(iteration, epoch)
        return self.initial_value * self.decay_rate ** jnp.floor(t / self.step)


@register_config
@dataclasses.dataclass(frozen=True)
class ExponentialSchedule(ISchedule):
    """lr = initial * gamma^t."""

    schedule_type: ScheduleType = ScheduleType.ITERATION
    initial_value: float = 1e-3
    gamma: float = 0.99

    def value_at(self, iteration, epoch):
        return self.initial_value * self.gamma ** self._t(iteration, epoch)


@register_config
@dataclasses.dataclass(frozen=True)
class InverseSchedule(ISchedule):
    """lr = initial / (1 + gamma*t)^power."""

    schedule_type: ScheduleType = ScheduleType.ITERATION
    initial_value: float = 1e-3
    gamma: float = 0.1
    power: float = 1.0

    def value_at(self, iteration, epoch):
        t = self._t(iteration, epoch)
        return self.initial_value / (1.0 + self.gamma * t) ** self.power


@register_config
@dataclasses.dataclass(frozen=True)
class PolySchedule(ISchedule):
    """lr = initial * (1 - t/maxIter)^power."""

    schedule_type: ScheduleType = ScheduleType.ITERATION
    initial_value: float = 1e-3
    power: float = 1.0
    max_iter: int = 10000

    def value_at(self, iteration, epoch):
        t = self._t(iteration, epoch)
        frac = jnp.clip(t / self.max_iter, 0.0, 1.0)
        return self.initial_value * (1.0 - frac) ** self.power


@register_config
@dataclasses.dataclass(frozen=True)
class SigmoidSchedule(ISchedule):
    """lr = initial / (1 + exp(-gamma*(t - stepSize)))."""

    schedule_type: ScheduleType = ScheduleType.ITERATION
    initial_value: float = 1e-3
    gamma: float = 0.01
    step_size: int = 1000

    def value_at(self, iteration, epoch):
        t = self._t(iteration, epoch)
        return self.initial_value / (1.0 + jnp.exp(-self.gamma * (t - self.step_size)))


@register_config
@dataclasses.dataclass(frozen=True)
class MapSchedule(ISchedule):
    """Piecewise-constant: explicit {t: lr} map (reference: MapSchedule).
    Value holds from each key until the next."""

    schedule_type: ScheduleType = ScheduleType.ITERATION
    values: Dict[str, float] = dataclasses.field(default_factory=dict)  # str keys for JSON

    def value_at(self, iteration, epoch):
        t = self._t(iteration, epoch)
        points = sorted((int(k), v) for k, v in self.values.items())
        if not points:
            raise ValueError("MapSchedule requires at least one entry")
        result = jnp.asarray(points[0][1])
        for thresh, val in points[1:]:
            result = jnp.where(t >= thresh, val, result)
        return result


@register_config
@dataclasses.dataclass(frozen=True)
class CycleSchedule(ISchedule):
    """1-cycle schedule (reference: CycleSchedule): ramp up to max_lr, back
    down, then annihilation phase at the end."""

    initial_value: float = 1e-4
    max_value: float = 1e-2
    cycle_length: int = 1000
    annealing_cycles: int = 1
    annealing_decay: float = 0.1

    def value_at(self, iteration, epoch):
        t = iteration % self.cycle_length
        half = self.cycle_length // 2
        up = self.initial_value + (self.max_value - self.initial_value) * (t / half)
        down = self.max_value - (self.max_value - self.initial_value) * ((t - half) / half)
        lr = jnp.where(t < half, up, down)
        cycle_idx = iteration // self.cycle_length
        decay = self.annealing_decay ** jnp.minimum(cycle_idx, self.annealing_cycles)
        return lr * decay


@register_config
@dataclasses.dataclass(frozen=True)
class RampSchedule(ISchedule):
    """Linear warmup wrapper (reference: RampSchedule)."""

    underlying: Optional[ISchedule] = None
    num_iterations: int = 100

    def value_at(self, iteration, epoch):
        base = self.underlying.value_at(iteration, epoch) if self.underlying else 1.0
        warm = jnp.minimum((iteration + 1) / self.num_iterations, 1.0)
        return base * warm


@register_config
@dataclasses.dataclass(frozen=True)
class WarmupSchedule(ISchedule):
    """Linear LR warmup from 0 over ``warmup_iterations`` steps, then the
    base schedule unmodified — the large-batch LARS/LAMB recipe's first
    ingredient (the trust ratio is undefined-noisy while the moments are
    cold, so the first steps must be small). ``base`` may be any
    ISchedule or a plain float; composes like every other schedule and
    JSON round-trips (nested configs serialize polymorphically)."""

    base: Optional[ISchedule] = None
    warmup_iterations: int = 100
    base_value: float = 1.0  # used when ``base`` is None (flat warmup target)

    def value_at(self, iteration, epoch):
        v = (self.base.value_at(iteration, epoch)
             if self.base is not None else self.base_value)
        if self.warmup_iterations <= 0:
            return v
        warm = jnp.clip((iteration + 1.0) / self.warmup_iterations, 0.0, 1.0)
        return v * warm
