"""Failure detection + elastic restart.

Reference: SURVEY.md §5.3 — the reference's story is worker-failure
handling in SharedTrainingMaster plus checkpoint restart (thin, by its own
admission). Here the subsystem is first-class because this environment's
accelerator has a DOCUMENTED failure mode the reference never faces: the
axon PJRT device can wedge mid-session, hanging device dispatches instead
of raising (TPU_ATTEMPTS.jsonl records hours of it). A hung dispatch cannot
be recovered in-process — the PJRT client is poisoned — so recovery means
process supervision:

* ``HeartbeatListener`` — writes ``heartbeat.json`` (iteration/epoch/score/
  timestamp) every iteration from inside fit(); the liveness signal.
* ``Watchdog`` — a daemon thread that watches heartbeat age and calls
  ``on_stall`` when training stops making progress (default: write a
  ``stalled`` marker and hard-exit with STALL_EXIT_CODE so a supervisor
  can restart — a wedged device never returns control to Python).
* ``elastic_fit`` — the supervisor: runs a training entry point in a child
  process, restarts it from the latest checkpoint on crash OR stall, up to
  ``max_restarts`` times. The entry point is a ``"module:function"``
  reference with signature ``fn(resume_path: Optional[str],
  checkpoint_dir: str) -> None`` (spawn-safe: the child imports it fresh).
"""

from __future__ import annotations

import importlib
import json
import os
import signal as _signal
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from ..core.listeners import TrainingListener

STALL_EXIT_CODE = 86  # distinct from crash codes: "alive but not progressing"
# EX_TEMPFAIL: an EXPECTED eviction (pod preemption), not a crash — the
# supervisor restarts immediately without burning crash budget
PREEMPTED_EXIT_CODE = 75
HEARTBEAT_FILE = "heartbeat.json"
PREEMPTED_MARKER = "preempted"


class HeartbeatListener(TrainingListener):
    """Per-iteration liveness record (SURVEY §5.3 failure detection)."""

    def __init__(self, directory: str) -> None:
        self.path = os.path.join(directory, HEARTBEAT_FILE)
        os.makedirs(directory, exist_ok=True)

    def iteration_done(self, model, iteration: int, epoch: int,
                       score: float) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"iteration": iteration, "epoch": epoch,
                       "score": float(score), "ts": time.time()}, f)
        os.replace(tmp, self.path)  # atomic: the watchdog never reads a torn file


def read_heartbeat(directory: str) -> Optional[dict]:
    path = os.path.join(directory, HEARTBEAT_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class Watchdog:
    """Stall detector: fires ``on_stall`` when the heartbeat stops aging
    forward for ``timeout`` seconds. Default action writes a ``stalled``
    marker and hard-exits — the only way out of a wedged device dispatch."""

    def __init__(self, directory: str, timeout: float = 300.0,
                 on_stall: Optional[Callable[[], None]] = None,
                 poll_interval: float = 5.0) -> None:
        self.directory = directory
        self.timeout = float(timeout)
        self.poll_interval = float(poll_interval)
        self.on_stall = on_stall or self._default_stall
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = None

    def _default_stall(self) -> None:
        if self._stop.is_set():  # raced with stop(): the fit finished
            return
        with open(os.path.join(self.directory, "stalled"), "w") as f:
            f.write(f"no heartbeat progress for {self.timeout}s\n")
        sys.stderr.write("Watchdog: training stalled — exiting for "
                         "supervisor restart\n")
        sys.stderr.flush()
        os._exit(STALL_EXIT_CODE)  # noqa: SLF001 — a hung dispatch blocks clean exit

    def start(self) -> "Watchdog":
        self._started_at = time.time()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop AND JOIN the checker thread: after stop() returns, no
        stall can fire. (Setting the event alone left a race — a check
        already past the wait could still hard-exit a process whose fit
        had just finished cleanly; _fire re-checks, and the join closes
        the window for the caller.)"""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout)
        self._thread = None

    def _fire(self) -> None:
        """Stall detected: re-check stop() immediately before acting —
        the only interleaving left is stop() arriving mid-on_stall."""
        if self._stop.is_set():
            return
        self.on_stall()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            hb = read_heartbeat(self.directory)
            # never trust a heartbeat older than our own start: a restarted
            # child inherits the previous run's stale file and must get the
            # full grace period to restore + compile before its first beat
            last = max(hb["ts"], self._started_at) if hb else self._started_at
            if time.time() - last > self.timeout:
                self._fire()
                return


class PreemptionHandler(TrainingListener):
    """Preemption-aware stop: SIGTERM/SIGINT (the pod scheduler's
    eviction notice) becomes "finish the in-flight step, force a final
    SYNCHRONOUS checkpoint, exit with :data:`PREEMPTED_EXIT_CODE`".

    The signal handler only sets a flag — nothing JAX-unsafe happens in
    signal context. The NEXT ``iteration_done`` (i.e. after the in-flight
    step completed and the listener chain ran, so heartbeat/periodic
    checkpoints for this iteration are already down) performs the final
    save and exits. ``elastic_fit`` classifies the exit code as a
    preemption: immediate restart, no backoff, no crash-loop budget.

    Attach AFTER the CheckpointListener/HeartbeatListener and call
    :meth:`install` from the main thread::

        ckpt = CheckpointListener(dir_, ..., async_save=True, iterator=it)
        model.add_listeners(ckpt, HeartbeatListener(dir_),
                            PreemptionHandler(checkpoint=ckpt).install())
    """

    def __init__(self, checkpoint=None, *,
                 signals: tuple = (_signal.SIGTERM, _signal.SIGINT),
                 watchdog: Optional[Watchdog] = None,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 log_fn: Callable[[str], None] = None) -> None:
        self.checkpoint = checkpoint  # CheckpointListener (or None)
        self.signals = tuple(signals)
        self.watchdog = watchdog
        self.directory = getattr(checkpoint, "directory", None)
        self._exit = exit_fn or os._exit  # noqa: SLF001 — must exit through user code
        self.log_fn = log_fn
        self._requested = threading.Event()
        self.signal_received: Optional[int] = None
        self._prev_handlers: dict = {}

    def install(self) -> "PreemptionHandler":
        """Register the signal handlers (main thread only — a CPython
        restriction on ``signal.signal``)."""
        for s in self.signals:
            self._prev_handlers[s] = _signal.signal(s, self._on_signal)
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev_handlers.items():
            _signal.signal(s, prev)
        self._prev_handlers.clear()

    def _on_signal(self, signum, frame) -> None:
        self.signal_received = signum
        self._requested.set()

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def iteration_done(self, model, iteration: int, epoch: int,
                       score: float) -> None:
        if not self._requested.is_set():
            return
        if self.watchdog is not None:  # a final sync save is not a stall
            self.watchdog.stop()
        ok = True
        if self.checkpoint is not None:
            ok = self.checkpoint.save_now(model, iteration, epoch, score)
        if self.directory is not None:
            try:
                with open(os.path.join(self.directory, PREEMPTED_MARKER),
                          "w") as f:
                    f.write(f"signal {self.signal_received} at iteration "
                            f"{iteration}\n")
            except OSError:
                pass
        msg = (f"PreemptionHandler: signal {self.signal_received} — final "
               f"checkpoint at iteration {iteration} "
               f"{'saved' if ok else 'FAILED'}, exiting "
               f"{PREEMPTED_EXIT_CODE}")
        (self.log_fn or (lambda m: (sys.stderr.write(m + "\n"),
                                    sys.stderr.flush())))(msg)
        self._exit(PREEMPTED_EXIT_CODE)


def _resolve(ref: str) -> Callable:
    mod, _, fn = ref.partition(":")
    return getattr(importlib.import_module(mod), fn)


def _child_main() -> None:
    ref, checkpoint_dir = sys.argv[2], sys.argv[3]
    timeout = float(sys.argv[4])
    from .checkpoint import CheckpointListener

    resume = CheckpointListener.last_checkpoint(checkpoint_dir)
    # sub-second stall timeouts (tests, chaos harness) need a matching
    # poll cadence; production keeps the cheap 5s poll
    Watchdog(checkpoint_dir, timeout=timeout,
             poll_interval=min(5.0, max(0.05, timeout / 4.0))).start()
    _resolve(ref)(resume, checkpoint_dir)


def _spawn_child(entry_ref: str, checkpoint_dir: str, stall_timeout: float,
                 env: Optional[dict]) -> int:
    proc = subprocess.run(
        [sys.executable, "-c",
         "from deeplearning4j_tpu.train.fault_tolerance import "
         "_child_main; _child_main()",
         "child", entry_ref, checkpoint_dir, str(stall_timeout)],
        env={**os.environ, **(env or {})},
    )
    return proc.returncode


def elastic_fit(entry_ref: str, checkpoint_dir: str, *,
                max_restarts: int = 3, stall_timeout: float = 300.0,
                env: Optional[dict] = None,
                retry_policy: Optional["RetryPolicy"] = None,
                crash_loop_window: float = 600.0,
                crash_loop_budget: Optional[int] = None,
                log_fn: Callable[[str], None] = print,
                spawn_fn: Optional[Callable[[], int]] = None,
                sleep: Callable[[float], None] = time.sleep,
                clock: Callable[[], float] = time.monotonic,
                max_preemptions: Optional[int] = None,
                registry=None) -> dict:
    """Supervised training: run ``entry_ref`` ("module:function") in a child
    process; restart from the latest checkpoint on crash or stall.

    Restart discipline (core/resilience.py): restarts back off
    exponentially with seeded jitter (``retry_policy``) so a flaky fleet
    doesn't hammer checkpoint storage, and a restart-budget-per-window
    crash-loop detector (more than ``crash_loop_budget`` restarts inside
    ``crash_loop_window`` seconds) gives up early — a child that dies
    instantly on every boot must not burn all ``max_restarts`` at full
    speed. ``spawn_fn``/``sleep``/``clock`` are injectable and the
    ``elastic_fit.spawn`` FaultInjector site fires before every child
    launch, so the whole recovery path is testable without subprocesses.

    Exit-code classification: ``PREEMPTED_EXIT_CODE`` (a
    :class:`PreemptionHandler` stop — the child already forced a final
    sync checkpoint) restarts IMMEDIATELY: no backoff, and it consumes
    neither ``max_restarts`` nor the crash-loop budget — preemption is
    the pod's routine operation, not a failure of ours.
    ``max_preemptions`` optionally bounds an eviction storm (None =
    scheduler-driven, unbounded); ``STALL_EXIT_CODE`` and everything
    else keep the crash discipline unchanged.

    Returns {"restarts": n, "preemptions": p, "events": [...], "ok": bool}.
    The entry function must attach CheckpointListener(checkpoint_dir, ...)
    and HeartbeatListener(checkpoint_dir) itself — it owns the model and
    data.
    """
    from ..core.resilience import RetryPolicy, get_fault_injector
    from ..obs.metrics import get_registry

    reg = registry if registry is not None else get_registry()
    ev_counts = reg.counter(
        "dl4j_tpu_training_elastic_events_total",
        "elastic_fit supervisor events", ("event",))
    c_restarts = reg.counter(
        "dl4j_tpu_training_restarts_total",
        "Child restarts performed by elastic_fit")

    def record(kind: str, **fields) -> None:
        ev_counts.labels(kind).inc()
        reg.log_event("elastic_fit", event=kind, **fields)

    policy = retry_policy or RetryPolicy(
        max_retries=max_restarts, initial_backoff=1.0, max_backoff=60.0)
    budget = crash_loop_budget if crash_loop_budget is not None \
        else max(2, max_restarts)
    os.makedirs(checkpoint_dir, exist_ok=True)
    events: List[dict] = []
    restart_times: List[float] = []
    restarts = 0
    preemptions = 0
    while True:
        get_fault_injector().fire("elastic_fit.spawn")
        rc = (spawn_fn or (lambda: _spawn_child(
            entry_ref, checkpoint_dir, stall_timeout, env)))()
        if rc == 0:
            events.append({"event": "completed", "restarts": restarts})
            record("completed", restarts=restarts)
            return {"ok": True, "restarts": restarts,
                    "preemptions": preemptions, "events": events}
        kind = ("stall" if rc == STALL_EXIT_CODE
                else "preempted" if rc == PREEMPTED_EXIT_CODE else "crash")
        hb = read_heartbeat(checkpoint_dir)
        events.append({"event": kind, "rc": rc, "last_heartbeat": hb})
        record(kind, rc=rc)
        log_fn(f"elastic_fit: child {kind} (rc={rc}), last iteration "
               f"{hb['iteration'] if hb else 'none'}")
        if kind == "preempted":
            # expected eviction: the child checkpointed and asked to be
            # rescheduled — restart NOW, burn no crash budget of any kind
            preemptions += 1
            if max_preemptions is not None and preemptions > max_preemptions:
                events.append({"event": "gave_up", "restarts": restarts,
                               "preemptions": preemptions})
                record("gave_up", restarts=restarts)
                log_fn(f"elastic_fit: {preemptions} preemptions exceed "
                       f"max_preemptions={max_preemptions}, giving up")
                return {"ok": False, "restarts": restarts,
                        "preemptions": preemptions, "events": events}
            c_restarts.inc()
            continue
        if restarts >= max_restarts:
            events.append({"event": "gave_up", "restarts": restarts})
            record("gave_up", restarts=restarts)
            return {"ok": False, "restarts": restarts,
                    "preemptions": preemptions, "events": events}
        now = clock()
        restart_times = [t for t in restart_times
                         if now - t <= crash_loop_window]
        if len(restart_times) >= budget:
            events.append({"event": "crash_loop", "restarts": restarts,
                           "window_s": crash_loop_window, "budget": budget})
            record("crash_loop", restarts=restarts)
            log_fn(f"elastic_fit: crash loop — {len(restart_times) + 1} "
                   f"failures within {crash_loop_window}s, giving up")
            return {"ok": False, "restarts": restarts,
                    "preemptions": preemptions, "events": events}
        restart_times.append(now)
        delay = policy.backoff(restarts)
        events.append({"event": "backoff", "delay_s": delay})
        record("backoff", delay_s=delay)
        log_fn(f"elastic_fit: restarting in {delay:.2f}s "
               f"(restart {restarts + 1}/{max_restarts})")
        sleep(delay)
        c_restarts.inc()
        restarts += 1
