"""Failure detection + elastic restart.

Reference: SURVEY.md §5.3 — the reference's story is worker-failure
handling in SharedTrainingMaster plus checkpoint restart (thin, by its own
admission). Here the subsystem is first-class because this environment's
accelerator has a DOCUMENTED failure mode the reference never faces: the
axon PJRT device can wedge mid-session, hanging device dispatches instead
of raising (TPU_ATTEMPTS.jsonl records hours of it). A hung dispatch cannot
be recovered in-process — the PJRT client is poisoned — so recovery means
process supervision:

* ``HeartbeatListener`` — writes ``heartbeat.json`` (iteration/epoch/score/
  timestamp) every iteration from inside fit(); the liveness signal.
* ``Watchdog`` — a daemon thread that watches heartbeat age and calls
  ``on_stall`` when training stops making progress (default: write a
  ``stalled`` marker and hard-exit with STALL_EXIT_CODE so a supervisor
  can restart — a wedged device never returns control to Python).
* ``elastic_fit`` — the supervisor: runs a training entry point in a child
  process, restarts it from the latest checkpoint on crash OR stall, up to
  ``max_restarts`` times. The entry point is a ``"module:function"``
  reference with signature ``fn(resume_path: Optional[str],
  checkpoint_dir: str) -> None`` (spawn-safe: the child imports it fresh),
  or ``fn(resume_path, checkpoint_dir, mesh_size)`` for resize-aware
  entries (see below).

Elastic resize (README "Elastic resize"): ``elastic_fit(mesh_size_fn=...)``
re-resolves the available device count before EVERY child boot, so a run
survives the fleet shrinking or growing mid-run: the new width reaches the
child via ``DL4J_ELASTIC_MESH_SIZE`` (and, on the CPU mesh,
``--xla_force_host_platform_device_count``), the entry function rebuilds
its trainer on the new mesh, and the checkpoint restore re-shards ZeRO-1
state onto the new ``data_axis`` width. The supervisor also keeps a
goodput ledger — ``dl4j_tpu_training_goodput_ratio`` plus
``dl4j_tpu_training_downtime_seconds_total{reason=}`` itemized by
``backoff``/``preempted``/``reshard``/``stall``/``crash`` — returned under
``result["goodput"]``.
"""

from __future__ import annotations

import importlib
import inspect
import json
import os
import signal as _signal
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from ..core.listeners import TrainingListener
from .checkpoint import _atomic_write_json

STALL_EXIT_CODE = 86  # distinct from crash codes: "alive but not progressing"
# EX_TEMPFAIL: an EXPECTED eviction (pod preemption), not a crash — the
# supervisor restarts immediately without burning crash budget
PREEMPTED_EXIT_CODE = 75
HEARTBEAT_FILE = "heartbeat.json"
PREEMPTED_MARKER = "preempted"


class HeartbeatListener(TrainingListener):
    """Per-iteration liveness record (SURVEY §5.3 failure detection)."""

    def __init__(self, directory: str) -> None:
        self.path = os.path.join(directory, HEARTBEAT_FILE)
        self._first_ts: Optional[float] = None
        os.makedirs(directory, exist_ok=True)

    def iteration_done(self, model, iteration: int, epoch: int,
                       score: float) -> None:
        now = time.time()
        if self._first_ts is None:
            self._first_ts = now
        try:
            # same tmp + fsync + os.replace discipline as the checkpoint
            # pointer: a power cut mid-beat leaves the previous beat
            # intact, never a torn file. first_ts/pid let the supervisor
            # tell THIS run's beats from a stale predecessor's and price
            # restore-to-first-step boot time in the goodput ledger.
            _atomic_write_json(self.path, {
                "iteration": iteration, "epoch": epoch,
                "score": float(score), "ts": now,
                "first_ts": self._first_ts, "pid": os.getpid()})
        except OSError:
            pass  # liveness only: a failed beat must not kill the fit —
            # if beats keep failing the watchdog takes over


def read_heartbeat(directory: str) -> Optional[dict]:
    """Latest heartbeat, or None — a missing, empty, torn, or otherwise
    unparseable ``heartbeat.json`` is reported as "no heartbeat", never
    raised into the supervisor/watchdog loop."""
    path = os.path.join(directory, HEARTBEAT_FILE)
    try:
        with open(path) as f:
            hb = json.load(f)
    except (OSError, ValueError):  # ValueError covers JSONDecodeError
        return None
    return hb if isinstance(hb, dict) else None


class Watchdog:
    """Stall detector: fires ``on_stall`` when the heartbeat stops aging
    forward for ``timeout`` seconds. Default action writes a ``stalled``
    marker and hard-exits — the only way out of a wedged device dispatch."""

    def __init__(self, directory: str, timeout: float = 300.0,
                 on_stall: Optional[Callable[[], None]] = None,
                 poll_interval: float = 5.0) -> None:
        self.directory = directory
        self.timeout = float(timeout)
        self.poll_interval = float(poll_interval)
        self.on_stall = on_stall or self._default_stall
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = None

    def _default_stall(self) -> None:
        if self._stop.is_set():  # raced with stop(): the fit finished
            return
        with open(os.path.join(self.directory, "stalled"), "w") as f:
            f.write(f"no heartbeat progress for {self.timeout}s\n")
        sys.stderr.write("Watchdog: training stalled — exiting for "
                         "supervisor restart\n")
        sys.stderr.flush()
        os._exit(STALL_EXIT_CODE)  # noqa: SLF001 — a hung dispatch blocks clean exit

    def start(self) -> "Watchdog":
        self._started_at = time.time()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Stop AND JOIN the checker thread: after stop() returns, no
        stall can fire. (Setting the event alone left a race — a check
        already past the wait could still hard-exit a process whose fit
        had just finished cleanly; _fire re-checks, and the join closes
        the window for the caller.)"""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout)
        self._thread = None

    def _fire(self) -> None:
        """Stall detected: re-check stop() immediately before acting —
        the only interleaving left is stop() arriving mid-on_stall."""
        if self._stop.is_set():
            return
        self.on_stall()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            hb = read_heartbeat(self.directory)
            # never trust a heartbeat older than our own start: a restarted
            # child inherits the previous run's stale file and must get the
            # full grace period to restore + compile before its first beat
            ts = hb.get("ts") if hb else None
            last = (max(float(ts), self._started_at)
                    if isinstance(ts, (int, float)) else self._started_at)
            if time.time() - last > self.timeout:
                self._fire()
                return


class PreemptionHandler(TrainingListener):
    """Preemption-aware stop: SIGTERM/SIGINT (the pod scheduler's
    eviction notice) becomes "finish the in-flight step, force a final
    SYNCHRONOUS checkpoint, exit with :data:`PREEMPTED_EXIT_CODE`".

    The signal handler only sets a flag — nothing JAX-unsafe happens in
    signal context. The NEXT ``iteration_done`` (i.e. after the in-flight
    step completed and the listener chain ran, so heartbeat/periodic
    checkpoints for this iteration are already down) performs the final
    save and exits. ``elastic_fit`` classifies the exit code as a
    preemption: immediate restart, no backoff, no crash-loop budget.

    Attach AFTER the CheckpointListener/HeartbeatListener and call
    :meth:`install` from the main thread::

        ckpt = CheckpointListener(dir_, ..., async_save=True, iterator=it)
        model.add_listeners(ckpt, HeartbeatListener(dir_),
                            PreemptionHandler(checkpoint=ckpt).install())
    """

    def __init__(self, checkpoint=None, *,
                 signals: tuple = (_signal.SIGTERM, _signal.SIGINT),
                 watchdog: Optional[Watchdog] = None,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 log_fn: Callable[[str], None] = None) -> None:
        self.checkpoint = checkpoint  # CheckpointListener (or None)
        self.signals = tuple(signals)
        self.watchdog = watchdog
        self.directory = getattr(checkpoint, "directory", None)
        self._exit = exit_fn or os._exit  # noqa: SLF001 — must exit through user code
        self.log_fn = log_fn
        self._requested = threading.Event()
        self.signal_received: Optional[int] = None
        self._prev_handlers: dict = {}

    def install(self) -> "PreemptionHandler":
        """Register the signal handlers (main thread only — a CPython
        restriction on ``signal.signal``)."""
        for s in self.signals:
            self._prev_handlers[s] = _signal.signal(s, self._on_signal)
        return self

    def uninstall(self) -> None:
        for s, prev in self._prev_handlers.items():
            _signal.signal(s, prev)
        self._prev_handlers.clear()

    def _on_signal(self, signum, frame) -> None:
        self.signal_received = signum
        self._requested.set()

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def iteration_done(self, model, iteration: int, epoch: int,
                       score: float) -> None:
        if not self._requested.is_set():
            return
        if self.watchdog is not None:  # a final sync save is not a stall
            self.watchdog.stop()
        ok = True
        if self.checkpoint is not None:
            ok = self.checkpoint.save_now(model, iteration, epoch, score)
        if self.directory is not None:
            try:
                with open(os.path.join(self.directory, PREEMPTED_MARKER),
                          "w") as f:
                    f.write(f"signal {self.signal_received} at iteration "
                            f"{iteration}\n")
            except OSError:
                pass
        msg = (f"PreemptionHandler: signal {self.signal_received} — final "
               f"checkpoint at iteration {iteration} "
               f"{'saved' if ok else 'FAILED'}, exiting "
               f"{PREEMPTED_EXIT_CODE}")
        (self.log_fn or (lambda m: (sys.stderr.write(m + "\n"),
                                    sys.stderr.flush())))(msg)
        self._exit(PREEMPTED_EXIT_CODE)


def _resolve(ref: str) -> Callable:
    mod, _, fn = ref.partition(":")
    return getattr(importlib.import_module(mod), fn)


def _accepts_mesh_size(fn: Callable) -> bool:
    """True when the entry function can take the resolved mesh width as a
    third argument (``fn(resume, dir, mesh_size)`` or a ``mesh_size``
    keyword) — pre-resize 2-arg entries keep working unchanged."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):  # C callables: assume the old contract
        return False
    if "mesh_size" in sig.parameters:
        return True
    positional = [p for p in sig.parameters.values()
                  if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    return len(positional) >= 3 or any(
        p.kind == p.VAR_POSITIONAL for p in sig.parameters.values())


def _mesh_child_env(env: dict, mesh_size: Optional[int]) -> dict:
    """Child environment for a boot at ``mesh_size`` devices.

    ``DL4J_ELASTIC_MESH_SIZE`` carries the width to ``_child_main`` (which
    forwards it to a resize-aware entry fn). On the CPU mesh — the env is
    empty-or-cpu ``JAX_PLATFORMS`` — the width is also enforced by
    rewriting ``--xla_force_host_platform_device_count`` in ``XLA_FLAGS``,
    so the child's fresh jax really sees ``mesh_size`` devices. On a real
    TPU fleet the device count is whatever the scheduler granted and the
    env var is advisory."""
    if mesh_size is None:
        return dict(env)
    out = dict(env)
    out["DL4J_ELASTIC_MESH_SIZE"] = str(int(mesh_size))
    if out.get("JAX_PLATFORMS", "").strip().lower() in ("", "cpu"):
        flags = [t for t in out.get("XLA_FLAGS", "").split()
                 if not t.startswith("--xla_force_host_platform_device_count")]
        flags.append(f"--xla_force_host_platform_device_count={int(mesh_size)}")
        out["XLA_FLAGS"] = " ".join(flags)
    return out


def _child_main() -> None:
    ref, checkpoint_dir = sys.argv[2], sys.argv[3]
    timeout = float(sys.argv[4])
    from .checkpoint import CheckpointListener

    resume = CheckpointListener.last_checkpoint(checkpoint_dir)
    # sub-second stall timeouts (tests, chaos harness) need a matching
    # poll cadence; production keeps the cheap 5s poll
    Watchdog(checkpoint_dir, timeout=timeout,
             poll_interval=min(5.0, max(0.05, timeout / 4.0))).start()
    fn = _resolve(ref)
    mesh_size = os.environ.get("DL4J_ELASTIC_MESH_SIZE")
    if mesh_size and _accepts_mesh_size(fn):
        fn(resume, checkpoint_dir, int(mesh_size))
    else:
        fn(resume, checkpoint_dir)


def _spawn_child(entry_ref: str, checkpoint_dir: str, stall_timeout: float,
                 env: Optional[dict], mesh_size: Optional[int] = None) -> int:
    proc = subprocess.run(
        [sys.executable, "-c",
         "from deeplearning4j_tpu.train.fault_tolerance import "
         "_child_main; _child_main()",
         "child", entry_ref, checkpoint_dir, str(stall_timeout)],
        env=_mesh_child_env({**os.environ, **(env or {})}, mesh_size),
    )
    return proc.returncode


def _call_spawn(spawn_fn: Callable, mesh_size: Optional[int]) -> int:
    """Invoke an injected ``spawn_fn``, passing the boot's mesh width to
    spawners that accept one (chaos harnesses); legacy zero-arg spawners
    keep working."""
    try:
        sig = inspect.signature(spawn_fn)
    except (TypeError, ValueError):
        return spawn_fn()
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                      p.VAR_POSITIONAL):
            return spawn_fn(mesh_size)
    return spawn_fn()


def elastic_fit(entry_ref: str, checkpoint_dir: str, *,
                max_restarts: int = 3, stall_timeout: float = 300.0,
                env: Optional[dict] = None,
                retry_policy: Optional["RetryPolicy"] = None,
                crash_loop_window: float = 600.0,
                crash_loop_budget: Optional[int] = None,
                log_fn: Callable[[str], None] = print,
                spawn_fn: Optional[Callable[[], int]] = None,
                sleep: Callable[[float], None] = time.sleep,
                clock: Callable[[], float] = time.monotonic,
                max_preemptions: Optional[int] = None,
                mesh_size_fn: Optional[Callable[[], Optional[int]]] = None,
                registry=None) -> dict:
    """Supervised training: run ``entry_ref`` ("module:function") in a child
    process; restart from the latest checkpoint on crash or stall.

    Restart discipline (core/resilience.py): restarts back off
    exponentially with seeded jitter (``retry_policy``) so a flaky fleet
    doesn't hammer checkpoint storage, and a restart-budget-per-window
    crash-loop detector (more than ``crash_loop_budget`` restarts inside
    ``crash_loop_window`` seconds) gives up early — a child that dies
    instantly on every boot must not burn all ``max_restarts`` at full
    speed. ``spawn_fn``/``sleep``/``clock`` are injectable and the
    ``elastic_fit.spawn`` FaultInjector site fires before every child
    launch, so the whole recovery path is testable without subprocesses.

    Exit-code classification: ``PREEMPTED_EXIT_CODE`` (a
    :class:`PreemptionHandler` stop — the child already forced a final
    sync checkpoint) restarts IMMEDIATELY: no backoff, and it consumes
    neither ``max_restarts`` nor the crash-loop budget — preemption is
    the pod's routine operation, not a failure of ours.
    ``max_preemptions`` optionally bounds an eviction storm (None =
    scheduler-driven, unbounded); ``STALL_EXIT_CODE`` and everything
    else keep the crash discipline unchanged.

    Elastic resize: ``mesh_size_fn`` (when given) is called once before
    EVERY child boot and returns the device count the boot should use —
    a changed width is recorded as a ``reshard`` event, the restart is
    counted under ``reason="resize"``, and the width reaches the child
    via :func:`_mesh_child_env` (``DL4J_ELASTIC_MESH_SIZE`` + the CPU
    mesh's ``--xla_force_host_platform_device_count``). Injected
    ``spawn_fn`` callables that accept an argument receive the width.

    Goodput ledger: the supervisor itemizes downtime seconds by reason —
    ``backoff`` (restart delays), ``stall`` (heartbeat age at watchdog
    fire: how long the child was wedged), ``crash`` (work seconds between
    the last beat and death), and the restore-to-first-beat boot time of
    each restart, attributed to ``reshard`` when the width changed and to
    the triggering failure kind otherwise. Exposed as
    ``dl4j_tpu_training_downtime_seconds_total{reason=}`` plus the
    ``dl4j_tpu_training_goodput_ratio`` gauge (useful seconds / wall
    seconds), and returned under ``result["goodput"]``.

    Returns {"restarts": n, "preemptions": p, "events": [...], "ok": bool,
    "goodput": {"ratio", "wall_seconds", "useful_seconds",
    "downtime_seconds": {reason: s}}}. Failure events carry
    ``heartbeat_age_s`` — wall seconds since the last beat at failure
    time, distinguishing "died mid-step" (small) from "heartbeat stale
    since boot" (large). The entry function must attach
    CheckpointListener(checkpoint_dir, ...) and
    HeartbeatListener(checkpoint_dir) itself — it owns the model and
    data.
    """
    from ..core.resilience import RetryPolicy, get_fault_injector
    from ..obs.metrics import get_registry

    reg = registry if registry is not None else get_registry()
    ev_counts = reg.counter(
        "dl4j_tpu_training_elastic_events_total",
        "elastic_fit supervisor events", ("event",))
    c_restarts = reg.counter(
        "dl4j_tpu_training_restarts_total",
        "Child restarts performed by elastic_fit",
        ("reason",))
    c_downtime = reg.counter(
        "dl4j_tpu_training_downtime_seconds_total",
        "Wall seconds the supervised run spent NOT making training "
        "progress, itemized by cause", ("reason",))
    g_goodput = reg.gauge(
        "dl4j_tpu_training_goodput_ratio",
        "Useful-step seconds / wall seconds over the supervised run")

    def record(kind: str, **fields) -> None:
        ev_counts.labels(kind).inc()
        reg.log_event("elastic_fit", event=kind, **fields)

    policy = retry_policy or RetryPolicy(
        max_retries=max_restarts, initial_backoff=1.0, max_backoff=60.0)
    budget = crash_loop_budget if crash_loop_budget is not None \
        else max(2, max_restarts)
    os.makedirs(checkpoint_dir, exist_ok=True)
    events: List[dict] = []
    restart_times: List[float] = []
    restarts = 0
    preemptions = 0
    t_start = clock()
    downtime = {"backoff": 0.0, "preempted": 0.0, "reshard": 0.0,
                "stall": 0.0, "crash": 0.0}
    prev_width: Optional[int] = None
    pending_restart: Optional[str] = None  # failure kind awaiting next boot

    def lose(reason: str, seconds: Optional[float]) -> None:
        if not seconds or seconds <= 0:
            return
        downtime[reason] = downtime.get(reason, 0.0) + float(seconds)
        c_downtime.labels(reason).inc(float(seconds))

    def finish(ok: bool) -> dict:
        wall = max(0.0, clock() - t_start)
        lost = min(wall, sum(downtime.values()))
        useful = wall - lost
        ratio = (useful / wall) if wall > 0 else 1.0
        g_goodput.set(ratio)
        reg.log_event("elastic_fit", event="goodput", ratio=ratio,
                      wall_seconds=wall, useful_seconds=useful)
        return {"ok": ok, "restarts": restarts, "preemptions": preemptions,
                "events": events,
                "goodput": {"ratio": ratio, "wall_seconds": wall,
                            "useful_seconds": useful,
                            "downtime_seconds": dict(downtime)}}

    while True:
        width = mesh_size_fn() if mesh_size_fn is not None else None
        boot_reason = pending_restart
        if pending_restart is not None:
            if (width is not None and prev_width is not None
                    and width != prev_width):
                boot_reason = "reshard"
                events.append({"event": "reshard", "from_width": prev_width,
                               "to_width": width})
                record("reshard", from_width=prev_width, to_width=width)
                log_fn(f"elastic_fit: mesh resize {prev_width} -> {width} "
                       f"devices; restoring re-sharded state")
                c_restarts.labels("resize").inc()
            else:
                c_restarts.labels(pending_restart).inc()
        if width is not None:
            prev_width = width
        get_fault_injector().fire("elastic_fit.spawn")
        spawn_wall = time.time()
        rc = (_call_spawn(spawn_fn, width) if spawn_fn is not None
              else _spawn_child(entry_ref, checkpoint_dir, stall_timeout,
                                env, width))
        if boot_reason is not None:
            # restore-to-first-beat boot time of a RESTART is downtime
            # (restore + re-shard + recompile before the first useful step)
            hb_boot = read_heartbeat(checkpoint_dir)
            first = hb_boot.get("first_ts") if hb_boot else None
            if isinstance(first, (int, float)) and first >= spawn_wall:
                lose(boot_reason, float(first) - spawn_wall)
        pending_restart = None
        if rc == 0:
            events.append({"event": "completed", "restarts": restarts})
            record("completed", restarts=restarts)
            return finish(True)
        kind = ("stall" if rc == STALL_EXIT_CODE
                else "preempted" if rc == PREEMPTED_EXIT_CODE else "crash")
        hb = read_heartbeat(checkpoint_dir)
        hb_ts = hb.get("ts") if hb else None
        hb_age = (max(0.0, time.time() - float(hb_ts))
                  if isinstance(hb_ts, (int, float)) else None)
        events.append({"event": kind, "rc": rc, "last_heartbeat": hb,
                       "heartbeat_age_s": hb_age})
        record(kind, rc=rc, heartbeat_age_s=hb_age)
        log_fn(f"elastic_fit: child {kind} (rc={rc}), last iteration "
               f"{hb.get('iteration') if hb else 'none'}"
               + (f", heartbeat age {hb_age:.1f}s" if hb_age is not None
                  else ""))
        if kind == "stall":
            # time the child sat wedged before the watchdog fired; with no
            # beat at all the whole stall_timeout was the wait
            lose("stall", hb_age if hb_age is not None else stall_timeout)
        elif kind == "crash":
            lose("crash", hb_age)  # work between the last beat and death
        if kind == "preempted":
            # expected eviction: the child checkpointed and asked to be
            # rescheduled — restart NOW, burn no crash budget of any kind
            preemptions += 1
            if max_preemptions is not None and preemptions > max_preemptions:
                events.append({"event": "gave_up", "restarts": restarts,
                               "preemptions": preemptions})
                record("gave_up", restarts=restarts)
                log_fn(f"elastic_fit: {preemptions} preemptions exceed "
                       f"max_preemptions={max_preemptions}, giving up")
                return finish(False)
            pending_restart = "preempted"
            continue
        if restarts >= max_restarts:
            events.append({"event": "gave_up", "restarts": restarts})
            record("gave_up", restarts=restarts)
            return finish(False)
        now = clock()
        restart_times = [t for t in restart_times
                         if now - t <= crash_loop_window]
        if len(restart_times) >= budget:
            events.append({"event": "crash_loop", "restarts": restarts,
                           "window_s": crash_loop_window, "budget": budget})
            record("crash_loop", restarts=restarts)
            log_fn(f"elastic_fit: crash loop — {len(restart_times) + 1} "
                   f"failures within {crash_loop_window}s, giving up")
            return finish(False)
        restart_times.append(now)
        delay = policy.backoff(restarts)
        events.append({"event": "backoff", "delay_s": delay})
        record("backoff", delay_s=delay)
        log_fn(f"elastic_fit: restarting in {delay:.2f}s "
               f"(restart {restarts + 1}/{max_restarts})")
        sleep(delay)
        lose("backoff", delay)
        pending_restart = kind
        restarts += 1
