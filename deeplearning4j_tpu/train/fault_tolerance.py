"""Failure detection + elastic restart.

Reference: SURVEY.md §5.3 — the reference's story is worker-failure
handling in SharedTrainingMaster plus checkpoint restart (thin, by its own
admission). Here the subsystem is first-class because this environment's
accelerator has a DOCUMENTED failure mode the reference never faces: the
axon PJRT device can wedge mid-session, hanging device dispatches instead
of raising (TPU_ATTEMPTS.jsonl records hours of it). A hung dispatch cannot
be recovered in-process — the PJRT client is poisoned — so recovery means
process supervision:

* ``HeartbeatListener`` — writes ``heartbeat.json`` (iteration/epoch/score/
  timestamp) every iteration from inside fit(); the liveness signal.
* ``Watchdog`` — a daemon thread that watches heartbeat age and calls
  ``on_stall`` when training stops making progress (default: write a
  ``stalled`` marker and hard-exit with STALL_EXIT_CODE so a supervisor
  can restart — a wedged device never returns control to Python).
* ``elastic_fit`` — the supervisor: runs a training entry point in a child
  process, restarts it from the latest checkpoint on crash OR stall, up to
  ``max_restarts`` times. The entry point is a ``"module:function"``
  reference with signature ``fn(resume_path: Optional[str],
  checkpoint_dir: str) -> None`` (spawn-safe: the child imports it fresh).
"""

from __future__ import annotations

import importlib
import json
import os
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional

from ..core.listeners import TrainingListener

STALL_EXIT_CODE = 86  # distinct from crash codes: "alive but not progressing"
HEARTBEAT_FILE = "heartbeat.json"


class HeartbeatListener(TrainingListener):
    """Per-iteration liveness record (SURVEY §5.3 failure detection)."""

    def __init__(self, directory: str) -> None:
        self.path = os.path.join(directory, HEARTBEAT_FILE)
        os.makedirs(directory, exist_ok=True)

    def iteration_done(self, model, iteration: int, epoch: int,
                       score: float) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"iteration": iteration, "epoch": epoch,
                       "score": float(score), "ts": time.time()}, f)
        os.replace(tmp, self.path)  # atomic: the watchdog never reads a torn file


def read_heartbeat(directory: str) -> Optional[dict]:
    path = os.path.join(directory, HEARTBEAT_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class Watchdog:
    """Stall detector: fires ``on_stall`` when the heartbeat stops aging
    forward for ``timeout`` seconds. Default action writes a ``stalled``
    marker and hard-exits — the only way out of a wedged device dispatch."""

    def __init__(self, directory: str, timeout: float = 300.0,
                 on_stall: Optional[Callable[[], None]] = None,
                 poll_interval: float = 5.0) -> None:
        self.directory = directory
        self.timeout = float(timeout)
        self.poll_interval = float(poll_interval)
        self.on_stall = on_stall or self._default_stall
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = None

    def _default_stall(self) -> None:
        with open(os.path.join(self.directory, "stalled"), "w") as f:
            f.write(f"no heartbeat progress for {self.timeout}s\n")
        sys.stderr.write("Watchdog: training stalled — exiting for "
                         "supervisor restart\n")
        sys.stderr.flush()
        os._exit(STALL_EXIT_CODE)  # noqa: SLF001 — a hung dispatch blocks clean exit

    def start(self) -> "Watchdog":
        self._started_at = time.time()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            hb = read_heartbeat(self.directory)
            # never trust a heartbeat older than our own start: a restarted
            # child inherits the previous run's stale file and must get the
            # full grace period to restore + compile before its first beat
            last = max(hb["ts"], self._started_at) if hb else self._started_at
            if time.time() - last > self.timeout:
                self.on_stall()
                return


def _resolve(ref: str) -> Callable:
    mod, _, fn = ref.partition(":")
    return getattr(importlib.import_module(mod), fn)


def _child_main() -> None:
    ref, checkpoint_dir = sys.argv[2], sys.argv[3]
    timeout = float(sys.argv[4])
    from .checkpoint import CheckpointListener

    resume = CheckpointListener.last_checkpoint(checkpoint_dir)
    Watchdog(checkpoint_dir, timeout=timeout).start()
    _resolve(ref)(resume, checkpoint_dir)


def _spawn_child(entry_ref: str, checkpoint_dir: str, stall_timeout: float,
                 env: Optional[dict]) -> int:
    proc = subprocess.run(
        [sys.executable, "-c",
         "from deeplearning4j_tpu.train.fault_tolerance import "
         "_child_main; _child_main()",
         "child", entry_ref, checkpoint_dir, str(stall_timeout)],
        env={**os.environ, **(env or {})},
    )
    return proc.returncode


def elastic_fit(entry_ref: str, checkpoint_dir: str, *,
                max_restarts: int = 3, stall_timeout: float = 300.0,
                env: Optional[dict] = None,
                retry_policy: Optional["RetryPolicy"] = None,
                crash_loop_window: float = 600.0,
                crash_loop_budget: Optional[int] = None,
                log_fn: Callable[[str], None] = print,
                spawn_fn: Optional[Callable[[], int]] = None,
                sleep: Callable[[float], None] = time.sleep,
                clock: Callable[[], float] = time.monotonic,
                registry=None) -> dict:
    """Supervised training: run ``entry_ref`` ("module:function") in a child
    process; restart from the latest checkpoint on crash or stall.

    Restart discipline (core/resilience.py): restarts back off
    exponentially with seeded jitter (``retry_policy``) so a flaky fleet
    doesn't hammer checkpoint storage, and a restart-budget-per-window
    crash-loop detector (more than ``crash_loop_budget`` restarts inside
    ``crash_loop_window`` seconds) gives up early — a child that dies
    instantly on every boot must not burn all ``max_restarts`` at full
    speed. ``spawn_fn``/``sleep``/``clock`` are injectable and the
    ``elastic_fit.spawn`` FaultInjector site fires before every child
    launch, so the whole recovery path is testable without subprocesses.

    Returns {"restarts": n, "events": [...], "ok": bool}. The entry function
    must attach CheckpointListener(checkpoint_dir, ...) and
    HeartbeatListener(checkpoint_dir) itself — it owns the model and data.
    """
    from ..core.resilience import RetryPolicy, get_fault_injector
    from ..obs.metrics import get_registry

    reg = registry if registry is not None else get_registry()
    ev_counts = reg.counter(
        "dl4j_tpu_training_elastic_events_total",
        "elastic_fit supervisor events", ("event",))
    c_restarts = reg.counter(
        "dl4j_tpu_training_restarts_total",
        "Child restarts performed by elastic_fit")

    def record(kind: str, **fields) -> None:
        ev_counts.labels(kind).inc()
        reg.log_event("elastic_fit", event=kind, **fields)

    policy = retry_policy or RetryPolicy(
        max_retries=max_restarts, initial_backoff=1.0, max_backoff=60.0)
    budget = crash_loop_budget if crash_loop_budget is not None \
        else max(2, max_restarts)
    os.makedirs(checkpoint_dir, exist_ok=True)
    events: List[dict] = []
    restart_times: List[float] = []
    restarts = 0
    while True:
        get_fault_injector().fire("elastic_fit.spawn")
        rc = (spawn_fn or (lambda: _spawn_child(
            entry_ref, checkpoint_dir, stall_timeout, env)))()
        if rc == 0:
            events.append({"event": "completed", "restarts": restarts})
            record("completed", restarts=restarts)
            return {"ok": True, "restarts": restarts, "events": events}
        kind = "stall" if rc == STALL_EXIT_CODE else "crash"
        hb = read_heartbeat(checkpoint_dir)
        events.append({"event": kind, "rc": rc, "last_heartbeat": hb})
        record(kind, rc=rc)
        log_fn(f"elastic_fit: child {kind} (rc={rc}), last iteration "
               f"{hb['iteration'] if hb else 'none'}")
        if restarts >= max_restarts:
            events.append({"event": "gave_up", "restarts": restarts})
            record("gave_up", restarts=restarts)
            return {"ok": False, "restarts": restarts, "events": events}
        now = clock()
        restart_times = [t for t in restart_times
                         if now - t <= crash_loop_window]
        if len(restart_times) >= budget:
            events.append({"event": "crash_loop", "restarts": restarts,
                           "window_s": crash_loop_window, "budget": budget})
            record("crash_loop", restarts=restarts)
            log_fn(f"elastic_fit: crash loop — {len(restart_times) + 1} "
                   f"failures within {crash_loop_window}s, giving up")
            return {"ok": False, "restarts": restarts, "events": events}
        restart_times.append(now)
        delay = policy.backoff(restarts)
        events.append({"event": "backoff", "delay_s": delay})
        record("backoff", delay_s=delay)
        log_fn(f"elastic_fit: restarting in {delay:.2f}s "
               f"(restart {restarts + 1}/{max_restarts})")
        sleep(delay)
        c_restarts.inc()
        restarts += 1
