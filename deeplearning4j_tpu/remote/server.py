"""JSON-over-HTTP model serving.

Reference: deeplearning4j-remote ``JsonModelServer`` (SURVEY.md §2.2
"Remote inference"): HTTP endpoint wrapping a model, JSON in/out, with a
matching ``JsonRemoteInference`` client. Serving goes through
:class:`~deeplearning4j_tpu.parallel.inference.ParallelInference` so
concurrent requests dynamically batch into one jitted forward (the
reference's worker-pool + BatchedInferenceObservable collapses to that).

Endpoints:
  POST <path>   {"data": [[...]]}  → {"output": [[...]]}
  GET  /health  → {"status": "ok"}
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import request as urllib_request

import numpy as np

from ..parallel.inference import InferenceMode, ParallelInference


class JsonModelServer:
    def __init__(self, model, *, port: int = 0, path: str = "/v1/serving",
                 batch_limit: int = 32, workers: int = 2) -> None:
        self.model = model
        self.path = path
        self._pi = ParallelInference(
            model, inference_mode=InferenceMode.BATCHED,
            batch_limit=batch_limit, workers=workers)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silent by default
                pass

            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "ok"})
                else:
                    self._send(404, {"error": f"unknown path {self.path}"})

            def do_POST(self):
                if self.path != outer.path:
                    self._send(404, {"error": f"unknown path {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    data = np.asarray(payload["data"], np.float32)
                    out = outer._pi.output(data)
                    self._send(200, {"output": np.asarray(out).tolist()})
                except Exception as e:
                    self._send(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "JsonModelServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="json-model-server",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._pi.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class JsonRemoteInference:
    """Client helper (reference: JsonRemoteInference)."""

    def __init__(self, endpoint: str, timeout: float = 30.0) -> None:
        self.endpoint = endpoint
        self.timeout = timeout

    def predict(self, data) -> np.ndarray:
        body = json.dumps({"data": np.asarray(data).tolist()}).encode()
        req = urllib_request.Request(
            self.endpoint, data=body,
            headers={"Content-Type": "application/json"})
        with urllib_request.urlopen(req, timeout=self.timeout) as resp:
            payload = json.loads(resp.read())
        if "error" in payload:
            raise RuntimeError(payload["error"])
        return np.asarray(payload["output"], np.float32)
