"""JSON-over-HTTP model serving.

Reference: deeplearning4j-remote ``JsonModelServer`` (SURVEY.md §2.2
"Remote inference"): HTTP endpoint wrapping a model, JSON in/out, with a
matching ``JsonRemoteInference`` client. Serving goes through
:class:`~deeplearning4j_tpu.parallel.inference.ParallelInference` so
concurrent requests dynamically batch into one jitted forward (the
reference's worker-pool + BatchedInferenceObservable collapses to that).

Status-code contract (see README.md "Serving resilience"):

  200  success
  400  malformed input (bad JSON, missing "data", non-numeric) — never retry
  404  unknown path
  503  overloaded (load shed), circuit open, or draining — retry after
       the ``Retry-After`` header (seconds)
  504  request deadline exceeded (client sets ``deadline_ms`` in the
       payload or the ``X-Deadline-Ms`` header; server default otherwise)
  500  internal error (bug — not retryable by policy)

Endpoints:
  POST <path>    {"data": [[...]], "deadline_ms": 250?} → {"output": [[...]]}
  GET  /health   → {"status": "ok" | "degraded" | "draining", ...}
                   (200 when ok, 503 otherwise — load balancers key off
                   the code, humans off the body)
  GET  /stats    → ParallelInference counters snapshot
  GET  /metrics  → Prometheus text exposition 0.0.4 of the server's
                   registry (default: the process-global one, so one
                   scrape sees serving + training + data metrics) —
                   contract enforced by tools/check_metrics_contract.py
  GET  /v1/traces → recent completed traces (?min_ms=&route=&limit=),
                   README "Tracing & step-time attribution"; contract
                   enforced by tools/check_trace_contract.py

Tracing (obs/tracing.py): every POST gets an ``X-Request-Id`` (echoed
when the client sent one, generated otherwise — also the canary routing
key); the W3C ``traceparent`` request header is honored and a
``server.request`` span (with engine child spans) is recorded for
sampled traces. ``JsonRemoteInference`` injects ``traceparent`` per
attempt under a ``client.request`` root span. Tracing off = byte
identical behavior.

Multi-model serving (serving/ — README "Model registry & hot-swap
serving"): registered :class:`~deeplearning4j_tpu.serving.manager.
ModelManager` endpoints add

  GET  /v1/models          → {"models": {name: manager.describe()}}
  POST /v1/models/<name>   → same payload/status contract as <path>;
                             response carries ``X-Model-Version``.
                             ``X-Model-Version`` request header pins a
                             resident version (live or canary; 404 when
                             that version is not currently serving) and
                             ``X-Request-Id`` is the canary routing key.

Replica-pool serving (README "Replica pools & caching"): a
:class:`~deeplearning4j_tpu.parallel.pool.EnginePool` passed as
``pool=`` serves the main POST path through power-of-two-choices
dispatch over N replicas. Request headers: ``X-Priority`` names an
admission priority class (low classes shed first under overload — also
honored on the single-engine, managed-model and generate routes);
``X-Cache-Bypass`` (or ``Cache-Control: no-cache``) skips the pool's
content-hash response cache. Responses carry ``X-Cache:
hit|miss|bypass`` when the cache is configured.

Generation serving (README "Generation serving"): a
:class:`~deeplearning4j_tpu.parallel.decode.DecodeEngine` passed as
``generator=`` — or an :class:`~deeplearning4j_tpu.parallel.pool.
EnginePool` with decode replicas passed as ``pool=`` (requests then go
through ``EnginePool.submit_generate``: power-of-two-choices over the
decode replicas, circuit-skip + least-loaded fallback; an explicit
``generator=`` wins when both are present) — adds

  POST /v1/generate → {"prompt": [ids...], "max_tokens"?, "greedy"?,
                       "temperature"?, "top_k"?, "top_p"?, "seed"?,
                       "eos_id"?, "speculative_k"? (cap this request's
                       draft window; 0 = plain decode), "deadline_ms"?,
                       "stream"? (default true)}
                      streamed as newline-delimited JSON token events
                      ({"token", "index"}... {"done", "reason",
                      "count"}) over one response; same 400/503 shed +
                      Retry-After contract BEFORE the stream starts, and
                      a deadline expiring MID-stream terminates cleanly
                      with the partial output (reason "deadline").
                      ``stream: false`` returns one JSON body instead.
                      Client disconnect cancels the request and frees
                      its cache slot. Contract enforced by
                      tools/check_generate_contract.py.
"""

from __future__ import annotations

import http.client as _http_client
import itertools
import json
import os
import threading
import time
import uuid
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import request as urllib_request
from urllib.error import HTTPError, URLError
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..core.resilience import (
    AdmissionRejectedError,
    CircuitOpenError,
    CircuitState,
    Deadline,
    DeadlineExceededError,
    ResilienceError,
    RetryPolicy,
)
from ..obs.metrics import MetricsRegistry, get_registry
from ..obs.prom import CONTENT_TYPE as _PROM_CONTENT_TYPE
from ..obs.prom import render_prometheus
from ..obs.tracing import (
    Tracer,
    current_context,
    decode_traceparent,
    encode_traceparent,
    get_tracer,
    trace_now,
)
from ..parallel.inference import InferenceMode, ParallelInference
from ..serving.store import VersionNotFoundError

_server_seq = itertools.count()
_client_seq = itertools.count()


class ServiceUnavailableError(ResilienceError):
    """Client-side image of a 503: retryable, with the server's
    Retry-After hint attached (RetryPolicy honors ``retry_after``)."""

    def __init__(self, msg: str, retry_after: Optional[float] = None):
        super().__init__(msg)
        self.retry_after = retry_after


class PartialStreamError(ResilienceError):
    """A generation stream died mid-way (connection drop, truncated
    NDJSON, or EOF before the terminal ``done`` event). Carries the
    tokens received before the drop so the caller keeps the partial
    output. The client NEVER silently retries a stream that already
    emitted tokens — a transparent retry would re-emit them."""

    def __init__(self, msg: str, tokens=None):
        super().__init__(msg)
        self.tokens = list(tokens or [])


_MODELS_PREFIX = "/v1/models"
_ADMIN_ACTIONS = ("deploy", "rollback")


class JsonModelServer:
    def __init__(self, model=None, *, port: int = 0, path: str = "/v1/serving",
                 batch_limit: int = 32, workers: int = 2,
                 queue_limit: int = 256,
                 default_deadline: float = 30.0,
                 circuit_breaker=None, admission=None,
                 clock=time.monotonic, fault_injector=None,
                 registry: Optional[MetricsRegistry] = None,
                 name: Optional[str] = None,
                 managers: Optional[dict] = None,
                 tracer: Optional[Tracer] = None,
                 generator=None,
                 generate_path: str = "/v1/generate",
                 pool=None,
                 prefill=None,
                 multiplexer=None) -> None:
        if model is not None and pool is not None:
            raise ValueError("pass model= (server-owned engine) or pool= "
                             "(caller-owned EnginePool), not both")
        self.model = model
        self.path = path
        # EnginePool behind the main POST path (caller-owned lifecycle,
        # like managers=/generator= — the server routes to it, threads
        # the X-Priority / X-Cache-Bypass headers through, and drains it
        # on stop; shutdown stays with the caller)
        self._pool = pool
        # DecodeEngine for POST /v1/generate (caller-owned lifecycle,
        # like managers= — the server routes to it and drains it on stop)
        self._generator = generator
        self.generate_path = generate_path
        # PrefillEngine for POST /v1/disagg/prefill — makes this host a
        # prefill-tier replica in a disaggregated pipeline (caller-owned
        # lifecycle). A host with a generator= whose engine supports
        # submit_prefilled() additionally serves /v1/disagg/resume.
        self._prefill = prefill
        self.default_deadline = float(default_deadline)
        self._clock = clock
        self._draining = False
        self.name = name or f"server-{next(_server_seq)}"
        self._t0_mono = time.monotonic()  # replica identity: uptime
        self.registry = registry if registry is not None else get_registry()
        self._tracer = tracer  # None -> process-global at request time
        # named ModelManager endpoints (serving/): name -> manager. The
        # server routes to them; their lifecycle (deploy/rollback/
        # shutdown) stays with the caller that owns them.
        self._managers: dict = dict(managers or {})
        # ModelMultiplexer (serving/multiplex.py): models it registers are
        # served under the same POST /v1/models/<name> route — an explicit
        # managers= entry wins on name collision. The multiplexer pages
        # weights in/out under its byte budget; the server threads the
        # X-Tenant header through so its per-tenant SLO policy applies.
        # Caller-owned lifecycle, drained on stop like managers=.
        self._mux = multiplexer
        self._pi = None if model is None else ParallelInference(
            model, inference_mode=InferenceMode.BATCHED,
            batch_limit=batch_limit, workers=workers,
            queue_limit=queue_limit, circuit_breaker=circuit_breaker,
            admission=admission, clock=clock, fault_injector=fault_injector,
            registry=self.registry, name=self.name, tracer=tracer)
        # per-status-code request counters + end-to-end request latency,
        # recorded once per POST in the handler's finally
        self._req_counts = self.registry.counter(
            "dl4j_tpu_serving_requests_total",
            "Serving HTTP requests by status code", ("instance", "code"))
        self._req_counts.labels(self.name, "200")  # exists from first scrape
        self._req_latency = self.registry.histogram(
            "dl4j_tpu_serving_request_latency_seconds",
            "Serving request latency (parse through response)",
            ("instance",)).labels(self.name)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silent by default
                pass

            def _send(self, code: int, payload: dict,
                      headers: Optional[dict] = None) -> None:
                self._sent_code = code
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                # every POST response names the request it answers —
                # client-provided id echoed, server-generated otherwise
                rid = getattr(self, "_request_id", None)
                if rid is not None:
                    self.send_header("X-Request-Id", rid)
                # load score piggybacks on every POST response so a
                # RemoteReplica in a front pool learns this host's load
                # for free (staleness-bounded /stats poll is the fallback)
                if self.command == "POST":
                    try:
                        self.send_header("X-Load-Score",
                                         f"{outer.load_score():.3f}")
                    except Exception:
                        pass
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_unavailable(self, reason: str, retry_after: float) -> None:
                self._send(503, {"error": reason, "retryable": True},
                           {"Retry-After": f"{max(retry_after, 0.001):.3f}"})

            def do_GET(self):
                if self.path == "/health":
                    status, code = outer.health()
                    self._send(code, status)
                elif self.path == "/stats":
                    self._send(200, outer.stats())
                elif self.path.split("?", 1)[0] == "/v1/traces":
                    self._send(200, outer.traces_payload(
                        urlparse(self.path).query))
                elif self.path == _MODELS_PREFIX:
                    payload = {"models": {
                        n: m.describe() for n, m in
                        sorted(outer._managers.items())}}
                    if outer._mux is not None:
                        # residency per model (warm|parked|paging) plus
                        # the budget gauges — the operator view of
                        # eviction churn
                        payload["multiplex"] = outer._mux.describe()
                    self._send(200, payload)
                elif self.path == "/metrics":
                    body = render_prometheus(outer.registry).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", _PROM_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(404, {"error": f"unknown path {self.path}"})

            def _deadline(self, payload: dict) -> Deadline:
                ms = payload.get("deadline_ms")
                if ms is None:
                    ms = self.headers.get("X-Deadline-Ms")
                seconds = (float(ms) / 1000.0 if ms is not None
                           else outer.default_deadline)
                return Deadline.after(seconds, clock=outer._clock)

            def do_POST(self):
                t0 = time.perf_counter()
                self._sent_code = None
                # X-Request-Id: client-provided or server-generated, echoed
                # on the response either way so canary routing / trace
                # lookup never silently key off a payload hash
                self._request_id = (self.headers.get("X-Request-Id")
                                    or uuid.uuid4().hex)
                tracer = outer.tracer
                ctx = decode_traceparent(self.headers.get("traceparent")) \
                    if tracer.enabled else None
                span = tracer.span(
                    "server.request", parent=ctx,
                    attrs={"route": self.path,
                           "request_id": self._request_id,
                           "server": outer.name})
                try:
                    with span:
                        self._handle_post()
                        if self._sent_code is not None:
                            span.set_attribute("status", self._sent_code)
                            if self._sent_code >= 500:
                                span.error = True
                finally:
                    if self._sent_code is not None:
                        outer._observe_request(
                            self._sent_code, time.perf_counter() - t0)

            def _priority(self):
                """``X-Priority`` header → admission priority class (None
                when absent; unknown names resolve to the strictest class
                inside the controller — headers are client-controlled)."""
                p = self.headers.get("X-Priority")
                return p.strip() if p else None

            def _submit_fn(self):
                """Resolve the POST path to a ``(data, deadline) ->
                (future, version|None)`` submitter, or answer 404."""
                prio = self._priority()
                if self.path == outer.path and outer._pool is not None:
                    # cache controls: X-Cache-Bypass (any value) or
                    # Cache-Control: no-cache skip lookup AND fill
                    cc = (self.headers.get("Cache-Control") or "").lower()
                    bypass = (self.headers.get("X-Cache-Bypass") is not None
                              or "no-cache" in cc)
                    return lambda data, deadline: (
                        outer._pool.output_async(
                            data, deadline=deadline, priority=prio,
                            use_cache=not bypass), None)
                if self.path == outer.path and outer._pi is not None:
                    return lambda data, deadline: (
                        outer._pi.output_async(data, deadline=deadline,
                                               priority=prio), None)
                if self.path.startswith(_MODELS_PREFIX + "/"):
                    mname = self.path[len(_MODELS_PREFIX) + 1:]
                    mgr = outer._managers.get(mname)
                    if mgr is None and outer._mux is not None \
                            and mname in outer._mux:
                        # multiplexed model: the pager resolves residency
                        # (cold miss queues behind the page-in, bounded by
                        # the tenant's deadline) before the manager submit
                        tenant = self.headers.get("X-Tenant")
                        pin = self.headers.get("X-Model-Version")
                        key = self._request_id
                        return lambda data, deadline: outer._mux.submit(
                            mname, data,
                            tenant=tenant.strip() if tenant else None,
                            priority=prio, deadline=deadline, version=pin,
                            key=key)
                    if mgr is None:
                        self._send(404, {"error": f"unknown model {mname!r}"})
                        return None
                    pin = self.headers.get("X-Model-Version")
                    # canary routing keys off the request id — generated
                    # server-side when the client sent none, so the split
                    # is always attributable to an id the client saw
                    key = self._request_id
                    return lambda data, deadline: mgr.submit(
                        data, key=key, version=pin, deadline=deadline,
                        priority=prio)
                self._send(404, {"error": f"unknown path {self.path}"})
                return None

            def _handle_admin(self):
                """``POST /v1/models/<name>/deploy`` (body
                ``{"version": N|"vN"|"latest", "optimize"?:
                "inference"|"inference:int8"|"inference:fp8"|null}``) and
                ``POST /v1/models/<name>/rollback`` against a registered
                ModelManager — the remote end of the pool's deploy
                fan-out (a front pool with RemoteReplicas rolls each
                host through this route). ``optimize`` overrides the
                host manager's rewrite pipeline for this deploy, so a
                quantized rollout fans out across fabric hosts like any
                version (each host loads the shared full-precision
                artifact and quantizes in memory)."""
                rest = self.path[len(_MODELS_PREFIX) + 1:]
                mname, _, action = rest.rpartition("/")
                mgr = outer._managers.get(mname)
                if mgr is None:
                    self._send(404, {"error": f"unknown model {mname!r}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = (json.loads(self.rfile.read(length))
                               if length else {})
                except Exception as e:
                    self._send(400, {"error": f"malformed request: {e}"})
                    return
                try:
                    if action == "deploy":
                        kw = {}
                        if "optimize" in payload:
                            opt = payload["optimize"]
                            if opt is not None and not isinstance(opt, str):
                                self._send(400, {
                                    "error": "optimize must be a pipeline "
                                             "name string or null"})
                                return
                            kw["optimize"] = opt
                        previous = mgr.live_version
                        entry = mgr.deploy(payload.get("version", "latest"),
                                           **kw)
                        self._send(200, {"deployed": str(entry.version),
                                         "previous": previous})
                    else:
                        mgr.rollback()
                        self._send(200, {"live": mgr.live_version})
                except VersionNotFoundError as e:
                    self._send(404, {"error": str(e)})
                except ValueError as e:  # unknown pipeline name: caller bug
                    self._send(400, {"error": str(e)})
                except Exception as e:
                    self._send(500, {"error": f"{action} failed: {e}"})

            def _handle_generate(self):
                # ---- parse: any failure here is the CLIENT's fault -> 400
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    prompt = [int(t) for t in payload["prompt"]]
                    deadline = self._deadline(payload)
                    stream = bool(payload.get("stream", True))
                    spec_k = payload.get("speculative_k")
                    kw = dict(
                        max_tokens=payload.get("max_tokens"),
                        greedy=bool(payload.get("greedy", True)),
                        temperature=float(payload.get("temperature", 1.0)),
                        top_k=int(payload.get("top_k", 0)),
                        top_p=float(payload.get("top_p", 1.0)),
                        seed=int(payload.get("seed", 0)),
                        eos_id=payload.get("eos_id"),
                        speculative_k=(None if spec_k is None
                                       else int(spec_k)),
                    )
                except Exception as e:
                    self._send(400, {"error": f"malformed request: {e}"})
                    return
                # ---- admit: shed/draining answer BEFORE any stream bytes
                try:
                    if outer._draining:
                        raise RuntimeError("draining")
                    if outer._generator is not None:
                        handle = outer._generator.submit(
                            prompt, deadline=deadline,
                            request_id=self._request_id,
                            priority=self._priority(), **kw)
                    else:  # pooled generation: p2c over decode replicas
                        handle = outer._pool.submit_generate(
                            prompt, deadline=deadline,
                            request_id=self._request_id,
                            priority=self._priority(), **kw)
                except ValueError as e:
                    self._send(400, {"error": str(e)})
                    return
                except AdmissionRejectedError as e:
                    self._send_unavailable(f"overloaded: {e}", e.retry_after)
                    return
                except CircuitOpenError as e:
                    self._send_unavailable(f"circuit open: {e}",
                                           e.retry_after)
                    return
                except RuntimeError as e:
                    if "drain" in str(e) or "shut down" in str(e):
                        self._send_unavailable("draining", 1.0)
                    else:
                        self._send(500, {"error": f"internal error: {e}"})
                    return
                except Exception as e:
                    self._send(500, {"error": f"internal error: {e}"})
                    return
                if not stream:
                    tokens = handle.result(
                        timeout=(deadline.remaining() or 30.0) + 30.0)
                    self._send(200, {"tokens": tokens,
                                     "count": len(tokens),
                                     "reason": handle.reason})
                    return
                # ---- stream: newline-delimited JSON events until done.
                # A write failure means the client went away — cancel so
                # the engine frees the cache slot instead of generating
                # for nobody.
                self._sent_code = 200
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("X-Request-Id", self._request_id)
                try:
                    self.send_header("X-Load-Score",
                                     f"{outer.load_score():.3f}")
                except Exception:
                    pass
                self.end_headers()
                try:
                    for ev in handle.events(
                            timeout=(deadline.remaining() or 30.0) + 30.0):
                        self.wfile.write(json.dumps(ev).encode() + b"\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionError, OSError):
                    handle.cancel()
                except Exception:
                    handle.cancel()
                    raise

            def _handle_disagg_prefill(self):
                """Prefill-tier hop: run the bucketed prefill + first-token
                sample and answer with the serialized handoff bytes."""
                from ..serving.disagg import serialize_handoff

                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    prompt = [int(t) for t in payload["prompt"]]
                    deadline = self._deadline(payload)
                    spec_k = payload.get("speculative_k")
                    kw = dict(
                        max_tokens=payload.get("max_tokens"),
                        greedy=bool(payload.get("greedy", True)),
                        temperature=float(payload.get("temperature", 1.0)),
                        top_k=int(payload.get("top_k", 0)),
                        top_p=float(payload.get("top_p", 1.0)),
                        seed=int(payload.get("seed", 0)),
                        eos_id=payload.get("eos_id"),
                        speculative_k=(None if spec_k is None
                                       else int(spec_k)),
                    )
                except Exception as e:
                    self._send(400, {"error": f"malformed request: {e}"})
                    return
                try:
                    if outer._draining:
                        raise RuntimeError("draining")
                    if deadline.expired():
                        raise DeadlineExceededError("deadline exceeded")
                    handoff = outer._prefill.prefill(prompt, **kw)
                    body = serialize_handoff(handoff)
                except ValueError as e:
                    self._send(400, {"error": str(e)})
                    return
                except AdmissionRejectedError as e:
                    self._send_unavailable(f"overloaded: {e}", e.retry_after)
                    return
                except CircuitOpenError as e:
                    self._send_unavailable(f"circuit open: {e}",
                                           e.retry_after)
                    return
                except DeadlineExceededError:
                    self._send(504, {"error": "deadline exceeded"})
                    return
                except RuntimeError as e:
                    if "drain" in str(e) or "shut down" in str(e):
                        self._send_unavailable("draining", 1.0)
                    else:
                        self._send(500, {"error": f"internal error: {e}"})
                    return
                except Exception as e:
                    self._send(500, {"error": f"internal error: {e}"})
                    return
                self._sent_code = 200
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("X-Request-Id", self._request_id)
                try:
                    self.send_header("X-Load-Score",
                                     f"{outer.load_score():.3f}")
                except Exception:
                    pass
                self.end_headers()
                self.wfile.write(body)

            def _handle_disagg_resume(self):
                """Decode-tier hop: deserialize a shipped prefill handoff,
                admit it into the local engine and stream tokens back
                (same NDJSON contract as /v1/generate)."""
                from ..serving.disagg import deserialize_handoff

                try:
                    length = int(self.headers.get("Content-Length", 0))
                    handoff = deserialize_handoff(self.rfile.read(length))
                    deadline = self._deadline({})
                except Exception as e:
                    self._send(400, {"error": f"malformed handoff: {e}"})
                    return
                try:
                    if outer._draining:
                        raise RuntimeError("draining")
                    handle = outer._generator.submit_prefilled(
                        handoff, deadline=deadline,
                        request_id=self._request_id,
                        priority=self._priority())
                except ValueError as e:
                    self._send(400, {"error": str(e)})
                    return
                except AdmissionRejectedError as e:
                    self._send_unavailable(f"overloaded: {e}", e.retry_after)
                    return
                except CircuitOpenError as e:
                    self._send_unavailable(f"circuit open: {e}",
                                           e.retry_after)
                    return
                except RuntimeError as e:
                    if "drain" in str(e) or "shut down" in str(e):
                        self._send_unavailable("draining", 1.0)
                    else:
                        self._send(500, {"error": f"internal error: {e}"})
                    return
                except Exception as e:
                    self._send(500, {"error": f"internal error: {e}"})
                    return
                self._sent_code = 200
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("X-Request-Id", self._request_id)
                try:
                    self.send_header("X-Load-Score",
                                     f"{outer.load_score():.3f}")
                except Exception:
                    pass
                self.end_headers()
                try:
                    for ev in handle.events(
                            timeout=(deadline.remaining() or 30.0) + 30.0):
                        self.wfile.write(json.dumps(ev).encode() + b"\n")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionError, OSError):
                    handle.cancel()
                except Exception:
                    handle.cancel()
                    raise

            def _handle_post(self):
                if (self.path.startswith(_MODELS_PREFIX + "/")
                        and self.path.rsplit("/", 1)[-1] in _ADMIN_ACTIONS):
                    self._handle_admin()
                    return
                if (self.path == "/v1/disagg/prefill"
                        and outer._prefill is not None):
                    self._handle_disagg_prefill()
                    return
                if (self.path == "/v1/disagg/resume"
                        and outer._generator is not None
                        and hasattr(outer._generator, "submit_prefilled")):
                    self._handle_disagg_resume()
                    return
                if self.path == outer.generate_path and (
                        outer._generator is not None
                        or (outer._pool is not None
                            and outer._pool.decode_replicas)):
                    self._handle_generate()
                    return
                submit = self._submit_fn()
                if submit is None:
                    return
                # ---- parse: any failure here is the CLIENT's fault -> 400
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    data = np.asarray(payload["data"], np.float32)
                    deadline = self._deadline(payload)
                except Exception as e:
                    self._send(400, {"error": f"malformed request: {e}"})
                    return
                # ---- serve: failures here are the SERVER's state -> 5xx
                # (except a pinned version that is not serving -> 404)
                try:
                    if outer._draining:
                        raise RuntimeError("draining")
                    fut, version = submit(data, deadline)
                    out = fut.result(timeout=deadline.remaining())
                    headers = {}
                    if version is not None:
                        headers["X-Model-Version"] = str(version)
                    cache_state = getattr(fut, "_dl4j_cache", None)
                    if cache_state is not None:
                        headers["X-Cache"] = cache_state
                    self._send(200, {"output": np.asarray(out).tolist()},
                               headers or None)
                except VersionNotFoundError as e:
                    self._send(404, {"error": str(e)})
                except AdmissionRejectedError as e:
                    self._send_unavailable(f"overloaded: {e}", e.retry_after)
                except CircuitOpenError as e:
                    self._send_unavailable(f"circuit open: {e}", e.retry_after)
                except (DeadlineExceededError, FutureTimeoutError):
                    self._send(504, {"error": "deadline exceeded"})
                except RuntimeError as e:
                    if "drain" in str(e) or "shut down" in str(e):
                        self._send_unavailable("draining", 1.0)
                    else:
                        self._send(500, {"error": f"internal error: {e}"})
                except Exception as e:
                    self._send(500, {"error": f"internal error: {e}"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def _observe_request(self, code: int, seconds: float) -> None:
        self._req_counts.labels(self.name, str(code)).inc()
        self._req_latency.observe(seconds)

    @property
    def tracer(self) -> Tracer:
        return self._tracer if self._tracer is not None else get_tracer()

    def identity(self) -> dict:
        """Stable replica identity, surfaced on ``/health`` and
        ``/stats``: lets a pool fan-out failure be attributed to a HOST
        (which process, how long it has been up), not just an endpoint."""
        return {"name": self.name,
                "uptime_seconds": round(
                    time.monotonic() - self._t0_mono, 3),
                "pid": os.getpid()}

    def load_score(self) -> float:
        """Aggregate load score across every engine this server routes
        to — piggybacked on POST responses as ``X-Load-Score`` so a
        front pool's ``RemoteReplica`` tracks this host's load without
        extra polling."""
        # the same engine/pool can be both the direct serving target and
        # a registered manager's engine — dedupe by identity so it is
        # counted once (double-counting inflates X-Load-Score and skews
        # the front pool's dispatch away from this host)
        targets = [self._pi, self._pool, self._generator, self._prefill,
                   self._mux]
        targets.extend(m.engine for m in self._managers.values()
                       if m.engine is not None)
        score, seen = 0.0, set()
        for e in targets:
            if e is None or id(e) in seen:
                continue
            seen.add(id(e))
            if hasattr(e, "load_score"):
                score += float(e.load_score())
        return score

    def traces_payload(self, query: str = "") -> dict:
        """``GET /v1/traces`` body: recent completed traces, filterable by
        ``min_ms`` (minimum total duration), ``route`` (span route
        attribute, e.g. ``/v1/serving``) and ``limit``."""
        q = parse_qs(query or "")

        def first(key, cast, default=None):
            vals = q.get(key)
            if not vals:
                return default
            try:
                return cast(vals[0])
            except (TypeError, ValueError):
                return default

        store = self.tracer.store
        return {
            "enabled": self.tracer.enabled,
            "trace_count": len(store),
            "traces": store.traces(
                min_duration_ms=first("min_ms", float),
                route=first("route", str),
                limit=first("limit", int, 50)),
        }

    def add_model(self, name: str, manager) -> "JsonModelServer":
        """Register a :class:`~deeplearning4j_tpu.serving.manager.
        ModelManager` under ``POST /v1/models/<name>``. Copy-on-write: a
        handler thread mid-request keeps the mapping it resolved against
        — registration with in-flight traffic never trips a concurrent
        iteration (health/stats snapshot the same way)."""
        managers = dict(self._managers)
        managers[name] = manager
        self._managers = managers
        return self

    def remove_model(self, name: str) -> None:
        """Unregister ``name`` (copy-on-write, see :meth:`add_model`).
        In-flight requests that already resolved the manager complete
        against it; the caller still owns the manager's lifecycle and
        drains/shuts it down after removal."""
        managers = dict(self._managers)
        managers.pop(name, None)
        self._managers = managers

    def health(self) -> tuple:
        """({"status": ...}, http_code). Truthful: draining while stopping,
        degraded while any live breaker is not closed, ok otherwise.
        EVERY engine the server routes to counts: the main engine,
        managed models, the decode generator (a tripped generate circuit
        must not report ok/200) and a replica pool (whose aggregate state
        is CLOSED while any replica is healthy — one sick replica out of
        N degrades that replica's traffic, not the whole node's health;
        per-replica circuits are itemized in the payload)."""
        # a parked manager has no engine (weights paged out) — it is not
        # unhealthy, just cold; residency is itemized per model below
        engines = ([] if self._pi is None else [self._pi]) + \
            [m.engine for m in self._managers.values()
             if m.engine is not None]
        circuits = [e.circuit_state for e in engines]
        queue_depth = sum(e.stats()["queue_depth"] for e in engines)
        payload = {}
        if self._pool is not None:
            circuits.append(self._pool.circuit_state)
            queue_depth += self._pool._admission.pending
            pool_reps = self._pool.replicas + self._pool.decode_replicas
            # per-replica serving roles + per-role circuit aggregate
            # (closed while ANY replica of that role can take traffic) —
            # a disaggregated front host reads this to see which TIER is
            # down, not just which endpoint
            roles = {e.name: getattr(e, "role", "unified")
                     for e in pool_reps}
            by_role: dict = {}
            for e in pool_reps:
                by_role.setdefault(roles[e.name], []).append(
                    e.circuit_state)
            rank = {CircuitState.CLOSED: 0, CircuitState.HALF_OPEN: 1,
                    CircuitState.OPEN: 2}
            payload["pool"] = {
                "replicas": {e.name: e.circuit_state.value
                             for e in pool_reps},
                "roles": roles,
                "role_circuits": {
                    r: min(states, key=rank.__getitem__).value
                    for r, states in by_role.items()},
                "circuit": self._pool.circuit_state.value,
            }
        if self._generator is not None:
            gen_circuit = self._generator.circuit_state
            circuits.append(gen_circuit)
            queue_depth += self._generator.stats()["queue_depth"]
            payload["generate"] = {
                "circuit": gen_circuit.value,
                "role": getattr(self._generator, "role", "decode"
                                if self._prefill is None else "unified"),
            }
            gen_roles = self._generator.stats().get("roles")
            if gen_roles:  # a DisaggCoordinator itemizes its targets
                payload["generate"]["roles"] = gen_roles
        if self._prefill is not None:
            pre_circuit = self._prefill.circuit_state
            circuits.append(pre_circuit)
            queue_depth += self._prefill.stats()["queue_depth"]
            payload["prefill"] = {"circuit": pre_circuit.value,
                                  "role": "prefill"}
        if self._draining:
            status = "draining"
        elif any(c is not CircuitState.CLOSED for c in circuits):
            status = "degraded"
        else:
            status = "ok"
        payload["status"] = status
        payload["queue_depth"] = queue_depth
        payload["replica"] = self.identity()
        if self._pi is not None:
            payload["circuit"] = self._pi.circuit_state.value
        if self._managers:
            payload["models"] = {
                n: {"circuit": (m.engine.circuit_state.value
                                if m.engine is not None else "parked"),
                    "residency": getattr(m, "residency", "warm"),
                    "live_version": m.live_version}
                for n, m in sorted(self._managers.items())}
        if self._mux is not None:
            d = self._mux.describe()
            payload["multiplex"] = {
                "budget_bytes": d["budget_bytes"],
                "resident_bytes": d["resident_bytes"],
                "resident_models": d["resident_models"],
                "registered_models": d["registered_models"],
                "models": {n: info["residency"]
                           for n, info in d["models"].items()},
            }
        return payload, (200 if status == "ok" else 503)

    def stats(self) -> dict:
        s = {} if self._pi is None else self._pi.stats()
        if self._pool is not None:
            s["pool"] = self._pool.stats()
        if self._managers:
            s["models"] = {n: m.stats()
                           for n, m in sorted(self._managers.items())}
        if self._mux is not None:
            s["multiplex"] = self._mux.stats()
        if self._generator is not None:
            s["generate"] = self._generator.stats()
        if self._prefill is not None:
            s["prefill"] = self._prefill.stats()
        s["draining"] = self._draining
        s["replica"] = self.identity()
        return s

    def start(self) -> "JsonModelServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="json-model-server",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, *, drain: bool = True,
             drain_timeout: Optional[float] = 30.0) -> None:
        """Graceful by default: flip to draining (new POSTs get 503 +
        Retry-After), let in-flight requests finish, then tear down."""
        self._draining = True
        if drain:
            if self._pi is not None:
                self._pi.drain(timeout=drain_timeout)
            if self._pool is not None:
                self._pool.drain(timeout=drain_timeout)
            for m in self._managers.values():
                if m.engine is not None:  # parked managers have no engine
                    m.engine.drain(timeout=drain_timeout)
            if self._mux is not None:
                self._mux.drain(timeout=drain_timeout)
            if self._generator is not None:
                self._generator.drain(timeout=drain_timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._pi is not None:
            self._pi.shutdown(drain=False)
        # registered managers are caller-owned: their engines drain above
        # but shutdown stays with whoever created them
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class JsonRemoteInference:
    """Client helper (reference: JsonRemoteInference) with deadline-aware
    retries: 503s and connection errors back off (exponential + seeded
    jitter, honoring Retry-After) under the request's total deadline;
    400s never retry — resending malformed input cannot help."""

    def __init__(self, endpoint: str, timeout: float = 30.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 sleep=time.sleep, clock=time.monotonic,
                 registry: Optional[MetricsRegistry] = None,
                 name: Optional[str] = None,
                 tracer: Optional[Tracer] = None) -> None:
        self.endpoint = endpoint
        self.timeout = timeout
        self.retry_policy = retry_policy or RetryPolicy(
            max_retries=3, initial_backoff=0.05, max_backoff=2.0)
        self._sleep = sleep
        self._clock = clock
        self._tracer = tracer  # None -> process-global at call time
        self.retries = 0  # attempts beyond the first, across calls
        self.name = name or f"client-{next(_client_seq)}"
        reg = registry if registry is not None else get_registry()
        self._c_retries = reg.counter(
            "dl4j_tpu_client_retries_total",
            "JsonRemoteInference retry attempts (beyond the first try)",
            ("instance",)).labels(self.name)

    @property
    def tracer(self) -> Tracer:
        return self._tracer if self._tracer is not None else get_tracer()

    def _call_once(self, body: bytes, deadline: Deadline) -> dict:
        rem = deadline.remaining()
        if rem is not None and rem <= 0:
            raise DeadlineExceededError("client deadline exceeded")
        headers = {"Content-Type": "application/json"}
        if rem is not None:
            headers["X-Deadline-Ms"] = str(int(rem * 1000))
        # one HTTP attempt = one span: a retry keeps the request's trace
        # id (the enclosing client.request span) but gets a fresh span id,
        # so the trace shows every attempt the server saw. The attempt
        # span is synthesized with the exact identity sent on the wire
        # (no contextvar churn on the request hot path).
        tracer = self.tracer
        parent = current_context() if tracer.enabled else None
        attempt = None
        t0 = 0.0
        if parent is not None:  # propagate identity even when unsampled
            attempt = parent.child()
            headers["traceparent"] = encode_traceparent(attempt)
            t0 = trace_now()
        status = None
        ok = False
        try:
            req = urllib_request.Request(self.endpoint, data=body,
                                         headers=headers)
            try:
                with urllib_request.urlopen(req, timeout=rem) as resp:
                    status = resp.status
                    payload = json.loads(resp.read())
                    ok = True
                    return payload
            except HTTPError as e:
                status = e.code
                detail = ""
                try:
                    detail = json.loads(e.read()).get("error", "")
                except Exception:
                    pass
                if e.code == 503:
                    ra = e.headers.get("Retry-After")
                    raise ServiceUnavailableError(
                        detail or "service unavailable",
                        retry_after=float(ra) if ra else None) from e
                if e.code == 504:
                    raise DeadlineExceededError(
                        detail or "deadline exceeded") from e
                if e.code == 400:
                    raise ValueError(detail or "bad request") from e
                raise RuntimeError(f"HTTP {e.code}: {detail}") from e
        finally:
            if attempt is not None:
                rec = tracer.make_record(
                    "client.http", parent, t0, trace_now(),
                    attrs={"endpoint": self.endpoint, "status": status},
                    error=not ok, span_id=attempt.span_id)
                if rec is not None:
                    tracer._export(rec)

    def predict(self, data, *, timeout: Optional[float] = None) -> np.ndarray:
        body = json.dumps({"data": np.asarray(data).tolist()}).encode()
        deadline = Deadline.after(
            timeout if timeout is not None else self.timeout,
            clock=self._clock)

        def note_retry(attempt, exc, delay):
            self.retries += 1
            self._c_retries.inc()

        with self.tracer.span("client.request",
                              attrs={"endpoint": self.endpoint}) as root:
            payload = self.retry_policy.execute(
                lambda: self._call_once(body, deadline),
                retry_on=(ServiceUnavailableError, URLError, ConnectionError),
                deadline=deadline, sleep=self._sleep, on_retry=note_retry)
            root.set_attribute("retries", self.retries)
        if "error" in payload:
            raise RuntimeError(payload["error"])
        return np.asarray(payload["output"], np.float32)

    def _generate_endpoint(self, path: str) -> str:
        from urllib.parse import urlparse, urlunparse

        u = urlparse(self.endpoint)
        return urlunparse((u.scheme, u.netloc, path, "", "", ""))

    def generate(self, prompt, *, max_tokens: Optional[int] = None,
                 greedy: bool = True, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 eos_id: Optional[int] = None,
                 speculative_k: Optional[int] = None,
                 timeout: Optional[float] = None,
                 path: str = "/v1/generate"):
        """Streamed generation against ``POST /v1/generate``: yields the
        server's ordered token events ({"token", "index"}...
        {"done", "reason", "count"}). 503 (shed/draining — only possible
        BEFORE the first event) retries under the deadline like
        :meth:`predict`; 400 raises ``ValueError``. The enclosing
        ``client.request`` span propagates ``traceparent`` so the server's
        trace gains the engine.prefill/engine.decode children."""
        payload = {"prompt": [int(t) for t in prompt], "stream": True,
                   "greedy": greedy, "temperature": temperature,
                   "top_k": top_k, "top_p": top_p, "seed": seed}
        if max_tokens is not None:
            payload["max_tokens"] = max_tokens
        if eos_id is not None:
            payload["eos_id"] = eos_id
        if speculative_k is not None:
            payload["speculative_k"] = speculative_k
        body = json.dumps(payload).encode()
        deadline = Deadline.after(
            timeout if timeout is not None else self.timeout,
            clock=self._clock)
        endpoint = self._generate_endpoint(path)
        tracer = self.tracer

        def open_stream():
            rem = deadline.remaining()
            if rem is not None and rem <= 0:
                raise DeadlineExceededError("client deadline exceeded")
            headers = {"Content-Type": "application/json"}
            if rem is not None:
                headers["X-Deadline-Ms"] = str(int(rem * 1000))
            parent = current_context() if tracer.enabled else None
            if parent is not None:
                headers["traceparent"] = encode_traceparent(parent.child())
            req = urllib_request.Request(endpoint, data=body,
                                         headers=headers)
            try:
                return urllib_request.urlopen(req, timeout=rem)
            except HTTPError as e:
                detail = ""
                try:
                    detail = json.loads(e.read()).get("error", "")
                except Exception:
                    pass
                if e.code == 503:
                    ra = e.headers.get("Retry-After")
                    raise ServiceUnavailableError(
                        detail or "service unavailable",
                        retry_after=float(ra) if ra else None) from e
                if e.code == 400:
                    raise ValueError(detail or "bad request") from e
                raise RuntimeError(f"HTTP {e.code}: {detail}") from e

        with tracer.span("client.request",
                         attrs={"endpoint": endpoint}):
            # retries cover stream OPENING only (503/connect errors before
            # the first byte). Once events flow, a connection drop raises
            # PartialStreamError with the tokens received so far — NEVER a
            # transparent re-open, which would re-emit tokens the caller
            # already consumed.
            resp = self.retry_policy.execute(
                open_stream,
                retry_on=(ServiceUnavailableError, URLError, ConnectionError),
                deadline=deadline, sleep=self._sleep)
            tokens: list = []
            with resp:
                try:
                    for line in resp:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            ev = json.loads(line)
                        except ValueError as e:  # truncated NDJSON line
                            raise PartialStreamError(
                                f"stream truncated after {len(tokens)} "
                                f"tokens: {e}", tokens) from e
                        if "token" in ev:
                            tokens.append(ev["token"])
                        yield ev
                        if ev.get("done"):
                            return
                except PartialStreamError:
                    raise
                except (ConnectionError, _http_client.HTTPException,
                        URLError, OSError) as e:
                    raise PartialStreamError(
                        f"stream dropped after {len(tokens)} tokens: {e}",
                        tokens) from e
            # EOF with no terminal event: the server died between lines
            raise PartialStreamError(
                f"stream ended without a done event after {len(tokens)} "
                f"tokens", tokens)
