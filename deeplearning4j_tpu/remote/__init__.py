from .server import JsonModelServer, JsonRemoteInference

__all__ = ["JsonModelServer", "JsonRemoteInference"]
