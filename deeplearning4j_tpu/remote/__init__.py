from .server import JsonModelServer, JsonRemoteInference, ServiceUnavailableError

__all__ = ["JsonModelServer", "JsonRemoteInference", "ServiceUnavailableError"]
