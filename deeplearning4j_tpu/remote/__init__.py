from .replica import RemoteDeployError, RemoteReplica
from .server import (JsonModelServer, JsonRemoteInference,
                     PartialStreamError, ServiceUnavailableError)

__all__ = ["JsonModelServer", "JsonRemoteInference", "PartialStreamError",
           "RemoteDeployError", "RemoteReplica", "ServiceUnavailableError"]
