"""Cross-host serving fabric: remote replicas behind ``EnginePool``.

At fleet size, host failure is the steady state, not the exception
(PAPERS.md: the TPU-generations retrospective makes resilience-at-scale
a first-class design axis; the TensorFlow paper's large-scale-system
discipline is the same lesson for the serving tier). PR 10's replica
protocol was kept deliberately narrow — ``name``, ``output_async``,
``load_score``, ``circuit_state`` — precisely so that a replica does not
have to live in this process. :class:`RemoteReplica` implements that
protocol over :class:`~deeplearning4j_tpu.remote.server.
JsonRemoteInference`-style HTTP, so one front
:class:`~deeplearning4j_tpu.parallel.pool.EnginePool` spans engines on
several hosts:

* **One attempt per dispatch.** ``output_async`` performs a single HTTP
  attempt (per-attempt connect/read timeouts bounded by the request
  deadline) on a private worker pool. Connection errors, read timeouts,
  truncated bodies and 503s surface as
  :class:`~deeplearning4j_tpu.core.resilience.ReplicaUnavailableError`
  — the pool's dispatch layer owns failover (next least-loaded replica),
  NOT this adapter, so a request is never retried twice by two layers.
  A 400 is the caller's fault and never fails over.
* **Load scores piggyback on responses.** Every ``JsonModelServer`` POST
  response carries ``X-Load-Score``; the adapter folds the latest value
  into :meth:`load_score` alongside its local in-flight count. When the
  piggybacked score goes stale (``load_score_max_age``), a non-blocking
  ``GET /stats`` poll refreshes it — dispatch never blocks on HTTP.
* **Health prober.** A background thread probes ``GET /health`` every
  ``probe_interval`` seconds and feeds the SAME per-replica
  :class:`~deeplearning4j_tpu.core.resilience.CircuitBreaker` the
  dispatcher respects: degraded/connect-failure probes accumulate
  breaker failures (a dead host is taken out of rotation even with zero
  traffic), an OPEN breaker waits out its timeout, and HALF_OPEN probes
  take exactly one trial slot — a healthy probe closes the breaker and
  the host rejoins dispatch without operator action.
* **Deploy fan-out.** With ``model_name=``, :meth:`make_servable` /
  :meth:`swap` mirror the engine servable surface by driving the remote
  host's ``POST /v1/models/<name>/deploy`` admin route (the host's own
  ``ModelManager`` loads, warms and swaps against the shared
  ``ModelStore``), so ``ModelManager(store, name, engine=pool)`` over a
  remote pool rolls each host atomically — and the pool's existing
  partial-failure rollback re-deploys the prior version on already
  rolled hosts.

Fault sites (chaos testing): ``remote_replica.request`` /
``remote_replica.health`` plus per-replica variants
``remote_replica.request.<name>`` / ``remote_replica.health.<name>`` —
inject latency for slow hosts, ``ConnectionError`` for drops.

Metrics (README "Observability"):
``dl4j_tpu_fabric_probe_total{replica=,outcome=ok|degraded|error}``,
``dl4j_tpu_fabric_replica_healthy{replica=}`` (1 = breaker closed),
``dl4j_tpu_fabric_request_latency_seconds{replica=}``; the pool adds
``dl4j_tpu_fabric_failover_total{pool=,replica=}``.
"""

from __future__ import annotations

import http.client
import itertools
import json
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, List, Optional
from urllib import request as urllib_request
from urllib.error import HTTPError, URLError
from urllib.parse import urlparse

import numpy as np

from ..core.resilience import (
    CircuitBreaker,
    CircuitState,
    Deadline,
    DeadlineExceededError,
    ReplicaUnavailableError,
    get_fault_injector,
)
from ..obs.metrics import MetricsRegistry, get_registry

REQUEST_SITE = "remote_replica.request"  # fired per HTTP request attempt
HEALTH_SITE = "remote_replica.health"    # fired per health probe

_PROBE_OUTCOMES = ("ok", "degraded", "error")

_replica_seq = itertools.count()


class RemoteDeployError(RuntimeError):
    """A remote admin deploy/rollback could not complete on that host."""


class _RemoteServable:
    """Servable handle for a version that lives on a remote host. ``fwd``
    is a no-op: the remote host warms its own jitted forward during its
    own deploy — there is nothing local to execute."""

    __slots__ = ("replica", "version", "model")

    remote = True

    def __init__(self, replica: "RemoteReplica", version: str) -> None:
        self.replica = replica
        self.version = str(version)
        self.model = None

    def fwd(self, x):  # warmed remotely at deploy time
        return None


class RemoteReplica:
    """EnginePool replica protocol (``name`` / ``output_async`` /
    ``load_score`` / ``circuit_state``) over HTTP to a
    :class:`~deeplearning4j_tpu.remote.server.JsonModelServer` on
    another host."""

    is_remote = True
    last_input_shape = None  # nothing local is compiled

    def __init__(
        self,
        endpoint: str,
        *,
        name: Optional[str] = None,
        model_name: Optional[str] = None,
        connect_timeout: float = 2.0,
        read_timeout: float = 30.0,
        deploy_timeout: float = 120.0,
        probe_interval: float = 1.0,
        load_score_max_age: float = 5.0,
        max_inflight: int = 64,
        workers: int = 4,
        circuit_breaker: Optional[CircuitBreaker] = None,
        clock: Callable[[], float] = time.monotonic,
        fault_injector=None,
        registry: Optional[MetricsRegistry] = None,
        start_prober: bool = True,
        role: str = "unified",
    ) -> None:
        u = urlparse(endpoint)
        if not u.scheme or not u.netloc:
            raise ValueError(f"endpoint must be an absolute URL, got "
                             f"{endpoint!r}")
        self._base = f"{u.scheme}://{u.netloc}"
        self.endpoint = endpoint if u.path else f"{self._base}/v1/serving"
        # the sequence number keeps auto-names unique: two adapters to
        # the same netloc must not share metric label children or
        # collide in the pool's per-name failover bookkeeping
        self.name = name or f"remote-{u.netloc}-{next(_replica_seq)}"
        self.model_name = model_name
        # serving role in a disaggregated tier (prefill | decode | unified)
        self.role = str(role)
        self.connect_timeout = float(connect_timeout)
        self.read_timeout = float(read_timeout)
        self.deploy_timeout = float(deploy_timeout)
        self.probe_interval = float(probe_interval)
        self.load_score_max_age = float(load_score_max_age)
        # pool's default admission window sums per-replica capacity; a
        # remote host's true window is not locally knowable — this is
        # the hint the pool uses
        self.max_pending = int(max_inflight)
        self._clock = clock
        self._fault_injector = fault_injector
        self._lock = threading.Lock()
        self._inflight = 0
        self._remote_score: Optional[float] = None
        self._remote_score_at = 0.0
        self._score_refreshing = False
        self._identity: Optional[dict] = None
        self._remote_speculative: Optional[dict] = None
        self._model_version: Optional[str] = None
        self._shutdown = False
        self._request_site = f"{REQUEST_SITE}.{self.name}"
        self._health_site = f"{HEALTH_SITE}.{self.name}"

        reg = registry if registry is not None else get_registry()
        probe = reg.counter(
            "dl4j_tpu_fabric_probe_total",
            "Remote-replica health probes by outcome",
            ("replica", "outcome"))
        self._c_probe = {o: probe.labels(self.name, o)
                         for o in _PROBE_OUTCOMES}
        self._g_healthy = reg.gauge(
            "dl4j_tpu_fabric_replica_healthy",
            "1 while the remote replica's breaker is closed, else 0",
            ("replica",)).labels(self.name)
        self._h_latency = reg.histogram(
            "dl4j_tpu_fabric_request_latency_seconds",
            "Remote-replica request latency (submit through response)",
            ("replica",)).labels(self.name)

        self._breaker: CircuitBreaker = None  # set by _adopt_breaker
        self._adopt_breaker(circuit_breaker
                            or CircuitBreaker(clock=clock))
        self._executor = ThreadPoolExecutor(
            max_workers=max(1, int(workers)),
            thread_name_prefix=f"{self.name}-rr")
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        if start_prober:
            self.start_prober()

    # ----- breaker / identity -----------------------------------------
    def _on_breaker_transition(self, old: CircuitState,
                               new: CircuitState) -> None:
        self._g_healthy.set(1.0 if new is CircuitState.CLOSED else 0.0)

    def _adopt_breaker(self, breaker: CircuitBreaker) -> None:
        """Swap in a (possibly shared, pool-probation) breaker — the
        prober and dispatch always read ``self._breaker`` live."""
        old = self._breaker
        if old is not None:
            old.remove_observer(self._on_breaker_transition)
        breaker.add_observer(self._on_breaker_transition)
        self._breaker = breaker
        self._g_healthy.set(
            1.0 if breaker.state is CircuitState.CLOSED else 0.0)

    @property
    def circuit_state(self) -> CircuitState:
        return self._breaker.state

    @property
    def model(self):
        return None  # the model lives on the remote host

    @property
    def model_version(self) -> str:
        """Last-known remote live version; fetched lazily from the remote
        ``GET /v1/models`` listing when a ``model_name`` is configured."""
        with self._lock:
            if self._model_version is not None:
                return self._model_version
        if not self.model_name:
            with self._lock:
                self._model_version = "0"
            return "0"
        try:
            with urllib_request.urlopen(
                    f"{self._base}/v1/models",
                    timeout=self.connect_timeout) as r:
                models = json.loads(r.read())["models"]
            v = str(models.get(self.model_name, {}).get(
                "live_version", "0"))
        except Exception:
            # transient fetch failure: answer "0" but do NOT cache it —
            # a later swap() would otherwise record old_version="0" and
            # the pool's partial-failure rollback would "roll back" to a
            # version that never existed. The next call retries.
            return "0"
        with self._lock:
            self._model_version = v
        return v

    def bucket_sizes(self) -> List[int]:
        return []  # batching happens on the remote host

    def _inj(self):
        return self._fault_injector or get_fault_injector()

    # ----- request path ------------------------------------------------
    def output_async(self, x, *, timeout: Optional[float] = None,
                     deadline: Optional[Deadline] = None,
                     priority: Optional[str] = None) -> Future:
        """Submit one inference request to the remote host. Fails fast
        with ``CircuitOpenError`` while the breaker is open (the pool
        skips/falls over synchronously); host-level failures settle the
        returned future with ``ReplicaUnavailableError`` (the pool's
        failover trigger)."""
        if self._shutdown:
            raise RuntimeError(f"{self.name} is shut down")
        self._breaker.check()
        if deadline is None:
            deadline = Deadline.after(
                timeout if timeout is not None else self.read_timeout,
                clock=self._clock)
        body = json.dumps(
            {"data": np.asarray(x, np.float32).tolist()}).encode()
        fut: Future = Future()
        with self._lock:
            self._inflight += 1
        try:
            self._executor.submit(self._run_request, body, deadline,
                                  priority, fut)
        except RuntimeError:
            with self._lock:
                self._inflight -= 1
            self._breaker.release()  # give back the half-open trial slot
            raise RuntimeError(f"{self.name} is shut down")
        return fut

    def output(self, x, *, timeout: Optional[float] = None,
               priority: Optional[str] = None) -> np.ndarray:
        return self.output_async(x, timeout=timeout,
                                 priority=priority).result()

    def _run_request(self, body: bytes, deadline: Deadline,
                     priority: Optional[str], fut: Future) -> None:
        t0 = time.perf_counter()
        breaker = self._breaker
        try:
            out = self._call_once(body, deadline, priority)
        except (ValueError, DeadlineExceededError) as e:
            # the caller's input / the caller's deadline — the host is
            # fine, so the breaker records nothing and nothing fails
            # over; the half-open trial slot check() reserved must still
            # be given back or the breaker wedges in HALF_OPEN
            breaker.release()
            fut.set_exception(e)
        except Exception as e:
            breaker.record_failure()
            fut.set_exception(e)
        else:
            breaker.record_success()
            fut.set_result(out)
        finally:
            with self._lock:
                self._inflight -= 1
            self._h_latency.observe(time.perf_counter() - t0)

    def _call_once(self, body: bytes, deadline: Deadline,
                   priority: Optional[str]) -> np.ndarray:
        inj = self._inj()
        inj.fire(REQUEST_SITE)
        inj.fire(self._request_site)
        rem = deadline.remaining()
        if rem is not None and rem <= 0:
            raise DeadlineExceededError(
                f"{self.name}: deadline exceeded before dispatch")
        # per-attempt timeout: a dead host is detected within
        # read_timeout even on an unbounded request, and an attempt
        # never outlives the request deadline
        t = self.read_timeout if rem is None else min(self.read_timeout, rem)
        headers = {"Content-Type": "application/json"}
        if rem is not None:
            headers["X-Deadline-Ms"] = str(int(rem * 1000))
        if priority:
            headers["X-Priority"] = priority
        req = urllib_request.Request(self.endpoint, data=body,
                                     headers=headers)
        try:
            with urllib_request.urlopen(req, timeout=max(t, 0.001)) as resp:
                raw = resp.read()
                self._note_score(resp.headers.get("X-Load-Score"))
        except HTTPError as e:
            detail = ""
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:
                pass
            if e.code == 503:
                # Retry-After may be an HTTP-date (RFC 7231), not just
                # delta-seconds — an unparseable hint must not turn a
                # host-unavailable signal into a caller error
                try:
                    ra = float(e.headers.get("Retry-After"))
                except (TypeError, ValueError):
                    ra = None
                raise ReplicaUnavailableError(
                    f"{self.name}: 503 {detail or 'unavailable'}",
                    retry_after=ra) from e
            if e.code == 400:
                raise ValueError(detail or "bad request") from e
            if e.code == 504:
                raise DeadlineExceededError(
                    detail or "deadline exceeded") from e
            raise RuntimeError(
                f"{self.name}: HTTP {e.code}: {detail}") from e
        except (ConnectionError, http.client.HTTPException, URLError,
                OSError) as e:
            if deadline.expired():
                raise DeadlineExceededError(
                    f"{self.name}: deadline exceeded in flight") from e
            raise ReplicaUnavailableError(
                f"{self.name}: connection failed: {e}") from e
        try:
            payload = json.loads(raw)
        except ValueError as e:  # truncated/garbled body: a host failure
            raise ReplicaUnavailableError(
                f"{self.name}: truncated response: {e}") from e
        if "error" in payload:
            raise RuntimeError(f"{self.name}: {payload['error']}")
        return np.asarray(payload["output"], np.float32)

    # ----- load score ---------------------------------------------------
    def _note_score(self, header_val) -> None:
        if header_val is None:
            return
        try:
            score = float(header_val)
        except (TypeError, ValueError):
            return
        with self._lock:
            self._remote_score = score
            self._remote_score_at = self._clock()

    def load_score(self) -> float:
        """Local in-flight count plus the host's last piggybacked load
        score. A stale remote score (older than ``load_score_max_age``)
        schedules a non-blocking ``GET /stats`` refresh — the dispatch
        path itself never blocks on HTTP."""
        with self._lock:
            inflight = self._inflight
            score, at = self._remote_score, self._remote_score_at
        if score is None:
            stale, score = True, 0.0
        else:
            stale = (self._clock() - at) > self.load_score_max_age
        if stale:
            self._schedule_score_refresh()
        return float(inflight) + max(0.0, float(score))

    def _schedule_score_refresh(self) -> None:
        if self._shutdown:
            return
        with self._lock:
            if self._score_refreshing:
                return
            self._score_refreshing = True

        def _poll():
            try:
                self.poll_stats()
            except Exception:
                pass
            finally:
                with self._lock:
                    self._score_refreshing = False

        try:
            self._executor.submit(_poll)
        except RuntimeError:
            with self._lock:
                self._score_refreshing = False

    @staticmethod
    def _extract_speculative(s: dict) -> Optional[dict]:
        """Normalize the host's speculative-decoding counters out of a
        ``/stats`` payload: a direct ``generator=`` host carries them
        under ``generate.speculative``; a host fronting its own pool of
        decode replicas under ``pool.generate``. Returns
        ``{proposed, accepted, steps}`` or None when the host serves no
        generation."""
        gen = s.get("generate")
        if isinstance(gen, dict) and isinstance(gen.get("speculative"),
                                                dict):
            gen = gen["speculative"]
        else:
            pool = s.get("pool")
            gen = pool.get("generate") if isinstance(pool, dict) else None
        if not isinstance(gen, dict) or "proposed" not in gen:
            return None
        return {"proposed": int(gen.get("proposed") or 0),
                "accepted": int(gen.get("accepted") or 0),
                "steps": int(gen.get("steps") or 0)}

    def poll_stats(self, timeout: Optional[float] = None) -> dict:
        """Synchronous ``GET /stats``: the staleness-bounded fallback for
        the piggybacked load score, the source of the remote identity
        block (``name``/``uptime_seconds``/``pid``), and of the host's
        speculative-decoding counters (folded into a front pool's
        ``stats()["generate"]`` aggregation)."""
        t = timeout if timeout is not None else self.connect_timeout
        with urllib_request.urlopen(f"{self._base}/stats", timeout=t) as r:
            s = json.loads(r.read())
        qd = s.get("queue_depth")
        spec = self._extract_speculative(s)
        with self._lock:
            if s.get("replica"):
                self._identity = s["replica"]
            if qd is not None:
                self._remote_score = float(qd)
                self._remote_score_at = self._clock()
            if spec is not None:
                self._remote_speculative = spec
        return s

    # ----- health prober -------------------------------------------------
    def start_prober(self) -> None:
        if self._probe_thread is not None or self._shutdown:
            return
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name=f"{self.name}-prober",
            daemon=True)
        self._probe_thread.start()

    def _probe_loop(self) -> None:
        while not self._probe_stop.wait(self.probe_interval):
            try:
                self.probe()
            except Exception:
                pass

    def probe(self) -> str:
        """One health probe, respecting the breaker state machine:
        OPEN waits out the breaker timeout (returns ``"open_wait"``),
        HALF_OPEN takes exactly one trial slot via ``allow()`` (a second
        concurrent probe returns ``"probe_inflight"``), CLOSED probes
        freely. A healthy probe records a breaker success (closing a
        half-open breaker); degraded/connect failure records a failure
        (opening the breaker even with zero request traffic)."""
        breaker = self._breaker
        state = breaker.state  # open -> half-open transition happens here
        if state is CircuitState.OPEN:
            return "open_wait"
        if state is CircuitState.HALF_OPEN and not breaker.allow():
            return "probe_inflight"
        payload = None
        try:
            inj = self._inj()
            inj.fire(HEALTH_SITE)
            inj.fire(self._health_site)
            with urllib_request.urlopen(
                    f"{self._base}/health",
                    timeout=self.connect_timeout) as r:
                payload = json.loads(r.read())
            outcome = "ok" if payload.get("status") == "ok" else "degraded"
        except HTTPError as e:  # degraded/draining answer 503 with a body
            outcome = "degraded"
            try:
                payload = json.loads(e.read())
            except Exception:
                payload = None
        except Exception:
            outcome = "error"
        self._c_probe[outcome].inc()
        if outcome == "ok":
            breaker.record_success()
            with self._lock:
                if payload.get("replica"):
                    self._identity = payload["replica"]
                qd = payload.get("queue_depth")
                if qd is not None:
                    self._remote_score = float(qd)
                    self._remote_score_at = self._clock()
        else:
            breaker.record_failure()
        return outcome

    # ----- servable lifecycle (remote deploy fan-out) --------------------
    @property
    def _servable(self) -> _RemoteServable:
        return _RemoteServable(self, self.model_version)

    def make_servable(self, model, *, version: str = "0") -> _RemoteServable:
        """The remote host loads ``version`` from the shared ModelStore at
        swap time; the locally loaded ``model`` is ignored."""
        return _RemoteServable(self, version)

    def swap(self, servable: _RemoteServable, *,
             circuit_breaker: Optional[CircuitBreaker] = None
             ) -> _RemoteServable:
        """Deploy ``servable.version`` on the remote host via its admin
        route (``POST /v1/models/<name>/deploy`` — the host's own
        ModelManager loads, warms and swaps). Returns the retired
        servable (the previously live version), so the pool's
        partial-failure rollback re-deploys it by swapping back."""
        if self.model_name is None:
            raise RemoteDeployError(
                f"{self.name}: remote deploy fan-out needs model_name=")
        old_version = self.model_version
        self._admin("deploy", {"version": servable.version})
        if circuit_breaker is not None:
            self._adopt_breaker(circuit_breaker)
        with self._lock:
            self._model_version = str(servable.version)
        return _RemoteServable(self, old_version)

    def _admin(self, action: str, payload: Optional[dict]) -> dict:
        url = f"{self._base}/v1/models/{self.model_name}/{action}"
        req = urllib_request.Request(
            url, data=json.dumps(payload or {}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib_request.urlopen(req,
                                        timeout=self.deploy_timeout) as r:
                return json.loads(r.read())
        except HTTPError as e:
            detail = ""
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:
                pass
            raise RemoteDeployError(
                f"{self.name}: {action} failed: HTTP {e.code} "
                f"{detail}") from e
        except (URLError, OSError) as e:
            raise RemoteDeployError(
                f"{self.name}: {action} failed: {e}") from e

    # ----- introspection / lifecycle -------------------------------------
    def stats(self) -> dict:
        with self._lock:
            score, at = self._remote_score, self._remote_score_at
            ident = dict(self._identity) if self._identity else None
            inflight = self._inflight
        age = None if score is None else max(0.0, self._clock() - at)
        if ident is None and not self._shutdown:
            try:  # attributable identity on demand (bounded, best-effort)
                self.poll_stats()
                with self._lock:
                    ident = (dict(self._identity)
                             if self._identity else None)
            except Exception:
                pass
        with self._lock:
            spec = (dict(self._remote_speculative)
                    if self._remote_speculative else None)
        out = {
            "name": self.name,
            "endpoint": self.endpoint,
            "role": self.role,
            "remote": ident,
            "circuit_state": self._breaker.state.value,
            "queue_depth": inflight,
            "inflight": inflight,
            "remote_load_score": score,
            "remote_score_age_s": age,
            "load_score": self.load_score(),
            "probes": {o: int(c.value) for o, c in self._c_probe.items()},
        }
        if spec is not None:
            # the host serves generation: surface its acceptance counters
            # so the front pool's stats()["generate"] can fold them in
            out["speculative"] = spec
        return out

    def drain(self, timeout: Optional[float] = None) -> bool:
        end = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                if self._inflight == 0:
                    return True
            if end is not None and time.monotonic() >= end:
                return False
            time.sleep(0.01)

    def shutdown(self, *, drain: bool = True,
                 drain_timeout: Optional[float] = 30.0) -> None:
        if drain and not self._shutdown:
            self.drain(timeout=drain_timeout)
        self._shutdown = True
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None
        self._executor.shutdown(wait=False)
