"""ONNX model import.

Reference: nd4j/samediff-import/samediff-import-onnx — OnnxFrameworkImporter
and the Kotlin OpMappingRegistry rules (SURVEY.md:119, §2.2 "ONNX import").
Same job here: ONNX ModelProto -> SameDiff graph, op-by-op mapping rules
with attribute/dtype translation, initializers becoming graph constants.

Design notes (TPU-first, mirroring tf_import.py):
* Parsing uses the vendored protoc schema ``onnx_proto.onnx_pb2`` — no
  external ``onnx`` package dependency (none exists in this environment).
* ONNX feeds shape-like operands (Reshape's shape, Slice's starts/ends,
  opset-13 Squeeze/Unsqueeze axes) as tensor inputs; XLA wants static
  shapes, so const-backed operands are folded into attrs at import time and
  truly dynamic ones are rejected with a clear error.
* ONNX is NCHW throughout — the framework's own CNN convention — so Conv /
  pooling map directly; only the weight layout transposes (ONNX
  [M, C/g, kH, kW] -> TF HWIO [kH, kW, C/g, M]), folded into the constant
  when the weight is an initializer.
* Inference semantics: Dropout is identity, BatchNormalization uses the
  stored running statistics.

The registry is ``ONNX_OP_RULES``: op_type -> rule(ctx) returning an
SDVariable, or a dict {output_name_index: SDVariable} for multi-output ops.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..samediff.samediff import SDVariable, SameDiff
from .onnx_proto import onnx_pb2

# TensorProto.DataType -> numpy dtype (ONNX IR spec enum values)
_ONNX_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}


def tensor_to_numpy(t) -> np.ndarray:
    """Decode a TensorProto: raw_data when present, typed repeated fields
    otherwise (the two serializations the spec allows)."""
    if t.data_type not in _ONNX_DTYPES:
        raise NotImplementedError(f"ONNX tensor dtype {t.data_type} unsupported")
    dtype = np.dtype(_ONNX_DTYPES[t.data_type])
    shape = tuple(t.dims)
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=dtype)
    elif t.data_type == 1:
        arr = np.asarray(list(t.float_data), dtype)
    elif t.data_type == 11:
        arr = np.asarray(list(t.double_data), dtype)
    elif t.data_type in (6, 2, 3, 4, 5, 9):
        arr = np.asarray(list(t.int32_data), dtype)
    elif t.data_type in (7,):
        arr = np.asarray(list(t.int64_data), dtype)
    elif t.data_type in (12, 13):
        arr = np.asarray(list(t.uint64_data), dtype)
    else:  # pragma: no cover
        raise NotImplementedError(f"no data field for dtype {t.data_type}")
    return arr.reshape(shape)


@dataclasses.dataclass
class _NodeCtx:
    name: str
    op: str
    inputs: List[str]
    outputs: List[str]
    attr: Dict[str, Any]  # name -> AttributeProto
    importer: "OnnxGraphMapper"

    # ---- attribute accessors (AttributeProto is a tagged union) ----------
    def a_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        return int(self.attr[key].i) if key in self.attr else default

    def a_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        return float(self.attr[key].f) if key in self.attr else default

    def a_str(self, key: str, default: str = "") -> str:
        return self.attr[key].s.decode() if key in self.attr else default

    def a_ints(self, key: str, default=None):
        return [int(v) for v in self.attr[key].ints] if key in self.attr else default

    def a_tensor(self, key: str) -> np.ndarray:
        return tensor_to_numpy(self.attr[key].t)

    # ---- inputs ----------------------------------------------------------
    def has(self, i: int) -> bool:
        return i < len(self.inputs) and self.inputs[i] != ""

    def var(self, i: int) -> SDVariable:
        return self.importer.resolve(self.inputs[i])

    def const_value(self, i: int) -> np.ndarray:
        src = self.inputs[i]
        if src not in self.importer.const_values:
            raise ValueError(
                f"{self.op} node {self.name!r}: input {i} ({src!r}) must be an "
                "initializer/Constant for static-shape import"
            )
        return self.importer.const_values[src]


Rule = Callable[[_NodeCtx], Any]
ONNX_OP_RULES: Dict[str, Rule] = {}


def onnx_rule(*names: str):
    def deco(fn: Rule):
        for n in names:
            ONNX_OP_RULES[n] = fn
        return fn

    return deco


# ---- simple 1:1 maps ------------------------------------------------------
_SIMPLE = {
    "Add": "add", "Sub": "sub", "Mul": "mul", "Div": "div", "Pow": "pow",
    "Max": "maximum", "Min": "minimum", "Neg": "neg", "Abs": "abs",
    "Sign": "sign", "Exp": "exp", "Log": "log", "Sqrt": "sqrt",
    "Reciprocal": "reciprocal", "Sin": "sin", "Cos": "cos", "Tan": "tan",
    "Asin": "asin", "Acos": "acos", "Atan": "atan", "Sinh": "sinh",
    "Cosh": "cosh", "Tanh": "tanh", "Asinh": "asinh", "Acosh": "acosh",
    "Atanh": "atanh", "Erf": "erf", "Floor": "floor", "Ceil": "ceil",
    "Round": "round", "Relu": "relu", "Elu": "elu", "Selu": "selu",
    "Sigmoid": "sigmoid", "Softplus": "softplus", "Softsign": "softsign",
    "Greater": "gt", "GreaterOrEqual": "gte", "Less": "lt",
    "LessOrEqual": "lte", "Equal": "eq", "And": "logical_and",
    "Or": "logical_or", "Not": "logical_not", "Identity": "identity",
    "Where": "select", "MatMul": "matmul", "IsNaN": "isnan", "IsInf": "isinf",
}

for _onnx_name, _sd_name in _SIMPLE.items():
    def _mk(sd_name):
        def rule(ctx: _NodeCtx):
            return ctx.importer.sd._op(
                sd_name, *(ctx.var(i) for i in range(len(ctx.inputs))),
                name=ctx.outputs[0])

        return rule

    ONNX_OP_RULES[_onnx_name] = _mk(_sd_name)


@onnx_rule("Sum")
def _sum(ctx):
    sd = ctx.importer.sd
    if len(ctx.inputs) == 1:  # valid per spec; must still bind the out name
        return sd._op("identity", ctx.var(0), name=ctx.outputs[0])
    out = ctx.var(0)
    for i in range(1, len(ctx.inputs) - 1):
        out = sd._op("add", out, ctx.var(i))
    return sd._op("add", out, ctx.var(len(ctx.inputs) - 1), name=ctx.outputs[0])


@onnx_rule("Gelu")
def _gelu(ctx):
    approx = ctx.a_str("approximate", "none") == "tanh"
    return ctx.importer.sd._op("gelu", ctx.var(0), name=ctx.outputs[0],
                               approximate=approx)


@onnx_rule("LeakyRelu")
def _leaky(ctx):
    return ctx.importer.sd._op("leaky_relu", ctx.var(0), name=ctx.outputs[0],
                               alpha=ctx.a_float("alpha", 0.01))


@onnx_rule("HardSigmoid")
def _hard_sigmoid(ctx):
    # sd hard_sigmoid is the alpha=0.2/beta=0.5 definition
    return ctx.importer.sd._op("hard_sigmoid", ctx.var(0), name=ctx.outputs[0])


@onnx_rule("Clip")
def _clip(ctx):
    lo = float(ctx.const_value(1)) if ctx.has(1) else None
    hi = float(ctx.const_value(2)) if ctx.has(2) else None
    return ctx.importer.sd._op("clip_by_value", ctx.var(0), name=ctx.outputs[0],
                               clip_value_min=lo, clip_value_max=hi)


@onnx_rule("Softmax", "LogSoftmax")
def _softmax(ctx):
    # opset >= 13 default axis is -1 (the exporters this importer targets)
    op = "softmax" if ctx.op == "Softmax" else "log_softmax"
    return ctx.importer.sd._op(op, ctx.var(0), name=ctx.outputs[0],
                               axis=ctx.a_int("axis", -1))


@onnx_rule("Gemm")
def _gemm(ctx):
    """Gemm: alpha*op(A)@op(B) + beta*C — composed from matmul/mul/add."""
    sd = ctx.importer.sd
    y = sd._op("matmul", ctx.var(0), ctx.var(1),
               transpose_a=bool(ctx.a_int("transA", 0)),
               transpose_b=bool(ctx.a_int("transB", 0)))
    alpha, beta = ctx.a_float("alpha", 1.0), ctx.a_float("beta", 1.0)
    if alpha != 1.0:
        y = sd._op("mul", y, sd.constant(np.float32(alpha)))
    if ctx.has(2):
        c = ctx.var(2)
        if beta != 1.0:
            c = sd._op("mul", c, sd.constant(np.float32(beta)))
        y = sd._op("add", y, c, name=ctx.outputs[0])
    else:
        y = sd._op("identity", y, name=ctx.outputs[0])
    return y


def _conv_padding(ctx, spatial_rank: int):
    """ONNX pads [x1b, x2b, ..., x1e, x2e] -> [(b, e), ...] per spatial dim;
    auto_pad when set wins (NOTSET is the exporter norm)."""
    auto = ctx.a_str("auto_pad", "NOTSET")
    if auto == "SAME_UPPER":
        return "SAME"
    if auto == "SAME_LOWER":
        # XLA 'SAME' is SAME_UPPER; with odd total padding the extra pixel
        # lands on the wrong side — wrong silently, so reject loudly
        raise NotImplementedError(
            f"{ctx.op} node {ctx.name!r}: auto_pad=SAME_LOWER unsupported "
            "(re-export with explicit pads)")
    if auto == "VALID":
        return "VALID"
    pads = ctx.a_ints("pads", [0] * (2 * spatial_rank))
    return [(pads[i], pads[i + spatial_rank]) for i in range(spatial_rank)]


@onnx_rule("Conv")
def _conv(ctx):
    sd = ctx.importer.sd
    groups = ctx.a_int("group", 1)
    kernel = ctx.a_ints("kernel_shape")
    if kernel is not None and len(kernel) != 2:
        raise NotImplementedError(f"Conv rank {len(kernel)} unsupported (2D only)")
    w_name = ctx.inputs[1]
    if w_name in ctx.importer.const_values:
        # fold the [M, C/g, kH, kW] -> HWIO transpose into the constant
        w_np = ctx.importer.const_values[w_name].transpose(2, 3, 1, 0)
        w = sd.constant(np.ascontiguousarray(w_np))
    else:
        w = sd._op("transpose", ctx.var(1), perm=[2, 3, 1, 0])
    bias = ctx.var(2) if ctx.has(2) else None
    args = (ctx.var(0), w) if bias is None else (ctx.var(0), w, bias)
    return sd._op(
        "conv2d", *args, name=ctx.outputs[0],
        strides=tuple(ctx.a_ints("strides", [1, 1])),
        dilations=tuple(ctx.a_ints("dilations", [1, 1])),
        padding=_conv_padding(ctx, 2), data_format="NCHW", groups=groups,
    )


@onnx_rule("BatchNormalization")
def _bn(ctx):
    # inputs: X, scale, B, input_mean, input_var (inference form)
    return ctx.importer.sd._op(
        "batch_norm", ctx.var(0), ctx.var(3), ctx.var(4), ctx.var(1),
        ctx.var(2), name=ctx.outputs[0], eps=ctx.a_float("epsilon", 1e-5),
        axis=1,
    )


@onnx_rule("LayerNormalization")
def _ln(ctx):
    axis = ctx.a_int("axis", -1)
    if axis not in (-1,):
        raise NotImplementedError("LayerNormalization only over the last axis")
    beta = ctx.var(2) if ctx.has(2) else None
    args = (ctx.var(0), ctx.var(1)) + ((beta,) if beta is not None else ())
    return ctx.importer.sd._op("layer_norm", *args, name=ctx.outputs[0],
                               axis=-1, eps=ctx.a_float("epsilon", 1e-5))


@onnx_rule("MaxPool", "AveragePool")
def _pool(ctx):
    if len(ctx.outputs) > 1 and ctx.outputs[1]:
        raise NotImplementedError("MaxPool Indices output unsupported")
    if ctx.a_int("ceil_mode", 0):
        raise NotImplementedError(
            f"{ctx.op} node {ctx.name!r}: ceil_mode=1 unsupported "
            "(lax.reduce_window floors output dims; re-export with "
            "ceil_mode=0 or explicit pads)")
    kernel = ctx.a_ints("kernel_shape")
    pad = _conv_padding(ctx, len(kernel))  # string or spatial (lo, hi) pairs
    if not isinstance(pad, str) and all(p == (0, 0) for p in pad):
        pad = "VALID"
    attrs = dict(
        kernel=tuple(kernel),
        strides=tuple(ctx.a_ints("strides", [1] * len(kernel))),
        padding=pad, data_format="NCHW",
    )
    if ctx.op == "AveragePool":
        attrs["count_include_pad"] = bool(ctx.a_int("count_include_pad", 0))
        return ctx.importer.sd._op("avg_pool2d", ctx.var(0),
                                   name=ctx.outputs[0], **attrs)
    return ctx.importer.sd._op("max_pool2d", ctx.var(0), name=ctx.outputs[0],
                               **attrs)


@onnx_rule("GlobalAveragePool")
def _gap(ctx):
    return ctx.importer.sd._op("reduce_mean", ctx.var(0), name=ctx.outputs[0],
                               axis=[2, 3], keepdims=True)


@onnx_rule("GlobalMaxPool")
def _gmp(ctx):
    return ctx.importer.sd._op("reduce_max", ctx.var(0), name=ctx.outputs[0],
                               axis=[2, 3], keepdims=True)


@onnx_rule("Flatten")
def _flatten(ctx):
    # [d0, d1..dn] -> [d0, prod(rest)]: batch-preserving flatten; the batch
    # dim stays dynamic (resolved from the input at trace time)
    if ctx.a_int("axis", 1) != 1:
        raise NotImplementedError("Flatten only for axis=1")
    return ctx.importer.sd._op("flatten2d", ctx.var(0), name=ctx.outputs[0])


@onnx_rule("Reshape")
def _reshape(ctx):
    shape = [int(s) for s in ctx.const_value(1).reshape(-1)]
    if 0 in shape:
        if ctx.a_int("allowzero", 0):
            raise NotImplementedError("Reshape allowzero=1 unsupported")
        return ctx.importer.sd._op("reshape_onnx", ctx.var(0),
                                   name=ctx.outputs[0], shape=shape)
    return ctx.importer.sd._op("reshape", ctx.var(0), name=ctx.outputs[0],
                               shape=shape)


@onnx_rule("Transpose")
def _transpose(ctx):
    return ctx.importer.sd._op("transpose", ctx.var(0), name=ctx.outputs[0],
                               perm=ctx.a_ints("perm"))


@onnx_rule("Concat")
def _concat(ctx):
    return ctx.importer.sd._op("concat", *(ctx.var(i) for i in range(len(ctx.inputs))),
                               name=ctx.outputs[0], axis=ctx.a_int("axis"))


@onnx_rule("Unsqueeze")
def _unsqueeze(ctx):
    axes = ctx.a_ints("axes") if "axes" in ctx.attr else \
        [int(v) for v in ctx.const_value(1).reshape(-1)]
    sd = ctx.importer.sd
    out = ctx.var(0)
    for j, ax in enumerate(sorted(axes)):
        out = sd._op("expand_dims", out, axis=ax,
                     name=ctx.outputs[0] if j == len(axes) - 1 else None)
    return out


@onnx_rule("Squeeze")
def _squeeze(ctx):
    axes = None
    if "axes" in ctx.attr:
        axes = ctx.a_ints("axes")
    elif ctx.has(1):
        axes = [int(v) for v in ctx.const_value(1).reshape(-1)]
    return ctx.importer.sd._op("squeeze", ctx.var(0), name=ctx.outputs[0],
                               axis=axes)


@onnx_rule("Slice")
def _slice(ctx):
    if "starts" in ctx.attr:  # opset < 10 attribute form
        starts, ends = ctx.a_ints("starts"), ctx.a_ints("ends")
        axes = ctx.a_ints("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    else:
        starts = [int(v) for v in ctx.const_value(1).reshape(-1)]
        ends = [int(v) for v in ctx.const_value(2).reshape(-1)]
        axes = [int(v) for v in ctx.const_value(3).reshape(-1)] if ctx.has(3) \
            else list(range(len(starts)))
        steps = [int(v) for v in ctx.const_value(4).reshape(-1)] if ctx.has(4) \
            else [1] * len(starts)
    return ctx.importer.sd._op("slice_onnx", ctx.var(0), name=ctx.outputs[0],
                               starts=starts, ends=ends, axes=axes, steps=steps)


@onnx_rule("Gather")
def _gather(ctx):
    return ctx.importer.sd._op("gather", ctx.var(0), ctx.var(1),
                               name=ctx.outputs[0], axis=ctx.a_int("axis", 0))


@onnx_rule("GatherND")
def _gather_nd(ctx):
    return ctx.importer.sd._op("gather_nd", ctx.var(0), ctx.var(1),
                               name=ctx.outputs[0])


def _reduce(sd_name: str):
    def rule(ctx: _NodeCtx):
        axes = ctx.a_ints("axes")
        if axes is None and ctx.has(1):  # opset 18 moved axes to an input
            axes = [int(v) for v in ctx.const_value(1).reshape(-1)]
        return ctx.importer.sd._op(
            sd_name, ctx.var(0), name=ctx.outputs[0], axis=axes,
            keepdims=bool(ctx.a_int("keepdims", 1)))

    return rule


ONNX_OP_RULES["ReduceMean"] = _reduce("reduce_mean")
ONNX_OP_RULES["ReduceSum"] = _reduce("reduce_sum")
ONNX_OP_RULES["ReduceMax"] = _reduce("reduce_max")
ONNX_OP_RULES["ReduceMin"] = _reduce("reduce_min")
ONNX_OP_RULES["ReduceProd"] = _reduce("reduce_prod")


@onnx_rule("ArgMax", "ArgMin")
def _argminmax(ctx):
    op = "argmax" if ctx.op == "ArgMax" else "argmin"
    if ctx.a_int("keepdims", 1):
        sd = ctx.importer.sd
        out = sd._op(op, ctx.var(0), axis=ctx.a_int("axis", 0))
        return sd._op("expand_dims", out, axis=ctx.a_int("axis", 0),
                      name=ctx.outputs[0])
    return ctx.importer.sd._op(op, ctx.var(0), name=ctx.outputs[0],
                               axis=ctx.a_int("axis", 0))


@onnx_rule("Cast")
def _cast(ctx):
    to = ctx.a_int("to")
    if to not in _ONNX_DTYPES:
        raise NotImplementedError(f"Cast to ONNX dtype {to} unsupported")
    return ctx.importer.sd._op("cast", ctx.var(0), name=ctx.outputs[0],
                               dtype=np.dtype(_ONNX_DTYPES[to]).name)


@onnx_rule("Shape")
def _shape(ctx):
    return ctx.importer.sd._op("shape_of", ctx.var(0), name=ctx.outputs[0])


@onnx_rule("Expand")
def _expand(ctx):
    shape = [int(v) for v in ctx.const_value(1).reshape(-1)]
    return ctx.importer.sd._op("broadcast_to", ctx.var(0), name=ctx.outputs[0],
                               shape=shape)


@onnx_rule("Tile")
def _tile(ctx):
    return ctx.importer.sd._op("tile", ctx.var(0), name=ctx.outputs[0],
                               reps=[int(v) for v in ctx.const_value(1).reshape(-1)])


@onnx_rule("Pad")
def _pad(ctx):
    mode = ctx.a_str("mode", "constant")
    if mode != "constant":
        raise NotImplementedError(f"Pad mode {mode!r} unsupported")
    if "pads" in ctx.attr:
        pads = ctx.a_ints("pads")
    else:
        pads = [int(v) for v in ctx.const_value(1).reshape(-1)]
    rank = len(pads) // 2
    paddings = [(pads[i], pads[i + rank]) for i in range(rank)]
    val = 0.0
    if ctx.has(2):
        val = float(ctx.const_value(2))
    return ctx.importer.sd._op("pad", ctx.var(0), name=ctx.outputs[0],
                               paddings=paddings, constant_value=val)


@onnx_rule("Split")
def _split(ctx):
    sd = ctx.importer.sd
    axis = ctx.a_int("axis", 0)
    sizes = ctx.a_ints("split")
    if sizes is None and ctx.has(1):
        sizes = [int(v) for v in ctx.const_value(1).reshape(-1)]
    if sizes is None:
        outs = sd._op("split", ctx.var(0), num_splits=len(ctx.outputs), axis=axis)
    else:
        outs = sd._op("split_v", ctx.var(0), size_splits=sizes, axis=axis)
    return {i: o for i, o in enumerate(outs)} if isinstance(outs, (tuple, list)) \
        else {0: outs}


@onnx_rule("Dropout")
def _dropout(ctx):
    # inference import: identity (mask output, if requested, is all-true)
    return ctx.importer.sd._op("identity", ctx.var(0), name=ctx.outputs[0])


@onnx_rule("ConstantOfShape")
def _const_of_shape(ctx):
    shape = [int(v) for v in ctx.const_value(0).reshape(-1)]
    value = tensor_to_numpy(ctx.attr["value"].t) if "value" in ctx.attr \
        else np.zeros(1, np.float32)
    return ctx.importer.sd.constant(
        np.full(shape, value.reshape(-1)[0], value.dtype), name=ctx.outputs[0])


@onnx_rule("Range")
def _range(ctx):
    def scalar(i):  # keep float Ranges float (int() would truncate delta)
        return ctx.const_value(i).reshape(()).item()

    return ctx.importer.sd._op(
        "range", name=ctx.outputs[0], start=scalar(0),
        limit=scalar(1), delta=scalar(2))


@onnx_rule("Einsum")
def _einsum(ctx):
    return ctx.importer.sd._op(
        "einsum", *(ctx.var(i) for i in range(len(ctx.inputs))),
        name=ctx.outputs[0], equation=ctx.a_str("equation"))


@onnx_rule("Resize")
def _resize(ctx):
    mode = ctx.a_str("mode", "nearest")
    sizes_idx = 3
    if not ctx.has(sizes_idx):
        raise NotImplementedError("Resize requires explicit sizes input")
    sizes = [int(v) for v in ctx.const_value(sizes_idx).reshape(-1)]
    op = "resize_nearest" if mode == "nearest" else "resize_bilinear"
    return ctx.importer.sd._op(op, ctx.var(0), name=ctx.outputs[0],
                               size=sizes[2:], data_format="NCHW")


# ---- tranche-3 rule widening (mirrors the TF-import widening; SURVEY
# §2.2 ONNX import breadth) --------------------------------------------------
_SIMPLE_T3 = {
    "Celu": "celu", "HardSwish": "hard_swish", "Mish": "mish",
    "ThresholdedRelu": "thresholded_relu", "PRelu": "prelu",
    "Xor": "logical_xor",
    "BitwiseAnd": "bitwise_and", "BitwiseOr": "bitwise_or",
    "BitwiseXor": "bitwise_xor", "BitwiseNot": "bitwise_not",
    "Det": "matrix_determinant", "Atan2": "atan2",
    "ReverseSequence": None,  # attr rule below; Mod handled by attr rule too
}
for _onnx_name, _sd_name in _SIMPLE_T3.items():
    if _sd_name is None or _onnx_name in ONNX_OP_RULES:
        continue

    def _mk_t3(sd_name):
        def rule(ctx: _NodeCtx):
            return ctx.importer.sd._op(
                sd_name, *(ctx.var(i) for i in range(len(ctx.inputs))),
                name=ctx.outputs[0])

        return rule

    ONNX_OP_RULES[_onnx_name] = _mk_t3(_sd_name)


ONNX_OP_RULES["ReduceLogSumExp"] = _reduce("logsumexp")


@onnx_rule("ReduceL1", "ReduceL2", "ReduceSumSquare", "ReduceLogSum")
def _reduce_composed(ctx):
    sd = ctx.importer.sd
    axes = ctx.a_ints("axes")
    if axes is None and ctx.has(1):  # opset 18 axes-as-input
        axes = [int(v) for v in ctx.const_value(1).reshape(-1)]
    keep = bool(ctx.a_int("keepdims", 1))
    x = ctx.var(0)
    pre = {"ReduceL1": "abs", "ReduceL2": "square",
           "ReduceSumSquare": "square", "ReduceLogSum": None}[ctx.op]
    if pre:
        x = sd._op(pre, x)
    s = sd._op("reduce_sum", x, axis=None if axes is None else axes,
               keepdims=keep,
               name=ctx.outputs[0] if ctx.op in ("ReduceL1",
                                                 "ReduceSumSquare") else None)
    if ctx.op == "ReduceL2":
        return sd._op("sqrt", s, name=ctx.outputs[0])
    if ctx.op == "ReduceLogSum":
        return sd._op("log", s, name=ctx.outputs[0])
    return s


@onnx_rule("ConvTranspose")
def _conv_transpose(ctx):
    sd = ctx.importer.sd
    if ctx.a_int("group", 1) != 1:
        raise NotImplementedError("grouped ConvTranspose unsupported")
    kernel = ctx.a_ints("kernel_shape")
    if kernel is not None and len(kernel) != 2:
        raise NotImplementedError("ConvTranspose 2D only")
    # ONNX W [C, M, kH, kW] -> our deconv2d forward-kernel [kH, kW, M, C]
    w_name = ctx.inputs[1]
    if w_name in ctx.importer.const_values:
        w_np = ctx.importer.const_values[w_name].transpose(2, 3, 1, 0)
        w = sd.constant(np.ascontiguousarray(w_np))
    else:
        w = sd._op("transpose", ctx.var(1), perm=[2, 3, 1, 0])
    if "output_padding" in ctx.attr or "output_shape" in ctx.attr:
        raise NotImplementedError(
            "ConvTranspose output_padding/output_shape unsupported")
    pads = ctx.a_ints("pads", [0, 0, 0, 0])
    strides_ = ctx.a_ints("strides", [1, 1])
    kern = ctx.a_ints("kernel_shape")
    if not any(pads):
        padding = "VALID"
    else:
        # ONNX out = (in-1)*s + k - total_pad; total_pad == k - s gives
        # out = in*s, exactly lax SAME — anything else has no string form
        tot = [pads[0] + pads[2], pads[1] + pads[3]]
        if kern is not None and all(
                t == k - st for t, k, st in zip(tot, kern, strides_)):
            padding = "SAME"
        else:
            raise NotImplementedError(
                f"ConvTranspose pads={pads} (kernel={kern}, "
                f"strides={strides_}): only VALID (all-zero) or the "
                "SAME-equivalent total pad k-s is supported")
    bias = ctx.var(2) if ctx.has(2) else None
    args = (ctx.var(0), w) if bias is None else (ctx.var(0), w, bias)
    return sd._op("deconv2d", *args, name=ctx.outputs[0],
                  strides=tuple(ctx.a_ints("strides", [1, 1])),
                  padding=padding, data_format="NCHW")


@onnx_rule("Mod")
def _mod_onnx(ctx):
    # fmod=1 (C fmod, REQUIRED for float inputs per spec) vs integer mod
    op = "fmod" if ctx.a_int("fmod", 0) else "mod"
    return ctx.importer.sd._op(op, ctx.var(0), ctx.var(1),
                               name=ctx.outputs[0])


@onnx_rule("InstanceNormalization")
def _instance_norm(ctx):
    return ctx.importer.sd._op(
        "instance_norm", ctx.var(0), ctx.var(1), ctx.var(2),
        name=ctx.outputs[0], eps=ctx.a_float("epsilon", 1e-5))


@onnx_rule("GroupNormalization")
def _group_norm(ctx):
    return ctx.importer.sd._op(
        "group_norm", ctx.var(0), ctx.var(1), ctx.var(2),
        name=ctx.outputs[0], groups=ctx.a_int("num_groups"),
        eps=ctx.a_float("epsilon", 1e-5))


@onnx_rule("LRN")
def _lrn_onnx(ctx):
    # ONNX normalizes over channel dim of NCHW with alpha/size scaling:
    # out = x / (bias + alpha/size * sqr_sum)^beta
    size = ctx.a_int("size")
    if size % 2 == 0:
        raise NotImplementedError(
            f"LRN size={size}: the symmetric window implementation "
            "supports odd sizes only")
    sd = ctx.importer.sd
    # our op normalizes the LAST axis: NCHW -> NHWC -> back
    x = sd._op("transpose", ctx.var(0), perm=[0, 2, 3, 1])
    y = sd._op("local_response_normalization", x, depth=size,
               bias=ctx.a_float("bias", 1.0),
               alpha=ctx.a_float("alpha", 1e-4) / size,
               beta=ctx.a_float("beta", 0.75))
    return sd._op("transpose", y, name=ctx.outputs[0], perm=[0, 3, 1, 2])


@onnx_rule("OneHot")
def _one_hot_onnx(ctx):
    depth = int(ctx.const_value(1))
    values = ctx.const_value(2).reshape(-1)  # [off, on]
    return ctx.importer.sd._op(
        "one_hot", ctx.var(0), name=ctx.outputs[0], depth=depth,
        axis=ctx.a_int("axis", -1), on_value=float(values[1]),
        off_value=float(values[0]))


@onnx_rule("TopK")
def _top_k_onnx(ctx):
    k = int(ctx.const_value(1))
    sd = ctx.importer.sd
    if ctx.a_int("axis", -1) not in (-1,):
        raise NotImplementedError("TopK axis != -1 unsupported")
    if not ctx.a_int("largest", 1):
        raise NotImplementedError("TopK largest=0 unsupported")
    tup = sd._op("top_k", ctx.var(0), k=k)
    vals = sd._op("getitem", tup, item=0, name=ctx.outputs[0])
    if len(ctx.outputs) > 1:
        sd._op("getitem", tup, item=1, name=ctx.outputs[1])
    return vals


@onnx_rule("ScatterND")
def _scatter_nd_onnx(ctx):
    return ctx.importer.sd._op("scatter_nd_update", ctx.var(0), ctx.var(1),
                               ctx.var(2), name=ctx.outputs[0])


@onnx_rule("GatherElements")
def _gather_elements(ctx):
    return ctx.importer.sd._op("take_along_axis", ctx.var(0), ctx.var(1),
                               name=ctx.outputs[0],
                               axis=ctx.a_int("axis", 0))


@onnx_rule("CumSum")
def _cumsum_onnx(ctx):
    return ctx.importer.sd._op(
        "cumsum", ctx.var(0), name=ctx.outputs[0],
        axis=int(ctx.const_value(1)),
        exclusive=bool(ctx.a_int("exclusive", 0)),
        reverse=bool(ctx.a_int("reverse", 0)))


@onnx_rule("Trilu")
def _trilu(ctx):
    k = int(ctx.const_value(1)) if ctx.has(1) else 0
    op = "triu" if ctx.a_int("upper", 1) else "tril"
    return ctx.importer.sd._op(op, ctx.var(0), name=ctx.outputs[0], k=k)


@onnx_rule("SpaceToDepth", "DepthToSpace")
def _space_depth_onnx(ctx):
    op = "space_to_depth" if ctx.op == "SpaceToDepth" else "depth_to_space"
    if ctx.op == "DepthToSpace" and ctx.a_str("mode", "DCR") != "DCR":
        # our NCHW depth_to_space matches ONNX's DCR element order
        raise NotImplementedError("DepthToSpace CRD mode unsupported")
    return ctx.importer.sd._op(op, ctx.var(0), name=ctx.outputs[0],
                               block_size=ctx.a_int("blocksize"),
                               data_format="NCHW")


@onnx_rule("ReverseSequence")
def _reverse_seq_onnx(ctx):
    t_ax = ctx.a_int("time_axis", 0)
    b_ax = ctx.a_int("batch_axis", 1)
    sd = ctx.importer.sd
    if b_ax == 0:
        return sd._op("reverse_sequence", ctx.var(0), ctx.var(1),
                      name=ctx.outputs[0], seq_axis=t_ax, batch_axis=0)
    if (t_ax, b_ax) == (0, 1):
        # spec-default time-major: transpose to batch-major and back
        x = sd._op("swapaxes", ctx.var(0), a=0, b=1)
        y = sd._op("reverse_sequence", x, ctx.var(1), seq_axis=1,
                   batch_axis=0)
        return sd._op("swapaxes", y, name=ctx.outputs[0], a=0, b=1)
    raise NotImplementedError(
        f"ReverseSequence time_axis={t_ax} batch_axis={b_ax} unsupported")


@onnx_rule("MeanVarianceNormalization")
def _mvn(ctx):
    return ctx.importer.sd._op(
        "standardize", ctx.var(0), name=ctx.outputs[0],
        axis=ctx.a_ints("axes", [0, 2, 3]))


def _item(value):
    """Extract a python scalar from a 0-d/1-element ndarray without relying on
    float()/int() of a sized array (deprecated in NumPy >= 1.25). Raises on
    larger tensors so per-axis quantization params fail loudly instead of
    silently collapsing to the first element."""
    arr = np.asarray(value)
    if arr.size != 1:
        raise NotImplementedError(
            f"per-axis quantization params unsupported (got shape "
            f"{arr.shape}); only per-tensor scale/zero_point import")
    return arr.reshape(-1)[0].item()


@onnx_rule("QuantizeLinear")
def _quantize_linear(ctx):
    scale = float(_item(ctx.const_value(1)))
    zp = 0
    signed = False
    if ctx.has(2):
        zp_arr = np.asarray(ctx.const_value(2))
        zp = int(_item(zp_arr))
        signed = np.issubdtype(zp_arr.dtype, np.signedinteger) \
            and zp_arr.dtype != np.int32  # int8 zero point = signed range
    return ctx.importer.sd._op("quantize", ctx.var(0), name=ctx.outputs[0],
                               scale=scale, zero_point=zp, signed=signed)


@onnx_rule("DequantizeLinear")
def _dequantize_linear(ctx):
    scale = float(_item(ctx.const_value(1)))
    zp = int(_item(ctx.const_value(2))) if ctx.has(2) else 0
    return ctx.importer.sd._op("dequantize", ctx.var(0), name=ctx.outputs[0],
                               scale=scale, zero_point=zp)


@onnx_rule("Mean")
def _mean_onnx(ctx):
    return ctx.importer.sd._op(
        "mergeavg", *(ctx.var(i) for i in range(len(ctx.inputs))),
        name=ctx.outputs[0])


@onnx_rule("Shrink")
def _shrink(ctx):
    lambd = ctx.a_float("lambd", 0.5)
    bias = ctx.a_float("bias", 0.0)
    sd = ctx.importer.sd
    if bias == 0.0:
        return sd._op("hardshrink", ctx.var(0), name=ctx.outputs[0],
                      lambd=lambd)
    # general form: x < -lambd -> x + bias; x > lambd -> x - bias; else 0
    x = ctx.var(0)
    neg = sd._op("mul", sd._op("cast", sd._op("lt", x, sd.constant(
        np.asarray(-lambd, np.float32))), dtype="float32"),
        sd._op("add", x, sd.constant(np.asarray(bias, np.float32))))
    pos = sd._op("mul", sd._op("cast", sd._op("gt", x, sd.constant(
        np.asarray(lambd, np.float32))), dtype="float32"),
        sd._op("sub", x, sd.constant(np.asarray(bias, np.float32))))
    return sd._op("add", neg, pos, name=ctx.outputs[0])


class OnnxGraphMapper:
    """Reference spelling: OnnxFrameworkImporter.runImport(model.onnx)."""

    def __init__(self) -> None:
        self.sd = SameDiff.create()
        self.const_values: Dict[str, np.ndarray] = {}
        self._produced: Dict[str, SDVariable] = {}

    # ---- public entry points ---------------------------------------------
    @staticmethod
    def import_model(model_or_path, outputs: Optional[Sequence[str]] = None) -> SameDiff:
        return OnnxGraphMapper().run(model_or_path, outputs)

    importModel = import_model
    run_import = import_model

    def run(self, model_or_path, outputs: Optional[Sequence[str]] = None) -> SameDiff:
        model = self._parse(model_or_path)
        g = model.graph

        init_names = set()
        for t in g.initializer:
            value = tensor_to_numpy(t)
            self.const_values[t.name] = value
            self._produced[t.name] = self.sd.constant(value, name=t.name)
            init_names.add(t.name)

        for vi in g.input:
            if vi.name in init_names:
                continue
            shape, dtype = self._value_info(vi)
            self._produced[vi.name] = self.sd.placeholder(
                vi.name, shape=shape, dtype=dtype)

        needed = self._dependency_closure(g, outputs) if outputs else None
        for node in g.node:
            if needed is not None and not (set(node.output) & needed):
                continue
            self._import_node(node)
        return self.sd

    # ---- internals -------------------------------------------------------
    @staticmethod
    def _parse(model_or_path):
        if isinstance(model_or_path, onnx_pb2.ModelProto):
            return model_or_path
        if isinstance(model_or_path, bytes):
            m = onnx_pb2.ModelProto()
            m.ParseFromString(model_or_path)
            return m
        with open(model_or_path, "rb") as f:
            m = onnx_pb2.ModelProto()
            m.ParseFromString(f.read())
        return m

    @staticmethod
    def _value_info(vi):
        tt = vi.type.tensor_type
        dtype = np.dtype(_ONNX_DTYPES.get(tt.elem_type, np.float32)).name
        shape = tuple(
            (d.dim_value if d.HasField("dim_value") and d.dim_value > 0 else None)
            for d in tt.shape.dim
        ) if tt.HasField("shape") else None
        return shape, dtype

    @staticmethod
    def _dependency_closure(g, outputs: Sequence[str]) -> set:
        producer = {}
        for n in g.node:
            for o in n.output:
                producer[o] = n
        seen: set = set()
        stack = list(outputs)
        while stack:
            name = stack.pop()
            if name in seen or name not in producer:
                continue
            seen.add(name)
            n = producer[name]
            seen.update(n.output)  # a node runs once; all its outputs appear
            stack.extend(i for i in n.input if i)
        return seen

    def resolve(self, ref: str) -> SDVariable:
        return self._produced[ref]

    def _import_node(self, node) -> None:
        op = node.op_type
        attr = {a.name: a for a in node.attribute}
        if op == "Constant":
            if "value" in attr:
                value = tensor_to_numpy(attr["value"].t)
            elif "value_float" in attr:
                value = np.float32(attr["value_float"].f)
            elif "value_int" in attr:
                value = np.int64(attr["value_int"].i)
            elif "value_floats" in attr:
                value = np.asarray(list(attr["value_floats"].floats), np.float32)
            elif "value_ints" in attr:
                value = np.asarray(list(attr["value_ints"].ints), np.int64)
            else:
                raise NotImplementedError("Constant node without tensor value")
            value = np.asarray(value)
            self.const_values[node.output[0]] = value
            self._produced[node.output[0]] = self.sd.constant(value, name=node.output[0])
            return
        rule = ONNX_OP_RULES.get(op)
        if rule is None:
            raise NotImplementedError(
                f"ONNX op {op!r} (node {node.name!r}) has no import rule; "
                f"{len(ONNX_OP_RULES)} ops are mapped"
            )
        ctx = _NodeCtx(
            name=node.name or node.output[0], op=op,
            inputs=list(node.input), outputs=list(node.output),
            attr=attr, importer=self,
        )
        result = rule(ctx)
        if isinstance(result, dict):
            for idx, var in result.items():
                self._produced[node.output[idx]] = var
        else:
            self._produced[node.output[0]] = result
