from .keras import KerasModelImport

__all__ = ["KerasModelImport"]
