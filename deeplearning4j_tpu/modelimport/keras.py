"""Keras HDF5 model import.

Reference: org.deeplearning4j.nn.modelimport.keras.KerasModelImport /
KerasModel / ~60 KerasLayer mappers (SURVEY.md §2.2 "Keras import"):
h5 parsing → config mapping → weight mapping, Sequential →
MultiLayerNetwork and functional → ComputationGraph.

Conventions handled here (the same dance the reference does):
* Keras conv weights are HWIO channels-last; ours are OIHW over NCHW
  activations — kernels transpose at import, and the first Dense after a
  Flatten gets its rows permuted from NHWC-flatten order to our
  channels-first flatten order.
* Keras LSTM gate columns are [i, f, g, o]; ours are [i, f, o, g]
  (reference LSTMParamInitializer order) — columns reorder at import.
* BatchNormalization moving stats land in the model's state pytree.
* Imported CNN models therefore take NCHW input; recurrent models take
  [batch, features, time] (the reference's conventions throughout).

Supports both Keras 2 ("kernel:0") and Keras 3 ("kernel") weight naming.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..nn.activations import Activation
from ..nn.conf import NeuralNetConfiguration
from ..nn.layers import (
    ActivationLayer,
    BatchNormalizationLayer,
    CnnToFeedForwardPreProcessor,
    ConvolutionLayer,
    ConvolutionMode,
    DenseLayer,
    DropoutLayer,
    GlobalPoolingLayer,
    LastTimeStepLayer,
    LSTMLayer,
    PoolingType,
    SubsamplingLayer,
)
from ..nn.sequential import MultiLayerNetwork

_ACTIVATIONS = {
    "linear": Activation.IDENTITY,
    "relu": Activation.RELU,
    "relu6": Activation.RELU6,
    "sigmoid": Activation.SIGMOID,
    "hard_sigmoid": Activation.HARDSIGMOID,
    "tanh": Activation.TANH,
    "softmax": Activation.SOFTMAX,
    "softplus": Activation.SOFTPLUS,
    "softsign": Activation.SOFTSIGN,
    "selu": Activation.SELU,
    "elu": Activation.ELU,
    "gelu": Activation.GELU,
    "swish": Activation.SWISH,
    "silu": Activation.SWISH,
    "mish": Activation.MISH,
    "leaky_relu": Activation.LEAKYRELU,
}

# keras column order [i, f, g, o] → ours [i, f, o, g]
_LSTM_GATE_PERM = (0, 1, 3, 2)


# ---- custom layer registry (reference: KerasLayer.registerCustomLayer +
# KerasLambdaLayer). Custom classes map class_name -> handler(importer,
# conf); Lambda layers map LAYER NAME -> a python callable (Keras
# serializes Lambda bodies as marshalled bytecode, which no importer can
# portably execute — the reference requires pre-registering a
# SameDiffLambdaLayer the same way).
KERAS_CUSTOM_LAYERS: Dict[str, Any] = {}
KERAS_LAMBDAS: Dict[str, Any] = {}


def register_keras_custom_layer(class_name: str, handler=None):
    """Register an import handler for a custom Keras layer class.
    ``handler(importer, conf)`` appends to importer.layers/params.
    Usable as a decorator."""
    def deco(fn):
        KERAS_CUSTOM_LAYERS[class_name] = fn
        return fn

    return deco(handler) if handler is not None else deco


def register_keras_lambda(layer_name: str, fn=None):
    """Register the forward fn for a Keras ``Lambda`` layer by its layer
    NAME (``fn(x) -> array`` or ``fn(sd, x)``, SameDiffLambdaLayer
    contract)."""
    def deco(f):
        KERAS_LAMBDAS[layer_name] = f
        return f

    return deco(fn) if fn is not None else deco


class KerasImportError(ValueError):
    pass


def _map_activation(name: Optional[str]) -> Activation:
    if name is None:
        return Activation.IDENTITY
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise KerasImportError(f"unsupported Keras activation {name!r}") from None


def _collect_weights(group) -> Dict[str, np.ndarray]:
    """Leaf datasets under a layer's weight group, keyed by FULL path with
    any Keras-2 ':0' suffix stripped (wrappers like Bidirectional hold
    same-named leaves for each direction — basenames alone collide)."""
    import h5py

    out: Dict[str, np.ndarray] = {}

    def walk(g, prefix: str):
        for k in g:
            item = g[k]
            key = f"{prefix}/{k}" if prefix else k
            if isinstance(item, h5py.Dataset):
                out[key.split(":")[0]] = np.asarray(item)
            else:
                walk(item, key)

    walk(group, "")
    return out


def _by_basename(weights: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    return {k.rsplit("/", 1)[-1]: v for k, v in weights.items()}


def _lstm_reorder(arr: np.ndarray, units: int) -> np.ndarray:
    """Reorder fused gate columns keras→ours along the last axis."""
    parts = np.split(arr, 4, axis=-1)
    return np.concatenate([parts[p] for p in _LSTM_GATE_PERM], axis=-1)


def _pad_mode(padding: str) -> ConvolutionMode:
    if padding == "same":
        return ConvolutionMode.SAME
    if padding in ("valid", None):
        return ConvolutionMode.TRUNCATE
    raise KerasImportError(f"unsupported padding {padding!r}")


def _conv_out(size: int, k: int, s: int, mode: ConvolutionMode,
              d: int = 1) -> int:
    if mode is ConvolutionMode.SAME:
        return -(-size // s)
    eff_k = (k - 1) * d + 1
    return (size - eff_k) // s + 1


class _Shape:
    """Tracks the activation shape through a sequential stack, in OUR
    conventions (conv: h/w/c over NCHW; rnn: features/timesteps)."""

    def __init__(self, input_shape: Tuple[Optional[int], ...]) -> None:
        # keras input_shape excludes batch: (d, h, w, c), (h, w, c),
        # (t, f) or (n,)
        if len(input_shape) == 4:
            self.kind = "conv3d"
            self.d, self.h, self.w, self.c = input_shape
        elif len(input_shape) == 3:
            self.kind = "conv"
            self.h, self.w, self.c = input_shape
        elif len(input_shape) == 2:
            self.kind = "rnn"
            self.t, self.f = input_shape
        elif len(input_shape) == 1:
            self.kind = "ff"
            self.n = input_shape[0]
        else:
            raise KerasImportError(f"unsupported input rank {input_shape}")


class _SequentialImporter:
    def __init__(self, layer_configs: List[dict], weights_by_layer) -> None:
        self.configs = layer_configs
        self.weights_by_layer = weights_by_layer
        self.layers: List[Any] = []
        self.params: Dict[str, Dict[str, np.ndarray]] = {}
        self.state: Dict[str, Dict[str, np.ndarray]] = {}
        self.shape: Optional[_Shape] = None
        self.dense_perm: Optional[np.ndarray] = None  # post-Flatten fixup

    def _add(self, layer, params=None, state=None):
        self.layers.append(layer)
        name = layer.name or f"layer_{len(self.layers) - 1}"
        if params:
            self.params[name] = params
        if state:
            self.state[name] = state

    def run(self) -> Tuple[List[Any], dict, dict]:
        for cfg in self.configs:
            cls = cfg["class_name"]
            conf = cfg["config"]
            handler = getattr(self, f"_import_{cls}", None)
            if cls == "InputLayer":
                shape = conf.get("batch_shape") or conf.get(
                    "batch_input_shape")
                self.shape = _Shape(tuple(shape[1:]))
                continue
            if self.shape is None and "batch_input_shape" in conf:
                self.shape = _Shape(tuple(conf["batch_input_shape"][1:]))
            # registered custom classes; keras serializes registered
            # classes as "package>ClassName" — accept both spellings
            custom = KERAS_CUSTOM_LAYERS.get(cls) \
                or KERAS_CUSTOM_LAYERS.get(cls.split(">")[-1])
            if handler is None and custom is not None:
                if self.shape is None:
                    raise KerasImportError("no input shape before first layer")
                custom(self, conf)
                continue
            if handler is None:
                raise KerasImportError(
                    f"unsupported Keras layer {cls!r} ({conf.get('name')})")
            if self.shape is None:
                raise KerasImportError("no input shape before first layer")
            handler(conf)
        return self.layers, self.params, self.state

    def _import_Lambda(self, conf):
        from ..nn.layers.samediff_layer import SameDiffLambdaLayer

        name = conf.get("name")
        fn = KERAS_LAMBDAS.get(name)
        if fn is None:
            raise KerasImportError(
                f"Lambda layer {name!r}: Keras serializes Lambda bodies as "
                "marshalled bytecode, which cannot be imported portably — "
                "register the forward with "
                f"register_keras_lambda({name!r}, fn) first "
                "(reference: SameDiffLambdaLayer registration)")
        self._add(SameDiffLambdaLayer(fn=fn, name=name))

    # --- per-class handlers -------------------------------------------

    def _weights(self, conf) -> Dict[str, np.ndarray]:
        return _by_basename(self.weights_by_layer.get(conf["name"], {}))

    def _import_Dense(self, conf):
        s = self.shape
        if s.kind == "conv":
            raise KerasImportError(
                f"Dense on 4D conv output ({conf['name']}) — insert a "
                "Flatten/GlobalPooling in the Keras model first")
        n_in = s.n if s.kind == "ff" else s.f
        w = self._weights(conf)
        kernel = w["kernel"]
        if self.dense_perm is not None:
            kernel = kernel[self.dense_perm]
            self.dense_perm = None
        params = {"W": kernel}
        if conf.get("use_bias", True):
            params["b"] = w["bias"]
        self._add(DenseLayer(
            name=conf["name"], n_in=int(n_in), n_out=int(conf["units"]),
            activation=_map_activation(conf.get("activation")),
            has_bias=conf.get("use_bias", True)), params)
        if s.kind == "rnn":
            s.f = conf["units"]  # TimeDistributed-style dense over features
        else:
            s.kind, s.n = "ff", conf["units"]

    def _import_Conv2D(self, conf):
        s = self.shape
        if s.kind != "conv":
            raise KerasImportError("Conv2D on non-convolutional input")
        if conf.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("only channels_last Keras models supported")
        if conf.get("groups", 1) != 1:
            raise KerasImportError("grouped Conv2D unsupported")
        mode = _pad_mode(conf.get("padding", "valid"))
        kh, kw = conf["kernel_size"]
        sh, sw = conf.get("strides", (1, 1))
        dh, dw = conf.get("dilation_rate", (1, 1))
        w = self._weights(conf)
        params = {"W": w["kernel"].transpose(3, 2, 0, 1)}  # HWIO → OIHW
        if conf.get("use_bias", True):
            params["b"] = w["bias"]
        self._add(ConvolutionLayer(
            name=conf["name"], n_in=int(s.c), n_out=int(conf["filters"]),
            kernel_size=(kh, kw), stride=(sh, sw), dilation=(dh, dw),
            convolution_mode=mode,
            activation=_map_activation(conf.get("activation")),
            has_bias=conf.get("use_bias", True)), params)
        s.h = _conv_out(s.h, kh, sh, mode, dh)
        s.w = _conv_out(s.w, kw, sw, mode, dw)
        s.c = conf["filters"]

    def _pool(self, conf, ptype):
        s = self.shape
        kh, kw = conf.get("pool_size", (2, 2))
        st = conf.get("strides") or (kh, kw)
        mode = _pad_mode(conf.get("padding", "valid"))
        self._add(SubsamplingLayer(
            name=conf["name"], kernel_size=(kh, kw), stride=tuple(st),
            pooling_type=ptype, convolution_mode=mode))
        s.h = _conv_out(s.h, kh, st[0], mode)
        s.w = _conv_out(s.w, kw, st[1], mode)

    def _import_MaxPooling2D(self, conf):
        self._pool(conf, PoolingType.MAX)

    def _import_AveragePooling2D(self, conf):
        self._pool(conf, PoolingType.AVG)

    def _import_GlobalAveragePooling2D(self, conf):
        s = self.shape
        self._add(GlobalPoolingLayer(name=conf["name"],
                                     pooling_type=PoolingType.AVG))
        s.kind, s.n = "ff", s.c

    def _import_GlobalMaxPooling2D(self, conf):
        s = self.shape
        self._add(GlobalPoolingLayer(name=conf["name"],
                                     pooling_type=PoolingType.MAX))
        s.kind, s.n = "ff", s.c

    def _import_GlobalAveragePooling1D(self, conf):
        s = self.shape
        if s.kind != "rnn":
            raise KerasImportError("GlobalAveragePooling1D needs sequence input")
        self._add(GlobalPoolingLayer(name=conf["name"],
                                     pooling_type=PoolingType.AVG))
        s.kind, s.n = "ff", s.f

    def _import_GlobalMaxPooling1D(self, conf):
        s = self.shape
        if s.kind != "rnn":
            raise KerasImportError("GlobalMaxPooling1D needs sequence input")
        self._add(GlobalPoolingLayer(name=conf["name"],
                                     pooling_type=PoolingType.MAX))
        s.kind, s.n = "ff", s.f

    def _import_Flatten(self, conf):
        s = self.shape
        if s.kind == "conv":
            self._add(CnnToFeedForwardPreProcessor(
                name=conf["name"], height=int(s.h), width=int(s.w),
                channels=int(s.c)))
            # keras flattens NHWC (c fastest); ours flattens NCHW (w fastest)
            n = int(s.h * s.w * s.c)
            self.dense_perm = (np.arange(n).reshape(s.h, s.w, s.c)
                               .transpose(2, 0, 1).ravel())
            s.kind, s.n = "ff", n
        elif s.kind == "ff":
            pass  # already flat
        else:
            raise KerasImportError("Flatten on recurrent input unsupported")

    def _import_Dropout(self, conf):
        # keras rate = drop probability; ours = retain probability
        self._add(DropoutLayer(name=conf["name"],
                               dropout=1.0 - float(conf["rate"])))

    def _import_Activation(self, conf):
        self._add(ActivationLayer(
            name=conf["name"],
            activation=_map_activation(conf.get("activation"))))

    def _import_ReLU(self, conf):
        if conf.get("max_value") not in (None, 6.0):
            raise KerasImportError("ReLU max_value other than None/6 "
                                   "unsupported")
        if conf.get("negative_slope") or conf.get("threshold"):
            raise KerasImportError(
                "ReLU negative_slope/threshold unsupported")
        act = Activation.RELU6 if conf.get("max_value") == 6.0 \
            else Activation.RELU
        self._add(ActivationLayer(name=conf["name"], activation=act))

    def _import_BatchNormalization(self, conf):
        s = self.shape
        axis = conf.get("axis")
        if isinstance(axis, list):
            axis = axis[0]
        rank = 4 if s.kind == "conv" else 2
        if axis not in (None, -1, rank - 1):
            raise KerasImportError("only channels-last BatchNormalization "
                                   "supported")
        n = s.c if s.kind == "conv" else (s.f if s.kind == "rnn" else s.n)
        w = self._weights(conf)

        def fix(arr):
            # per-feature params between Flatten and the next Dense are in
            # keras NHWC-flatten order; permute to our NCHW-flatten order
            # (the pending Dense still gets its own row permutation after)
            return arr[self.dense_perm] if self.dense_perm is not None \
                else arr

        params = {}
        if conf.get("scale", True):
            params["gamma"] = fix(w["gamma"])
        if conf.get("center", True):
            params["beta"] = fix(w["beta"])
        state = {"mean": fix(w["moving_mean"]),
                 "var": fix(w["moving_variance"])}
        self._add(BatchNormalizationLayer(
            name=conf["name"], n_out=int(n), eps=float(conf.get(
                "epsilon", 1e-3)), decay=float(conf.get("momentum", 0.99))),
            params, state)

    def _import_Embedding(self, conf):
        s = self.shape
        if s.kind != "ff":
            raise KerasImportError(
                "Embedding expects [batch, time] integer input")
        if conf.get("mask_zero", False):
            # keras skips masked timesteps downstream; importing without
            # the mask would silently change the numerics
            raise KerasImportError(
                "Embedding mask_zero=True unsupported (pass an explicit "
                "mask to output()/fit() instead)")
        from ..nn.layers import EmbeddingSequenceLayer

        w = self._weights(conf)
        self._add(EmbeddingSequenceLayer(
            name=conf["name"], n_in=int(conf["input_dim"]),
            n_out=int(conf["output_dim"])), {"W": w["embeddings"]})
        # [batch, t] ids -> recurrent [batch, output_dim, t]
        timesteps = s.n
        s.kind = "rnn"
        s.t = timesteps
        s.f = int(conf["output_dim"])

    def _import_SeparableConv2D(self, conf):
        s = self.shape
        if s.kind != "conv":
            raise KerasImportError("SeparableConv2D on non-convolutional input")
        if conf.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("only channels_last Keras models supported")
        from ..nn.layers import SeparableConvolution2DLayer

        mode = _pad_mode(conf.get("padding", "valid"))
        kh, kw = conf["kernel_size"]
        sh, sw = conf.get("strides", (1, 1))
        dm = int(conf.get("depth_multiplier", 1))
        w = self._weights(conf)
        params = {
            # keras depthwise [kh, kw, in, mult] == our W layout directly
            "W": w["depthwise_kernel"],
            # keras pointwise [1, 1, in*mult, out] -> our [out, in*mult, 1, 1]
            "pW": w["pointwise_kernel"].transpose(3, 2, 0, 1),
        }
        if conf.get("use_bias", True):
            params["b"] = w["bias"]
        self._add(SeparableConvolution2DLayer(
            name=conf["name"], n_in=int(s.c), n_out=int(conf["filters"]),
            depth_multiplier=dm, kernel_size=(kh, kw), stride=(sh, sw),
            convolution_mode=mode,
            activation=_map_activation(conf.get("activation")),
            has_bias=conf.get("use_bias", True)), params)
        s.h = _conv_out(s.h, kh, sh, mode)
        s.w = _conv_out(s.w, kw, sw, mode)
        s.c = conf["filters"]

    def _import_Bidirectional(self, conf):
        s = self.shape
        if s.kind != "rnn":
            raise KerasImportError("Bidirectional needs sequence input")
        inner = conf["layer"]
        if inner["class_name"] != "LSTM":
            raise KerasImportError(
                f"Bidirectional({inner['class_name']}) unsupported (LSTM only)")
        icfg = inner["config"]
        if not icfg.get("return_sequences", False):
            # keras's backward half would return its LAST state (original
            # position 0); our LastTimeStep extraction reads position T-1 —
            # semantically different, so reject rather than silently differ
            raise KerasImportError(
                "Bidirectional with return_sequences=False unsupported "
                "(re-export with return_sequences=True + pooling)")
        from ..nn.layers import BidirectionalLayer, BidirectionalMode, LSTMLayer

        mode = {
            "concat": BidirectionalMode.CONCAT, "sum": BidirectionalMode.ADD,
            "mul": BidirectionalMode.MUL, "ave": BidirectionalMode.AVERAGE,
        }.get(conf.get("merge_mode", "concat"))
        if mode is None:
            raise KerasImportError(
                f"Bidirectional merge_mode {conf.get('merge_mode')!r} unsupported")
        units = int(icfg["units"])
        full = self.weights_by_layer.get(conf["name"], {})

        def side(tag: str) -> Dict[str, np.ndarray]:
            got = {}
            for path, arr in full.items():
                if f"{tag}_" not in path and not path.startswith(tag):
                    continue
                base = path.rsplit("/", 1)[-1].split(":")[0]
                got[base] = arr
            if "kernel" not in got:
                raise KerasImportError(
                    f"Bidirectional {conf['name']}: no {tag} weights found")
            return got

        params = {}
        for prefix, tag in (("f", "forward"), ("b", "backward")):
            w = side(tag)
            params[f"{prefix}_W"] = _lstm_reorder(w["kernel"], units)
            params[f"{prefix}_RW"] = _lstm_reorder(w["recurrent_kernel"], units)
            if icfg.get("use_bias", True):
                params[f"{prefix}_b"] = _lstm_reorder(w["bias"], units)
        self._add(BidirectionalLayer(
            name=conf["name"], mode=mode,
            fwd=LSTMLayer(n_in=int(s.f), n_out=units)), params)
        s.f = units * 2 if mode is BidirectionalMode.CONCAT else units

    def _import_LSTM(self, conf):
        s = self.shape
        if s.kind != "rnn":
            raise KerasImportError("LSTM needs sequence input")
        if conf.get("activation", "tanh") != "tanh" or conf.get(
                "recurrent_activation", "sigmoid") != "sigmoid":
            raise KerasImportError("non-default LSTM activations unsupported")
        if conf.get("go_backwards", False):
            raise KerasImportError("LSTM go_backwards unsupported")
        units = int(conf["units"])
        w = self._weights(conf)
        params = {
            "W": _lstm_reorder(w["kernel"], units),
            "RW": _lstm_reorder(w["recurrent_kernel"], units),
        }
        if conf.get("use_bias", True):
            params["b"] = _lstm_reorder(w["bias"], units)
        self._add(LSTMLayer(name=conf["name"], n_in=int(s.f), n_out=units),
                  params)
        s.f = units
        if not conf.get("return_sequences", False):
            self._add(LastTimeStepLayer(name=conf["name"] + "_last"))
            s.kind, s.n = "ff", units

    def _import_GRU(self, conf):
        s = self.shape
        if s.kind != "rnn":
            raise KerasImportError("GRU needs sequence input")
        if conf.get("activation", "tanh") != "tanh" or conf.get(
                "recurrent_activation", "sigmoid") != "sigmoid":
            raise KerasImportError("non-default GRU activations unsupported")
        if conf.get("go_backwards", False):
            raise KerasImportError("GRU go_backwards unsupported")
        from ..nn.layers import GRULayer

        units = int(conf["units"])
        reset_after = bool(conf.get("reset_after", True))
        w = self._weights(conf)
        # keras GRU fused columns are already [z, r, h~] — our storage order
        params = {"W": w["kernel"], "RW": w["recurrent_kernel"]}
        if conf.get("use_bias", True):
            bias = w["bias"]
            if reset_after and bias.ndim == 1:
                bias = bias.reshape(2, -1)
            params["b"] = bias
        else:
            params["b"] = np.zeros(
                (2, 3 * units) if reset_after else (3 * units,), np.float32)
        self._add(GRULayer(name=conf["name"], n_in=int(s.f), n_out=units,
                           reset_after=reset_after), params)
        s.f = units
        if not conf.get("return_sequences", False):
            self._add(LastTimeStepLayer(name=conf["name"] + "_last"))
            s.kind, s.n = "ff", units

    def _import_SimpleRNN(self, conf):
        s = self.shape
        if s.kind != "rnn":
            raise KerasImportError("SimpleRNN needs sequence input")
        if conf.get("go_backwards", False):
            raise KerasImportError("SimpleRNN go_backwards unsupported")
        from ..nn.layers import SimpleRnnLayer

        units = int(conf["units"])
        w = self._weights(conf)
        params = {"W": w["kernel"], "RW": w["recurrent_kernel"]}
        params["b"] = w["bias"] if conf.get("use_bias", True) \
            else np.zeros((units,), np.float32)
        self._add(SimpleRnnLayer(
            name=conf["name"], n_in=int(s.f), n_out=units,
            activation=_map_activation(conf.get("activation", "tanh"))),
            params)
        s.f = units
        if not conf.get("return_sequences", False):
            self._add(LastTimeStepLayer(name=conf["name"] + "_last"))
            s.kind, s.n = "ff", units

    def _import_Conv1D(self, conf):
        s = self.shape
        if s.kind != "rnn":
            raise KerasImportError(
                "Conv1D expects sequence input [batch, steps, features]")
        if conf.get("padding") == "causal":
            raise KerasImportError("causal Conv1D unsupported")
        if conf.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("only channels_last Keras models supported")
        from ..nn.layers import Convolution1DLayer

        mode = _pad_mode(conf.get("padding", "valid"))
        (k,) = conf["kernel_size"] if isinstance(
            conf["kernel_size"], (list, tuple)) else (conf["kernel_size"],)
        (st,) = conf.get("strides", (1,)) if isinstance(
            conf.get("strides", (1,)), (list, tuple)) else (conf["strides"],)
        (dil,) = conf.get("dilation_rate", (1,)) if isinstance(
            conf.get("dilation_rate", (1,)), (list, tuple)) \
            else (conf["dilation_rate"],)
        w = self._weights(conf)
        # keras [k, in, out] -> ours [out, in, k]
        params = {"W": w["kernel"].transpose(2, 1, 0)}
        if conf.get("use_bias", True):
            params["b"] = w["bias"]
        self._add(Convolution1DLayer(
            name=conf["name"], n_in=int(s.f), n_out=int(conf["filters"]),
            kernel_size=int(k), stride=int(st), dilation=int(dil),
            convolution_mode=mode,
            activation=_map_activation(conf.get("activation")),
            has_bias=conf.get("use_bias", True)), params)
        if s.t is not None:
            s.t = _conv_out(s.t, int(k), int(st), mode, int(dil))
        s.f = int(conf["filters"])

    def _import_DepthwiseConv2D(self, conf):
        s = self.shape
        if s.kind != "conv":
            raise KerasImportError("DepthwiseConv2D on non-convolutional input")
        if conf.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("only channels_last Keras models supported")
        from ..nn.layers import DepthwiseConvolution2DLayer

        mode = _pad_mode(conf.get("padding", "valid"))
        kh, kw = conf["kernel_size"]
        sh, sw = conf.get("strides", (1, 1))
        dh, dw = conf.get("dilation_rate", (1, 1))
        dm = int(conf.get("depth_multiplier", 1))
        w = self._weights(conf)
        # keras depthwise [kh, kw, in, mult] == our W layout directly
        # (keras 2 names it depthwise_kernel; keras 3 just kernel)
        params = {"W": w.get("depthwise_kernel", w.get("kernel"))}
        if conf.get("use_bias", True):
            params["b"] = w["bias"]
        self._add(DepthwiseConvolution2DLayer(
            name=conf["name"], n_in=int(s.c), n_out=int(s.c) * dm,
            depth_multiplier=dm, kernel_size=(kh, kw), stride=(sh, sw),
            dilation=(dh, dw), convolution_mode=mode,
            activation=_map_activation(conf.get("activation")),
            has_bias=conf.get("use_bias", True)), params)
        s.h = _conv_out(s.h, kh, sh, mode, dh)
        s.w = _conv_out(s.w, kw, sw, mode, dw)
        s.c = int(s.c) * dm

    def _import_TimeDistributed(self, conf):
        s = self.shape
        if s.kind != "rnn":
            raise KerasImportError("TimeDistributed needs sequence input")
        inner = conf["layer"]
        if inner["class_name"] != "Dense":
            raise KerasImportError(
                f"TimeDistributed({inner['class_name']}) unsupported "
                "(Dense only — the reference wrapper covers the same case)")
        from ..nn.layers import TimeDistributedLayer

        icfg = inner["config"]
        units = int(icfg["units"])
        w = self._weights(conf)
        params = {"W": w["kernel"]}
        if icfg.get("use_bias", True):
            params["b"] = w["bias"]
        self._add(TimeDistributedLayer(
            name=conf["name"],
            underlying=DenseLayer(
                n_in=int(s.f), n_out=units,
                activation=_map_activation(icfg.get("activation")),
                has_bias=icfg.get("use_bias", True))), params)
        s.f = units

    def _import_ZeroPadding2D(self, conf):
        s = self.shape
        if s.kind != "conv":
            raise KerasImportError("ZeroPadding2D on non-convolutional input")
        if conf.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("only channels_last Keras models supported")
        from ..nn.layers import ZeroPaddingLayer

        pad = conf.get("padding", (1, 1))
        if isinstance(pad, int):
            t = b = l = r = pad
        else:
            ph, pw = pad
            t, b = (ph, ph) if isinstance(ph, int) else ph
            l, r = (pw, pw) if isinstance(pw, int) else pw
        self._add(ZeroPaddingLayer(name=conf["name"],
                                   padding=(int(t), int(b), int(l), int(r))))
        s.h = s.h + t + b
        s.w = s.w + l + r

    def _import_UpSampling2D(self, conf):
        s = self.shape
        if s.kind != "conv":
            raise KerasImportError("UpSampling2D on non-convolutional input")
        if conf.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("only channels_last Keras models supported")
        if conf.get("interpolation", "nearest") != "nearest":
            raise KerasImportError(
                "only nearest-neighbor UpSampling2D supported")
        from ..nn.layers import Upsampling2DLayer

        sh, sw = conf.get("size", (2, 2))
        self._add(Upsampling2DLayer(name=conf["name"],
                                    size=(int(sh), int(sw))))
        s.h, s.w = s.h * int(sh), s.w * int(sw)

    def _import_LeakyReLU(self, conf):
        # keras 2 spells it alpha (default 0.3); keras 3 negative_slope
        alpha = conf.get("negative_slope", conf.get("alpha", 0.3))
        self._add(ActivationLayer(name=conf["name"],
                                  activation=Activation.LEAKYRELU,
                                  alpha=float(alpha)))

    def _import_ELU(self, conf):
        self._add(ActivationLayer(name=conf["name"],
                                  activation=Activation.ELU,
                                  alpha=float(conf.get("alpha", 1.0))))

    def _import_Cropping2D(self, conf):
        s = self.shape
        if s.kind != "conv":
            raise KerasImportError("Cropping2D on non-convolutional input")
        if conf.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("only channels_last Keras models supported")
        from ..nn.layers import Cropping2DLayer

        crop = conf.get("cropping", ((0, 0), (0, 0)))
        if isinstance(crop, int):
            t = b = l = r = crop
        else:
            ch, cw = crop
            t, b = (ch, ch) if isinstance(ch, int) else ch
            l, r = (cw, cw) if isinstance(cw, int) else cw
        self._add(Cropping2DLayer(name=conf["name"],
                                  crop=(int(t), int(b), int(l), int(r))))
        s.h = s.h - t - b
        s.w = s.w - l - r

    def _import_Conv2DTranspose(self, conf):
        s = self.shape
        if s.kind != "conv":
            raise KerasImportError(
                "Conv2DTranspose on non-convolutional input")
        if conf.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("only channels_last Keras models supported")
        if conf.get("output_padding") not in (None, (0, 0), [0, 0]):
            raise KerasImportError("Conv2DTranspose output_padding "
                                   "unsupported")
        if tuple(conf.get("dilation_rate", (1, 1))) != (1, 1):
            raise KerasImportError("dilated Conv2DTranspose unsupported")
        from ..nn.layers import Deconvolution2DLayer

        mode = _pad_mode(conf.get("padding", "valid"))
        kh, kw = conf["kernel_size"]
        sh, sw = conf.get("strides", (1, 1))
        w = self._weights(conf)
        # keras [kh, kw, out, in] -> ours [in, out, kh, kw]
        params = {"W": w["kernel"].transpose(3, 2, 0, 1)}
        if conf.get("use_bias", True):
            params["b"] = w["bias"]
        self._add(Deconvolution2DLayer(
            name=conf["name"], n_in=int(s.c), n_out=int(conf["filters"]),
            kernel_size=(kh, kw), stride=(sh, sw), convolution_mode=mode,
            activation=_map_activation(conf.get("activation")),
            has_bias=conf.get("use_bias", True)), params)
        if mode is ConvolutionMode.SAME:
            s.h, s.w = s.h * sh, s.w * sw
        else:
            s.h = (s.h - 1) * sh + kh
            s.w = (s.w - 1) * sw + kw
        s.c = conf["filters"]

    def _import_LayerNormalization(self, conf):
        s = self.shape
        if s.kind not in ("rnn", "ff"):
            raise KerasImportError(
                "LayerNormalization supported on sequence/flat inputs only")
        if conf.get("rms_scaling", False):
            raise KerasImportError(
                "LayerNormalization rms_scaling=True (RMSNorm) unsupported")
        axis = conf.get("axis", -1)
        if isinstance(axis, list):
            axis = axis[0] if len(axis) == 1 else None
        rank = 3 if s.kind == "rnn" else 2
        if axis not in (-1, rank - 1):
            raise KerasImportError(
                "only last-axis LayerNormalization supported")
        from ..nn.layers import LayerNormLayer

        n = int(s.f if s.kind == "rnn" else s.n)
        w = self._weights(conf)
        params = {}
        params["gamma"] = w["gamma"] if conf.get("scale", True) \
            else np.ones((n,), np.float32)
        params["beta"] = w["beta"] if conf.get("center", True) \
            else np.zeros((n,), np.float32)
        self._add(LayerNormLayer(
            name=conf["name"], n_out=n,
            eps=float(conf.get("epsilon", 1e-3))), params)

    def _pool1d(self, conf, ptype):
        s = self.shape
        if s.kind != "rnn":
            raise KerasImportError("1D pooling needs sequence input")
        from ..nn.layers import Subsampling1DLayer

        (k,) = conf.get("pool_size", (2,)) if isinstance(
            conf.get("pool_size", (2,)), (list, tuple)) \
            else (conf["pool_size"],)
        st = conf.get("strides")
        if st is None:
            st = k
        elif isinstance(st, (list, tuple)):
            (st,) = st
        mode = _pad_mode(conf.get("padding", "valid"))
        self._add(Subsampling1DLayer(
            name=conf["name"], pooling_type=ptype, kernel_size=int(k),
            stride=int(st), convolution_mode=mode))
        if s.t is not None:
            s.t = _conv_out(s.t, int(k), int(st), mode)

    def _import_MaxPooling1D(self, conf):
        self._pool1d(conf, PoolingType.MAX)

    def _import_AveragePooling1D(self, conf):
        self._pool1d(conf, PoolingType.AVG)

    def _import_Conv3D(self, conf):
        s = self.shape
        if s.kind != "conv3d":
            raise KerasImportError(
                "Conv3D expects [batch, d, h, w, c] input")
        if conf.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("only channels_last Keras models supported")
        if conf.get("groups", 1) != 1:
            raise KerasImportError("grouped Conv3D unsupported")
        from ..nn.layers import Convolution3DLayer

        mode = _pad_mode(conf.get("padding", "valid"))
        kd, kh, kw = conf["kernel_size"]
        sd_, sh, sw = conf.get("strides", (1, 1, 1))
        dd, dh, dw = conf.get("dilation_rate", (1, 1, 1))
        w = self._weights(conf)
        # keras [kd, kh, kw, in, out] -> ours [out, in, kd, kh, kw]
        params = {"W": w["kernel"].transpose(4, 3, 0, 1, 2)}
        if conf.get("use_bias", True):
            params["b"] = w["bias"]
        self._add(Convolution3DLayer(
            name=conf["name"], n_in=int(s.c), n_out=int(conf["filters"]),
            kernel_size=(kd, kh, kw), stride=(sd_, sh, sw),
            dilation=(dd, dh, dw), convolution_mode=mode,
            activation=_map_activation(conf.get("activation")),
            has_bias=conf.get("use_bias", True)), params)
        s.d = _conv_out(s.d, kd, sd_, mode, dd)
        s.h = _conv_out(s.h, kh, sh, mode, dh)
        s.w = _conv_out(s.w, kw, sw, mode, dw)
        s.c = conf["filters"]

    def _import_GlobalAveragePooling3D(self, conf):
        s = self.shape
        if s.kind != "conv3d":
            raise KerasImportError("GlobalAveragePooling3D needs 5D input")
        self._add(GlobalPoolingLayer(name=conf["name"],
                                     pooling_type=PoolingType.AVG))
        s.kind, s.n = "ff", s.c

    def _import_GlobalMaxPooling3D(self, conf):
        s = self.shape
        if s.kind != "conv3d":
            raise KerasImportError("GlobalMaxPooling3D needs 5D input")
        self._add(GlobalPoolingLayer(name=conf["name"],
                                     pooling_type=PoolingType.MAX))
        s.kind, s.n = "ff", s.c

    def _import_PReLU(self, conf):
        s = self.shape
        from ..nn.layers import PReLULayer

        w = self._weights(conf)
        alpha = w["alpha"]
        shared = conf.get("shared_axes") or ()
        if isinstance(shared, int):
            shared = (shared,)
        if s.kind == "conv":
            # keras alpha is NHWC-shaped [h, w, c] (dims possibly 1 where
            # shared); ours is NCHW-shaped [c, h, w]
            alpha = np.transpose(alpha, (2, 0, 1))
            shape = (int(s.c), int(s.h), int(s.w))
            ax_map = {1: 2, 2: 3, 3: 1}  # keras axis -> our axis (1-indexed)
            shared_ours = tuple(sorted(ax_map[a] for a in shared))
            shape = tuple(1 if (i + 1) in shared_ours else d
                          for i, d in enumerate(shape))
        elif s.kind == "ff":
            shape = (int(s.n),)
            shared_ours = ()
            if shared:
                raise KerasImportError(
                    "PReLU shared_axes on flat input unsupported")
        else:
            raise KerasImportError("PReLU on sequence input unsupported")
        if tuple(alpha.shape) != shape:
            raise KerasImportError(
                f"PReLU alpha shape {alpha.shape} != expected {shape}")
        full_shape = (int(s.c), int(s.h), int(s.w)) if s.kind == "conv" \
            else (int(s.n),)
        self._add(PReLULayer(name=conf["name"], input_shape=full_shape,
                             shared_axes=shared_ours), {"W": alpha})


def _inbound_names(layer_cfg: dict) -> List[str]:
    """Producer layer names feeding this functional-API layer — handles the
    Keras 2 nested-list node format and the Keras 3 keras_history format."""
    nodes = layer_cfg.get("inbound_nodes") or []
    if not nodes:
        return []
    node = nodes[0]
    names: List[str] = []
    if isinstance(node, dict):  # keras 3
        def walk(o):
            if isinstance(o, dict):
                if o.get("class_name") == "__keras_tensor__":
                    names.append(o["config"]["keras_history"][0])
                else:
                    for v in o.values():
                        walk(v)
            elif isinstance(o, (list, tuple)):
                for v in o:
                    walk(v)

        walk(node.get("args", []))
        walk(node.get("kwargs", {}))
    else:  # keras 2: [["name", node_idx, tensor_idx, kwargs], ...]
        for entry in node:
            names.append(entry[0])
    return names


_MERGE_CLASSES = ("Add", "Subtract", "Multiply", "Average", "Maximum",
                  "Concatenate")


class _FunctionalImporter(_SequentialImporter):
    """Functional Keras model -> ComputationGraph specs. Reuses every
    per-class handler from the Sequential importer: before each node the
    current tensor shape is staged into ``self.shape``, and ``_add`` is
    redirected to record graph vertices with explicit inbound edges
    (reference: KerasModel.getComputationGraphConfiguration)."""

    def __init__(self, layer_configs, weights_by_layer) -> None:
        super().__init__(layer_configs, weights_by_layer)
        import copy as _copy

        self._copy = _copy
        self.specs: List[Tuple[str, str, Any, List[str]]] = []
        self.shapes: Dict[str, _Shape] = {}
        self.perms: Dict[str, Optional[np.ndarray]] = {}
        self.graph_inputs: List[str] = []
        self.alias: Dict[str, str] = {}  # keras layer name -> final vertex
        self._current_inputs: List[str] = []
        self._last_added: Optional[str] = None

    def _add(self, layer, params=None, state=None):
        name = layer.name or f"vertex_{len(self.specs)}"
        self.specs.append(("layer", name, layer, list(self._current_inputs)))
        if params:
            self.params[name] = params
        if state:
            self.state[name] = state
        self._current_inputs = [name]  # chained _adds stack onto this node
        self._last_added = name

    def run_graph(self):
        from ..nn.vertices import ElementWiseOp, ElementWiseVertex, MergeVertex

        for cfg in self.configs:
            cls = cfg["class_name"]
            conf = cfg["config"]
            name = conf["name"]
            inbound = [self.alias.get(n, n) for n in _inbound_names(cfg)]
            if cls == "InputLayer":
                shape = conf.get("batch_shape") or conf.get("batch_input_shape")
                self.shapes[name] = _Shape(tuple(shape[1:]))
                self.perms[name] = None
                self.graph_inputs.append(name)
                continue
            if cls in _MERGE_CLASSES:
                if cls == "Concatenate":
                    axis = conf.get("axis", -1)
                    # MergeVertex concatenates the feature/channel axis only;
                    # keras spells that axis differently per input rank
                    kind = self.shapes[inbound[0]].kind
                    chan_axes = {"conv": (-1, 3), "rnn": (-1, 2),
                                 "ff": (-1, 1)}[kind]
                    if axis not in chan_axes:
                        raise KerasImportError(
                            f"Concatenate axis {axis} on {kind} input "
                            f"unsupported (channel/feature axis only: "
                            f"{chan_axes})")
                    vertex = MergeVertex()
                    out_shape = self._copy.copy(self.shapes[inbound[0]])
                    sizes = [self._feat(self.shapes[i]) for i in inbound]
                    self._set_feat(out_shape, sum(sizes))
                else:
                    op = {"Add": ElementWiseOp.ADD,
                          "Subtract": ElementWiseOp.SUBTRACT,
                          "Multiply": ElementWiseOp.PRODUCT,
                          "Average": ElementWiseOp.AVERAGE,
                          "Maximum": ElementWiseOp.MAX}[cls]
                    vertex = ElementWiseVertex(op=op)
                    out_shape = self._copy.copy(self.shapes[inbound[0]])
                self.specs.append(("vertex", name, vertex, inbound))
                self.shapes[name] = out_shape
                self.perms[name] = None
                continue
            handler = getattr(self, f"_import_{cls}", None)
            if handler is None:
                raise KerasImportError(
                    f"unsupported Keras layer {cls!r} ({name})")
            if len(inbound) != 1:
                raise KerasImportError(
                    f"{cls} ({name}): expected exactly one inbound tensor")
            self.shape = self._copy.copy(self.shapes[inbound[0]])
            self.dense_perm = self.perms.get(inbound[0])
            self._current_inputs = [inbound[0]]
            self._last_added = None
            handler(conf)
            if self._last_added is None:
                # no-op handlers (Flatten on already-flat input): the keras
                # tensor aliases straight to its producer
                self.alias[name] = inbound[0]
                self.shapes[name] = self.shape
                self.perms[name] = self.dense_perm
                continue
            self.alias[name] = self._last_added
            self.shapes[name] = self.shape
            self.shapes[self._last_added] = self.shape
            self.perms[name] = self.perms[self._last_added] = self.dense_perm
        return self

    @staticmethod
    def _feat(s: _Shape) -> int:
        return s.c if s.kind == "conv" else (s.f if s.kind == "rnn" else s.n)

    @staticmethod
    def _set_feat(s: _Shape, v: int) -> None:
        if s.kind == "conv":
            s.c = v
        elif s.kind == "rnn":
            s.f = v
        else:
            s.n = v


def _load_params(model, params, state) -> None:
    """Copy imported arrays into an initialized model, shape-checked."""
    dtype = model.dtype
    for lname, lparams in params.items():
        if lname not in model.params:
            raise KerasImportError(f"internal: no params slot {lname}")
        for pname, arr in lparams.items():
            have = model.params[lname][pname]
            if tuple(have.shape) != tuple(arr.shape):
                raise KerasImportError(
                    f"shape mismatch for {lname}/{pname}: "
                    f"{arr.shape} vs {have.shape}")
            model.params[lname][pname] = np.asarray(arr, dtype)
    for lname, lstate in state.items():
        for sname, arr in lstate.items():
            model.state[lname][sname] = np.asarray(arr, dtype)


class KerasModelImport:
    """Reference API: KerasModelImport.importKerasModelAndWeights()."""

    @staticmethod
    def import_keras_sequential_model_and_weights(
            path: str) -> MultiLayerNetwork:
        model = KerasModelImport.import_keras_model_and_weights(path)
        if not isinstance(model, MultiLayerNetwork):
            raise KerasImportError("model is not Sequential")
        return model

    @staticmethod
    def import_keras_model_and_weights(path: str):
        import h5py

        with h5py.File(path, "r") as f:
            if "model_config" not in f.attrs:
                raise KerasImportError(
                    "no model_config attribute — not a Keras h5 model file")
            raw = f.attrs["model_config"]
            if isinstance(raw, bytes):
                raw = raw.decode()
            cfg = json.loads(raw)
            weights_by_layer: Dict[str, Dict[str, np.ndarray]] = {}
            wg = f["model_weights"] if "model_weights" in f else f
            for lname in wg:
                weights_by_layer[lname] = _collect_weights(wg[lname])

        if cfg["class_name"] in ("Functional", "Model"):
            return KerasModelImport._import_functional(cfg, weights_by_layer)
        if cfg["class_name"] != "Sequential":
            raise KerasImportError(
                f"unsupported model class {cfg['class_name']!r}")
        layer_cfgs = cfg["config"]["layers"]
        importer = _SequentialImporter(layer_cfgs, weights_by_layer)
        layers, params, state = importer.run()

        # As in the reference importer: a trailing Dense becomes an
        # OutputLayer with a matching loss, so the imported model is
        # directly trainable (fit/score). Forward behavior is identical.
        if layers and isinstance(layers[-1], DenseLayer):
            from ..nn.layers import OutputLayer
            from ..nn.losses import LossFunction

            last = layers[-1]
            act = last.activation or Activation.IDENTITY
            loss = {Activation.SOFTMAX: LossFunction.MCXENT,
                    Activation.SIGMOID: LossFunction.XENT}.get(
                        act, LossFunction.MSE)
            layers[-1] = OutputLayer(
                name=last.name, n_in=last.n_in, n_out=last.n_out,
                activation=act, has_bias=last.has_bias, loss=loss)

        lb = NeuralNetConfiguration.builder().list()
        for layer in layers:
            lb.layer(layer)
        model = MultiLayerNetwork(lb.build()).init()
        _load_params(model, params, state)
        return model

    @staticmethod
    def _import_functional(cfg: dict, weights_by_layer):
        """Functional model -> ComputationGraph (reference: KerasModel ->
        ComputationGraphConfiguration for non-Sequential models)."""
        from ..nn.graph import ComputationGraph
        from ..nn.layers import OutputLayer
        from ..nn.losses import LossFunction

        imp = _FunctionalImporter(cfg["config"]["layers"], weights_by_layer)
        imp.run_graph()

        out_refs = cfg["config"]["output_layers"]
        # single-output models serialize as a flat ["name", 0, 0]
        if out_refs and isinstance(out_refs[0], str):
            out_refs = [out_refs]
        raw_names = [
            r["config"]["keras_history"][0] if isinstance(r, dict) else r[0]
            for r in out_refs
        ]
        out_names = [imp.alias.get(n, n) for n in raw_names]

        # trailing Dense outputs become OutputLayers (directly trainable),
        # exactly as the Sequential path does
        specs = []
        for kind, name, obj, inputs in imp.specs:
            if kind == "layer" and name in out_names and isinstance(obj, DenseLayer):
                act = obj.activation or Activation.IDENTITY
                loss = {Activation.SOFTMAX: LossFunction.MCXENT,
                        Activation.SIGMOID: LossFunction.XENT}.get(
                            act, LossFunction.MSE)
                obj = OutputLayer(name=obj.name, n_in=obj.n_in, n_out=obj.n_out,
                                  activation=act, has_bias=obj.has_bias,
                                  loss=loss)
            specs.append((kind, name, obj, inputs))

        g = NeuralNetConfiguration.builder().graph_builder()
        g.add_inputs(*imp.graph_inputs)
        for kind, name, obj, inputs in specs:
            if kind == "layer":
                g.add_layer(name, obj, *inputs)
            else:
                g.add_vertex(name, obj, *inputs)
        g.set_outputs(*out_names)
        model = ComputationGraph(g.build()).init()
        _load_params(model, imp.params, imp.state)
        return model
