"""Input types — shape inference through the network config.

Parity with the reference's ``org.deeplearning4j.nn.conf.inputs.InputType``
(canonical: deeplearning4j-nn): ``setInputType`` on the config builder walks
layers, auto-computes each layer's nIn, and inserts preprocessors at
format-change boundaries. Same machinery here, as pure data.

Data formats (reference defaults preserved at the API boundary):
* feed-forward: [batch, size]
* recurrent:    [batch, size, time]  (NCW)
* CNN 2D:       [batch, channels, height, width]  (NCHW)
* CNN 3D:       [batch, channels, depth, height, width] (NCDHW)
XLA re-lays-out internally for the TPU; the declared format only fixes the
user-facing axis order.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from ..core.config import register_config


@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str = "feed_forward"  # feed_forward | recurrent | convolutional | convolutional3d | convolutional_flat

    @staticmethod
    def feed_forward(size: int) -> "FeedForwardType":
        return FeedForwardType(size=int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "RecurrentType":
        return RecurrentType(size=int(size), timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "ConvolutionalType":
        return ConvolutionalType(height=int(height), width=int(width), channels=int(channels))

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int, channels: int) -> "Convolutional3DType":
        return Convolutional3DType(
            depth=int(depth), height=int(height), width=int(width), channels=int(channels)
        )

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "ConvolutionalFlatType":
        return ConvolutionalFlatType(height=int(height), width=int(width), channels=int(channels))

    def flat_size(self) -> int:
        raise NotImplementedError

    def shape(self, batch: int = -1) -> Tuple[int, ...]:
        raise NotImplementedError


@register_config
@dataclasses.dataclass(frozen=True)
class FeedForwardType(InputType):
    kind: str = "feed_forward"
    size: int = 0

    def flat_size(self) -> int:
        return self.size

    def shape(self, batch: int = -1) -> Tuple[int, ...]:
        return (batch, self.size)


@register_config
@dataclasses.dataclass(frozen=True)
class RecurrentType(InputType):
    kind: str = "recurrent"
    size: int = 0
    timesteps: Optional[int] = None

    def flat_size(self) -> int:
        if self.timesteps is None:
            raise ValueError("Recurrent input with unknown timesteps has no flat size")
        return self.size * self.timesteps

    def shape(self, batch: int = -1) -> Tuple[int, ...]:
        return (batch, self.size, self.timesteps or -1)


@register_config
@dataclasses.dataclass(frozen=True)
class ConvolutionalType(InputType):
    kind: str = "convolutional"
    height: int = 0
    width: int = 0
    channels: int = 0

    def flat_size(self) -> int:
        return self.height * self.width * self.channels

    def shape(self, batch: int = -1) -> Tuple[int, ...]:
        return (batch, self.channels, self.height, self.width)


@register_config
@dataclasses.dataclass(frozen=True)
class Convolutional3DType(InputType):
    kind: str = "convolutional3d"
    depth: int = 0
    height: int = 0
    width: int = 0
    channels: int = 0

    def flat_size(self) -> int:
        return self.depth * self.height * self.width * self.channels

    def shape(self, batch: int = -1) -> Tuple[int, ...]:
        return (batch, self.channels, self.depth, self.height, self.width)


@register_config
@dataclasses.dataclass(frozen=True)
class ConvolutionalFlatType(InputType):
    """Flattened image input (e.g. MNIST as [batch, 784]) that conv layers
    should interpret as [batch, c, h, w] — reference inserts a
    FeedForwardToCnnPreProcessor for this case."""

    kind: str = "convolutional_flat"
    height: int = 0
    width: int = 0
    channels: int = 0

    def flat_size(self) -> int:
        return self.height * self.width * self.channels

    def shape(self, batch: int = -1) -> Tuple[int, ...]:
        return (batch, self.flat_size())

    def as_convolutional(self) -> ConvolutionalType:
        return ConvolutionalType(height=self.height, width=self.width, channels=self.channels)
