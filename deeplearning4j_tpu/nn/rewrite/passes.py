"""The shipped rewrite passes: space-to-depth stem, conv+BN fold, BN affine.

Round-5 calibration (BENCH_latest.json) located ResNet-50's two remaining
step-time losses precisely: the 7×7/2 conv1 stem runs at 8.3 TF/s against a
183–191 TF/s body because a 3-channel input pads the 128×128 MXU to 2.3%
occupancy, and ~5.6 ms/step of BatchNorm/elementwise HBM traffic rides on
every step. Google's MLPerf TPU submissions ("Scale MLPerf-0.6 models on
Google TPU-v3 Pods", PAPERS.md) close exactly this gap with the
space-to-depth stem transform implemented here; the BN passes remove or
collapse the elementwise chain so XLA fuses it into the conv epilogue.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..activations import Activation
from ..conf import MultiLayerConfiguration
from ..graph_conf import ComputationGraphConfiguration, VertexSpec
from ..input_type import ConvolutionalType
from ..layers.conv import ConvolutionLayer, ConvolutionMode
from ..layers.norm import BatchNormalizationLayer
from ..layers.pooling import SpaceToDepthLayer
from .base import (
    Params,
    PassResult,
    RewritePass,
    State,
    remap_sequential,
    unique_vertex_name,
)


def _identity_act(layer) -> bool:
    return layer.activation is None or layer.activation is Activation.IDENTITY


def _asarray(x, orig):
    """Cast a float64 numpy result back to the original array's dtype."""
    import jax.numpy as jnp

    return jnp.asarray(x, np.asarray(orig).dtype)


# ---------------------------------------------------------------------------
# 1. space-to-depth stem
# ---------------------------------------------------------------------------

class SpaceToDepthStemPass(RewritePass):
    """Rewrite a leading 7×7 stride-2 SAME conv on few channels into a 2×2
    space-to-depth followed by an equivalent 4×4 stride-1 SAME conv on 4×
    the channels (the MLPerf-0.6 TPU stem transform).

    Exactness: for even H×W, XLA's SAME padding for the original conv is
    (2, 3) per spatial dim and for the new conv (1, 2); writing the
    original tap ``x[2i' + dh - 2]`` as ``x[2(i' + m - 1) + u]`` gives
    ``dh = 2m + u`` — so the 7×7 kernel zero-padded to 8×8 and reshaped
    into (4×4, 4·C) taps reads *exactly* the same input pixels with
    exactly the same weights. The kernel transform is a pure pad+reshape
    (no arithmetic), hence bit-exact on the weights; outputs match to
    float tolerance (summation order inside the conv may differ).
    """

    name = "space_to_depth_stem"
    training_safe = True
    BLOCK = 2
    #: stem channels worth rewriting — the MXU-padding pathology is a
    #: small-nIn property (3-channel images); wide convs occupy the MXU.
    MAX_STEM_CHANNELS = 4

    # ---- pattern ----------------------------------------------------------
    def _matches(self, layer, input_type) -> bool:
        if type(layer) is not ConvolutionLayer:
            return False
        if not isinstance(input_type, ConvolutionalType):
            return False
        return (
            layer.kernel_size == (7, 7)
            and layer.stride == (2, 2)
            and layer.convolution_mode is ConvolutionMode.SAME
            and layer.dilation == (1, 1)
            and layer.data_format == "NCHW"
            and 0 < layer.n_in <= self.MAX_STEM_CHANNELS
            and layer.n_in == input_type.channels
            and input_type.height % 2 == 0
            and input_type.width % 2 == 0
        )

    # ---- transform --------------------------------------------------------
    @staticmethod
    def _transform_kernel(w) -> np.ndarray:
        """[O, C, 7, 7] -> [O, 4C, 4, 4] via zero-pad to 8×8 + reshape.
        New channel index (u·2 + v)·C + c matches SpaceToDepthLayer's
        block-major channel layout."""
        w = np.asarray(w)
        o, c, kh, kw = w.shape
        wp = np.zeros((o, c, 8, 8), w.dtype)
        wp[:, :, :kh, :kw] = w
        return (wp.reshape(o, c, 4, 2, 4, 2)
                  .transpose(0, 3, 5, 1, 2, 4)
                  .reshape(o, 4 * c, 4, 4))

    def _rewritten(self, conv: ConvolutionLayer,
                   conv_params: Dict[str, Any]):
        s2d = SpaceToDepthLayer(
            block_size=self.BLOCK,
            name=f"{conv.name}_s2d" if conv.name else None)
        new_conv = dataclasses.replace(
            conv, n_in=conv.n_in * 4, kernel_size=(4, 4), stride=(1, 1),
            padding=(0, 0))
        new_params = dict(conv_params)
        new_params["W"] = _asarray(
            self._transform_kernel(conv_params["W"]), conv_params["W"])
        return s2d, new_conv, new_params

    # ---- sequential -------------------------------------------------------
    def apply_sequential(self, conf: MultiLayerConfiguration,
                         params: Params, state: State) -> PassResult:
        if not conf.layers or not self._matches(conf.layers[0], conf.input_type):
            return conf, params, state, False
        conv = conf.layers[0]
        s2d, new_conv, new_conv_params = self._rewritten(
            conv, params.get(conf.layer_name(0), {}))
        new_layers = (s2d, new_conv) + tuple(conf.layers[1:])
        index_map = {i: i + 1 for i in range(len(conf.layers))}
        new_conf, new_params, new_state = remap_sequential(
            conf, new_layers, index_map, params, state,
            param_overrides={0: new_conv_params})
        return new_conf, new_params, new_state, True

    # ---- graph ------------------------------------------------------------
    def apply_graph(self, conf: ComputationGraphConfiguration,
                    params: Params, state: State) -> PassResult:
        if not conf.input_types:
            return conf, params, state, False
        in_types = dict(zip(conf.network_inputs, conf.input_types))
        new_vertices: List[VertexSpec] = []
        new_params = dict(params)
        new_state = dict(state)
        changed = False
        for spec in conf.vertices:
            if (not changed
                    and spec.layer is not None
                    and spec.preprocessor is None
                    and len(spec.inputs) == 1
                    and spec.inputs[0] in in_types
                    and self._matches(spec.layer, in_types[spec.inputs[0]])):
                s2d, new_conv, new_conv_params = self._rewritten(
                    spec.layer, params.get(spec.name, {}))
                s2d_name = unique_vertex_name(conf, f"{spec.name}_s2d")
                new_vertices.append(VertexSpec(
                    name=s2d_name, layer=s2d, inputs=spec.inputs))
                new_vertices.append(dataclasses.replace(
                    spec, layer=new_conv, inputs=(s2d_name,)))
                new_params[spec.name] = new_conv_params
                new_params[s2d_name] = {}
                new_state[s2d_name] = {}
                changed = True
            else:
                new_vertices.append(spec)
        if not changed:
            return conf, params, state, False
        new_conf = dataclasses.replace(conf, vertices=tuple(new_vertices))
        return new_conf, new_params, new_state, True


# ---------------------------------------------------------------------------
# 2. conv + BN fold (inference only)
# ---------------------------------------------------------------------------

class ConvBatchNormFoldPass(RewritePass):
    """Fold a BatchNormalizationLayer into the preceding identity-activation
    ConvolutionLayer for inference: with s = γ/√(σ²+ε),

        W' = W · s (per out-channel)      b' = β + (b − μ)·s

    eliminating the BN op and its HBM round-trip from every served
    forward. Weight math runs in float64 and casts back to the param
    dtype. Inference-only: the fold freezes the running statistics into
    the conv, so training through it would silently stop updating them —
    ``resolve_passes(context="training")`` rejects this pass.
    """

    name = "conv_bn_fold"
    training_safe = False

    @staticmethod
    def _foldable(conv, bn) -> bool:
        return (
            type(conv) is ConvolutionLayer
            and type(bn) is BatchNormalizationLayer
            and _identity_act(conv)
            and bn.n_out == conv.n_out
            and conv.n_out > 0
        )

    @staticmethod
    def _fold(conv: ConvolutionLayer, bn: BatchNormalizationLayer,
              conv_params: Dict[str, Any], bn_params: Dict[str, Any],
              bn_state: Dict[str, Any]):
        w = np.asarray(conv_params["W"], np.float64)
        n = bn.n_out
        gamma = (np.asarray(bn_params["gamma"], np.float64)
                 if "gamma" in bn_params else np.full(n, bn.gamma_init))
        beta = (np.asarray(bn_params["beta"], np.float64)
                if "beta" in bn_params else np.full(n, bn.beta_init))
        mean = np.asarray(bn_state["mean"], np.float64)
        var = np.asarray(bn_state["var"], np.float64)
        scale = gamma / np.sqrt(var + bn.eps)
        b = (np.asarray(conv_params["b"], np.float64)
             if "b" in conv_params else np.zeros(n))
        new_w = w * scale.reshape(-1, 1, 1, 1)
        new_b = beta + (b - mean) * scale
        new_conv = dataclasses.replace(
            conv, has_bias=True,
            activation=bn.activation if bn.activation is not None
            else conv.activation)
        new_params = {
            "W": _asarray(new_w, conv_params["W"]),
            "b": _asarray(new_b, conv_params.get("W")),
        }
        return new_conv, new_params

    # ---- sequential -------------------------------------------------------
    def apply_sequential(self, conf: MultiLayerConfiguration,
                         params: Params, state: State) -> PassResult:
        layers = conf.layers
        new_layers: List[Any] = []
        index_map: Dict[int, int] = {}
        overrides: Dict[int, Dict[str, Any]] = {}
        changed = False
        i = 0
        while i < len(layers):
            nxt = layers[i + 1] if i + 1 < len(layers) else None
            if nxt is not None and self._foldable(layers[i], nxt):
                bn_state = state.get(conf.layer_name(i + 1), {})
                if "mean" in bn_state and "var" in bn_state:
                    new_conv, new_conv_params = self._fold(
                        layers[i], nxt,
                        params.get(conf.layer_name(i), {}),
                        params.get(conf.layer_name(i + 1), {}), bn_state)
                    index_map[i] = len(new_layers)
                    overrides[i] = new_conv_params
                    new_layers.append(new_conv)
                    changed = True
                    i += 2  # BN dropped: no mapping for old index i+1
                    continue
            index_map[i] = len(new_layers)
            new_layers.append(layers[i])
            i += 1
        if not changed:
            return conf, params, state, False
        new_conf, new_params, new_state = remap_sequential(
            conf, new_layers, index_map, params, state,
            param_overrides=overrides)
        return new_conf, new_params, new_state, True

    # ---- graph ------------------------------------------------------------
    def apply_graph(self, conf: ComputationGraphConfiguration,
                    params: Params, state: State) -> PassResult:
        consumers: Dict[str, List[str]] = {}
        by_name = {v.name: v for v in conf.vertices}
        for v in conf.vertices:
            for inp in v.inputs:
                consumers.setdefault(inp, []).append(v.name)

        # BN vertices whose single input is a conv that feeds ONLY that BN
        # (rewiring away a conv with other consumers would change them)
        folds: Dict[str, str] = {}  # conv name -> bn name
        for v in conf.vertices:
            if (v.layer is None or v.preprocessor is not None
                    or len(v.inputs) != 1):
                continue
            src = by_name.get(v.inputs[0])
            if src is None or src.layer is None:
                continue
            if not self._foldable(src.layer, v.layer):
                continue
            if consumers.get(src.name, []) != [v.name]:
                continue
            if src.name in conf.network_outputs:
                continue
            bn_state = state.get(v.name, {})
            if "mean" not in bn_state or "var" not in bn_state:
                continue
            folds[src.name] = v.name

        if not folds:
            return conf, params, state, False

        bn_to_conv = {bn: cv for cv, bn in folds.items()}
        new_vertices: List[VertexSpec] = []
        new_params = dict(params)
        new_state = dict(state)
        for v in conf.vertices:
            if v.name in bn_to_conv:  # folded BN: vertex disappears
                new_params.pop(v.name, None)
                new_state.pop(v.name, None)
                continue
            # consumers of a folded BN now read the conv directly
            inputs = tuple(bn_to_conv.get(i, i) for i in v.inputs)
            if v.name in folds:
                bn_name = folds[v.name]
                bn_spec = by_name[bn_name]
                new_conv, conv_params = self._fold(
                    v.layer, bn_spec.layer, params.get(v.name, {}),
                    params.get(bn_name, {}), state.get(bn_name, {}))
                v = dataclasses.replace(v, layer=new_conv, inputs=inputs)
                new_params[v.name] = conv_params
            elif inputs != v.inputs:
                v = dataclasses.replace(v, inputs=inputs)
            new_vertices.append(v)
        outputs = tuple(bn_to_conv.get(o, o) for o in conf.network_outputs)
        new_conf = dataclasses.replace(
            conf, vertices=tuple(new_vertices), network_outputs=outputs)
        return new_conf, new_params, new_state, True


# ---------------------------------------------------------------------------
# 3. BN affine precompute (training-safe)
# ---------------------------------------------------------------------------

class BatchNormAffinePass(RewritePass):
    """Collapse BN's normalize+scale+shift chain into one fused
    multiply-add: precompute per-channel ``scale = γ·rsqrt(σ²+ε)`` and
    ``shift = β − μ·scale`` (O(channels) work), then apply
    ``y = x·scale + shift`` as a single FMA over the tensor instead of the
    4-op elementwise chain — XLA fuses it into one epilogue, cutting the
    BN HBM round-trips. Pure config rewrite (``fused=True`` on each BN);
    params/state are untouched and batch statistics are still computed in
    training mode, so this is training-safe and checkpoint-neutral.
    """

    name = "bn_affine_precompute"
    training_safe = True

    @staticmethod
    def _fuse(layer):
        if type(layer) is BatchNormalizationLayer and not layer.fused:
            return dataclasses.replace(layer, fused=True), True
        return layer, False

    def apply_sequential(self, conf: MultiLayerConfiguration,
                         params: Params, state: State) -> PassResult:
        fused = [self._fuse(l) for l in conf.layers]
        if not any(c for _, c in fused):
            return conf, params, state, False
        new_conf = dataclasses.replace(
            conf, layers=tuple(l for l, _ in fused))
        return new_conf, params, state, True

    def apply_graph(self, conf: ComputationGraphConfiguration,
                    params: Params, state: State) -> PassResult:
        new_vertices: List[VertexSpec] = []
        changed = False
        for v in conf.vertices:
            if v.layer is not None:
                new_layer, c = self._fuse(v.layer)
                if c:
                    v = dataclasses.replace(v, layer=new_layer)
                    changed = True
            new_vertices.append(v)
        if not changed:
            return conf, params, state, False
        return (dataclasses.replace(conf, vertices=tuple(new_vertices)),
                params, state, True)
