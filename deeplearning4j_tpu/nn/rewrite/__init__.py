"""Graph rewrite passes (README "Graph optimization passes").

Pattern-match-and-rewrite over ``MultiLayerConfiguration`` /
``ComputationGraphConfiguration`` configs **plus their params**: each
pass returns a numerically equivalent (config, params, state) triple.
Rewrites are in-memory only — serialized artifacts always store the
un-rewritten model.

Entry points: ``Solver``/``GraphSolver`` ``optimize=`` (training-safe
set), ``ModelManager`` ``optimize=`` (inference set, applied before
warmup on every deploy/canary), or direct ``rewrite_model``.
"""

from .base import (
    RewritePass,
    apply_passes,
    inference_passes,
    resolve_passes,
    rewrite_model,
    rewrite_model_inplace,
    training_passes,
)
from .passes import (
    BatchNormAffinePass,
    ConvBatchNormFoldPass,
    SpaceToDepthStemPass,
)

__all__ = [
    "BatchNormAffinePass",
    "ConvBatchNormFoldPass",
    "RewritePass",
    "SpaceToDepthStemPass",
    "apply_passes",
    "inference_passes",
    "resolve_passes",
    "rewrite_model",
    "rewrite_model_inplace",
    "training_passes",
]
