"""Graph rewrite passes (README "Graph optimization passes").

Pattern-match-and-rewrite over ``MultiLayerConfiguration`` /
``ComputationGraphConfiguration`` configs **plus their params**: each
pass returns a numerically equivalent (config, params, state) triple —
except the post-training quantization passes (``quantize.py``), which
trade bounded rounding error for serving capacity and therefore deploy
through the canary gate. Rewrites are in-memory only — serialized
artifacts always store the un-rewritten model.

Entry points: ``Solver``/``GraphSolver`` ``optimize=`` (training-safe
set), ``ModelManager`` ``optimize=`` (inference set, applied before
warmup on every deploy/canary; ``"inference:int8"``/``"inference:fp8"``
adds weight quantization), or direct ``rewrite_model``.
"""

from .base import (
    RewritePass,
    apply_passes,
    inference_passes,
    quantization_passes,
    resolve_passes,
    rewrite_model,
    rewrite_model_inplace,
    training_passes,
)
from .passes import (
    BatchNormAffinePass,
    ConvBatchNormFoldPass,
    SpaceToDepthStemPass,
)
from .quantize import (
    QuantizedConvolutionLayer,
    QuantizedDenseLayer,
    QuantizedMixtureOfExpertsLayer,
    QuantizedSelfAttentionLayer,
    QuantizedTransformerDecoderBlockLayer,
    QuantizeWeightsPass,
    calibrate,
    count_quantized_layers,
    quantize_weight,
)

__all__ = [
    "BatchNormAffinePass",
    "ConvBatchNormFoldPass",
    "QuantizeWeightsPass",
    "QuantizedConvolutionLayer",
    "QuantizedDenseLayer",
    "QuantizedMixtureOfExpertsLayer",
    "QuantizedSelfAttentionLayer",
    "QuantizedTransformerDecoderBlockLayer",
    "RewritePass",
    "SpaceToDepthStemPass",
    "apply_passes",
    "calibrate",
    "count_quantized_layers",
    "inference_passes",
    "quantization_passes",
    "quantize_weight",
    "resolve_passes",
    "rewrite_model",
    "rewrite_model_inplace",
    "training_passes",
]
