"""Pattern-match-and-rewrite pass framework over network configs + params.

Graph-level rewriting before execution is the standard systems answer to
model-shaped inefficiency (TensorFlow's Grappler, PAPERS.md): a pass
pattern-matches a structural idiom in a ``MultiLayerConfiguration`` or
``ComputationGraphConfiguration`` and returns a transformed
``(config, params, state)`` triple that is **numerically equivalent** —
weight transforms are exact (float64 intermediate math, pad+reshape),
equivalence is gradchecked (tests/test_rewrite.py), and a pass that finds
no match returns its inputs untouched (byte-identical config, same param
objects), so running the pipeline on BERT/LSTM/MoE graphs is a provable
no-op.

Two pass sets, threaded through the stack:

* ``TRAINING_PASSES`` (``training_safe = True``) — applied by
  ``Solver``/``GraphSolver`` via the ``optimize=`` knob at step-build
  time. Safe to train through: gradients of the rewritten graph match
  the original (space-to-depth stem, BN affine precompute).
* ``INFERENCE_PASSES`` — applied by ``ModelManager.deploy`` before
  warmup so every swapped-in version serves the rewritten graph
  (adds conv+BN folding, which freezes BN statistics into conv weights
  and therefore must never run under training).

Rewrites are **in-memory only**: serialized artifacts and the
``ModelStore`` always hold the un-rewritten model, so checkpoints stay
compatible across versions that add or change passes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ..conf import MultiLayerConfiguration
from ..graph_conf import ComputationGraphConfiguration

Params = Dict[str, Dict[str, Any]]
State = Dict[str, Dict[str, Any]]
# (config, params, state, changed)
PassResult = Tuple[Any, Params, State, bool]


class RewritePass:
    """One pattern-match-and-rewrite transform.

    Subclasses implement ``apply_sequential`` and/or ``apply_graph``; both
    take (config, params, state) and return (config, params, state,
    changed). The contract:

    * **Equivalence** — the rewritten model's forward (and, for
      ``training_safe`` passes, backward) matches the original to float
      tolerance for every input.
    * **Exact no-op on non-matching graphs** — when the pattern is
      absent, return the *same* config/params/state objects with
      ``changed=False``.
    * **Params travel with the config** — any layer rename, insertion or
      removal remaps the params/state pytrees in the same call, so the
      triple is always self-consistent.
    """

    name: str = "rewrite"
    #: True when training through the rewritten graph is equivalent to
    #: training through the original (exact reparametrization). Inference
    #: -only passes (conv+BN fold) freeze statistics and must never be
    #: applied by a Solver.
    training_safe: bool = False

    def apply(self, conf: Any, params: Params, state: State) -> PassResult:
        if isinstance(conf, MultiLayerConfiguration):
            return self.apply_sequential(conf, params, state)
        if isinstance(conf, ComputationGraphConfiguration):
            return self.apply_graph(conf, params, state)
        return conf, params, state, False

    def apply_sequential(self, conf: MultiLayerConfiguration,
                         params: Params, state: State) -> PassResult:
        return conf, params, state, False

    def apply_graph(self, conf: ComputationGraphConfiguration,
                    params: Params, state: State) -> PassResult:
        return conf, params, state, False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"{type(self).__name__}(name={self.name!r})"


# ---------------------------------------------------------------------------
# sequential-config plumbing: inserting/removing layers shifts the implicit
# ``layer_{i}`` names of unnamed layers, so params/state must be remapped in
# lockstep with the layer list.
# ---------------------------------------------------------------------------

def remap_sequential(
    conf: MultiLayerConfiguration,
    new_layers: Sequence,
    index_map: Dict[int, int],
    params: Params,
    state: State,
    param_overrides: Optional[Dict[int, Dict[str, Any]]] = None,
) -> Tuple[MultiLayerConfiguration, Params, State]:
    """Rebuild (config, params, state) for an edited sequential layer list.

    ``index_map`` maps old layer index -> new layer index (dropped layers
    absent); ``param_overrides`` maps *old* index -> replacement param dict
    (e.g. the transformed stem kernel). Inserted layers get empty
    params/state entries via the new config's own naming."""
    new_conf = dataclasses.replace(conf, layers=tuple(new_layers))
    new_params: Params = {}
    new_state: State = {}
    mapped_new = set()
    overrides = param_overrides or {}
    for old_i, new_i in index_map.items():
        old_name = conf.layer_name(old_i)
        new_name = new_conf.layer_name(new_i)
        mapped_new.add(new_name)
        if old_i in overrides:
            new_params[new_name] = dict(overrides[old_i])
        elif old_name in params:
            new_params[new_name] = params[old_name]
        if old_name in state:
            new_state[new_name] = state[old_name]
    for i in range(len(new_layers)):
        name = new_conf.layer_name(i)
        if name not in mapped_new:
            new_params.setdefault(name, {})
            new_state.setdefault(name, {})
    return new_conf, new_params, new_state


def unique_vertex_name(conf: ComputationGraphConfiguration, base: str) -> str:
    taken = set(conf.network_inputs) | {v.name for v in conf.vertices}
    name = base
    i = 0
    while name in taken:
        i += 1
        name = f"{base}{i}"
    return name


# ---------------------------------------------------------------------------
# pass pipelines
# ---------------------------------------------------------------------------

def training_passes() -> List[RewritePass]:
    """Default training-safe pipeline (the ``optimize="training"`` set)."""
    from .passes import BatchNormAffinePass, SpaceToDepthStemPass

    return [SpaceToDepthStemPass(), BatchNormAffinePass()]


def inference_passes(quantize: Optional[str] = None,
                     act_ranges=None) -> List[RewritePass]:
    """Default inference pipeline (the ``ModelManager.deploy`` set):
    stem rewrite, then conv+BN fold, then affine precompute for any BN
    the fold could not consume. ``quantize="int8"``/``"fp8"`` appends the
    post-training weight-quantization pass AFTER the folds (so the folded
    conv weights are what gets quantized); ``act_ranges`` (a
    :func:`~.quantize.calibrate` result) turns on the calibrated
    activation-quantization variant for the named Dense layers."""
    from .passes import (
        BatchNormAffinePass,
        ConvBatchNormFoldPass,
        SpaceToDepthStemPass,
    )

    passes: List[RewritePass] = [SpaceToDepthStemPass(),
                                 ConvBatchNormFoldPass(),
                                 BatchNormAffinePass()]
    if quantize is not None:
        passes += quantization_passes(quantize, act_ranges=act_ranges)
    return passes


def quantization_passes(dtype: str = "int8",
                        act_ranges=None) -> List[RewritePass]:
    """The post-training quantization set on its own (inference-only)."""
    from .quantize import QuantizeWeightsPass

    return [QuantizeWeightsPass(dtype, act_ranges=act_ranges)]


def resolve_passes(
    spec: Union[None, bool, str, RewritePass, Sequence[RewritePass]],
    *,
    context: str = "inference",
) -> List[RewritePass]:
    """Normalize an ``optimize=`` argument into a pass list.

    ``True``/``"training"`` -> the training-safe set; ``"inference"`` ->
    the inference set; ``"inference:int8"``/``"inference:fp8"`` -> the
    inference set plus post-training weight quantization (the deploy-time
    serving spec for quantized models — see rewrite/quantize.py);
    a pass or list of passes is taken verbatim. In a
    ``context="training"`` resolution, inference-only passes are
    rejected — folding BN into a conv that is about to be *trained*
    silently changes semantics, so it is an error, not a warning."""
    if not spec:
        return []
    if spec is True:
        spec = context
    if isinstance(spec, str):
        if spec == "training":
            passes = training_passes()
        elif spec == "inference":
            passes = inference_passes()
        elif spec.startswith("inference:"):
            passes = inference_passes(quantize=spec.split(":", 1)[1])
        else:
            raise ValueError(
                f"Unknown rewrite pipeline {spec!r}; expected 'training', "
                f"'inference', or a list of RewritePass instances")
    elif isinstance(spec, RewritePass):
        passes = [spec]
    else:
        passes = list(spec)
    if context == "training":
        bad = [p.name for p in passes if not p.training_safe]
        if bad:
            raise ValueError(
                f"Pass(es) {bad} are inference-only and cannot be applied "
                f"at training time (optimize=); use them via "
                f"ModelManager/rewrite_model for serving instead")
    return passes


def apply_passes(
    conf: Any, params: Params, state: State,
    passes: Sequence[RewritePass],
) -> Tuple[Any, Params, State, List[str]]:
    """Run ``passes`` in order; returns the transformed triple plus the
    names of passes that actually changed the graph."""
    applied: List[str] = []
    for p in passes:
        conf, params, state, changed = p.apply(conf, params, state)
        if changed:
            applied.append(p.name)
    return conf, params, state, applied


# ---------------------------------------------------------------------------
# model-level entry points
# ---------------------------------------------------------------------------

def _layer_names(conf: Any):
    if isinstance(conf, MultiLayerConfiguration):
        return [(conf.layer_name(i), l) for i, l in enumerate(conf.layers)]
    return [(s.name, s.layer) for s in conf.vertices if s.layer is not None]


def _install(model, conf: Any, params: Params, state: State) -> None:
    """Point ``model`` at the rewritten triple, keeping the invariants
    ``init()`` normally establishes (state entry per layer, persistent-key
    map, fresh jit caches)."""
    full_state: State = {}
    persistent: Dict[str, Tuple[str, ...]] = {}
    for name, _layer in _layer_names(conf):
        st = dict(state.get(name, {}))
        full_state[name] = st
        persistent[name] = tuple(st.keys())
    model.conf = conf
    if isinstance(conf, MultiLayerConfiguration):
        model.layers = conf.layers
    model.params = params
    model.state = full_state
    model._persistent_keys = persistent
    model._output_fn_cache.clear()
    model._initialized = True


def rewrite_model(model, passes: Union[str, Sequence[RewritePass]] = "inference",
                  *, context: str = "inference"):
    """Apply ``passes`` to a **copy** of ``model``; returns
    ``(new_model, applied_pass_names)``. When nothing matched, the
    original model object is returned unchanged (zero cost). The original
    model is never mutated — this is the serving entry point
    (``ModelManager`` folds the loaded copy; the store artifact stays
    un-rewritten)."""
    model._check_init()
    plist = resolve_passes(passes, context=context)
    conf, params, state, applied = apply_passes(
        model.conf, model.params, model.state, plist)
    if not applied:
        return model, []
    new = type(model)(conf)
    _install(new, conf, params, state)
    return new, applied


def rewrite_model_inplace(
    model, passes: Union[str, Sequence[RewritePass]] = "training",
    *, context: str = "training",
) -> List[str]:
    """Apply ``passes`` to ``model`` in place (the ``Solver``/
    ``GraphSolver`` ``optimize=`` path, where the caller keeps training
    the same model object). Returns the applied pass names."""
    model._check_init()
    plist = resolve_passes(passes, context=context)
    conf, params, state, applied = apply_passes(
        model.conf, model.params, model.state, plist)
    if applied:
        _install(model, conf, params, state)
    return applied
