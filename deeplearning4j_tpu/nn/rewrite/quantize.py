"""Post-training quantization rewrite passes: int8/fp8 weights, int8 acts.

Serving capacity per chip is the scarcest fleet resource (ROADMAP north
star); post-training quantization is the classic lever — int8 weights
halve (vs bf16) or quarter (vs f32) the weight HBM traffic per forward
and double effective MXU throughput where the hardware has an int8 path,
*if accuracy holds*. Following the TensorFlow-paper pattern of serving a
rewritten, lower-precision graph distinct from the training graph
(PAPERS.md, arxiv 1605.08695), quantization here is an inference-only
:class:`~.base.RewritePass`, applied in memory at deploy time by
``ModelManager(optimize="inference:int8")`` — the ``ModelStore``
artifact stays full-precision, so rollback is free and checkpoints never
know quantization exists.

Scheme (weight-only, the default):

* per-OUTPUT-channel absmax scales — ``scale_c = max|W[..., c]| / 127``
  (int8) or ``/ 448`` (fp8 e4m3) — computed in float64 on the host;
* the stored weight is the quantized integer/fp8 tensor; the matmul runs
  on it directly (small integers are exact in any float compute dtype)
  and the **dequant is folded into the output epilogue**:
  ``y = (x @ Wq) * scale + b`` — one fused per-channel multiply, never a
  dequantized weight copy in HBM.

Activation quantization (optional, int8 only) additionally quantizes the
layer INPUT against a per-layer absmax range measured by
:func:`calibrate` over representative batches; the matmul then runs
int8×int8 with int32 accumulation (``lax.dot_general(...,
preferred_element_type=int32)``) and the combined ``s_x · s_w`` scale
lands in the same epilogue. The calibrated ranges are carried in the
pass config (``QuantizeWeightsPass(act_ranges=...)``), not in the model.

Unlike every other pass in this package, quantization is deliberately
NOT numerically equivalent — it trades bounded rounding error for
capacity. That is exactly why it deploys through the canary machinery:
``start_canary(v, optimize="inference:int8")`` serves the quantized
graph next to the full-precision incumbent under hash-split routing, and
``promote_canary``/``rollback`` gate it on measured accuracy/latency
(tools/check_quantize_contract.py). The passes DO keep the framework's
no-op contract: a graph without Dense/Conv/attention matmuls is returned
byte-identical (tests/test_rewrite.py property test).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..activations import Activation
from ..conf import MultiLayerConfiguration
from ..graph_conf import ComputationGraphConfiguration, VertexSpec
from ..layers.attention import (
    SelfAttentionLayer,
    TransformerDecoderBlockLayer,
    _cached_attention,
    _merge_heads,
    _split_heads,
    dot_product_attention,
)
from ..layers.base import Layer, LayerContext, Params, State, apply_input_dropout
from ..layers.conv import ConvolutionLayer, _lax_padding
from ..layers.feedforward import DenseLayer
from ..layers.moe import MixtureOfExpertsLayer
from .base import PassResult, RewritePass

#: int8 symmetric range and fp8 e4m3 max-normal — the scale denominators.
_INT8_MAX = 127.0
_FP8_MAX = 448.0
_EPS = 1e-12

_FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)

QUANT_DTYPES = ("int8", "fp8")


def _quant_storage_dtype(quant_dtype: str):
    if quant_dtype == "int8":
        return jnp.int8
    if quant_dtype == "fp8":
        if _FP8_DTYPE is None:
            raise ValueError(
                "fp8 weight quantization needs a jaxlib with float8_e4m3fn "
                "support; this build has none — use dtype='int8'")
        return _FP8_DTYPE
    raise ValueError(f"unknown quant dtype {quant_dtype!r}; "
                     f"expected one of {QUANT_DTYPES}")


def quantize_weight(w, quant_dtype: str, *,
                    channel_axis: "int | Tuple[int, ...]" = -1
                    ) -> Tuple[jax.Array, jax.Array]:
    """Per-output-channel absmax quantization of one weight tensor.

    ``channel_axis`` names the OUTPUT-channel axis (kept at full
    granularity; every other axis is reduced into the absmax). A TUPLE of
    axes keeps several — e.g. ``(0, 2)`` on an ``[E, d, h]`` expert slab
    yields per-expert per-output-channel scales ``[E, h]``. Scale math
    runs in float64 on the host; returns ``(Wq, scale)`` with ``Wq`` in
    the storage dtype and ``scale`` float32 shaped by the kept axes.
    The dequant identity is ``W ≈ Wq * scale`` broadcast over
    ``channel_axis``."""
    storage = _quant_storage_dtype(quant_dtype)
    w64 = np.asarray(w, np.float64)
    if isinstance(channel_axis, tuple):
        keep = tuple(sorted(a % w64.ndim for a in channel_axis))
    else:
        keep = (channel_axis % w64.ndim,)
    reduce_axes = tuple(a for a in range(w64.ndim) if a not in keep)
    amax = np.max(np.abs(w64), axis=reduce_axes) if reduce_axes \
        else np.abs(w64)
    denom = _INT8_MAX if quant_dtype == "int8" else _FP8_MAX
    scale = np.maximum(amax, _EPS) / denom
    expand = tuple(slice(None) if a in keep else None
                   for a in range(w64.ndim))
    scaled = w64 / scale[expand]
    if quant_dtype == "int8":
        q = jnp.asarray(np.clip(np.rint(scaled), -127, 127), storage)
    else:
        q = jnp.asarray(scaled, np.float32).astype(storage)
    return q, jnp.asarray(scale, jnp.float32)


def _epilogue_scale(scale: jax.Array, like: jax.Array) -> jax.Array:
    """Scale cast for the output epilogue (compute-dtype multiply)."""
    return scale.astype(like.dtype)


def _qmm(x: jax.Array, wq: jax.Array, scale: jax.Array) -> jax.Array:
    """Weight-only quantized matmul: operand is the raw quantized tensor
    (exact in float), dequant scale applied to the OUTPUT columns —
    ``(x @ Wq) * s == x @ (Wq·s)`` because ``s`` is per output channel."""
    y = x @ wq.astype(x.dtype)
    return y * _epilogue_scale(scale, y)


def _act_quantize(x: jax.Array, absmax: float) -> Tuple[jax.Array, float]:
    """Symmetric int8 activation quantization against a CALIBRATED
    absmax (data-independent, so the shapes/ops stay static)."""
    s = max(float(absmax), _EPS) / _INT8_MAX
    q = jnp.clip(jnp.round(x / s), -127, 127).astype(jnp.int8)
    return q, s


# ---------------------------------------------------------------------------
# quantized layer configs (rewrite products — inference-only, never trained
# or serialized: the store artifact always holds the full-precision layer)
# ---------------------------------------------------------------------------

from ...core.config import register_config  # noqa: E402  (import order doc'd)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class QuantizedDenseLayer(DenseLayer):
    """Rewrite product of :class:`QuantizeWeightsPass` over a
    :class:`DenseLayer`. Params: ``W_q`` (int8/fp8 ``[nIn, nOut]``),
    ``W_scale`` (f32 ``[nOut]``), plus the untouched bias. With
    ``act_absmax`` set (calibrated activation quantization, int8 only)
    the input is quantized too and the matmul accumulates in int32."""

    quant_dtype: str = "int8"
    act_absmax: Optional[float] = None

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ()  # inference-only: a Solver must never touch these

    def init(self, key: jax.Array, dtype: Any) -> Params:
        raise RuntimeError(
            "QuantizedDenseLayer is a rewrite product — it is created by "
            "QuantizeWeightsPass with params transformed from the "
            "full-precision layer, never initialized fresh")

    def apply(self, params: Params, state: State, x: jax.Array,
              ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        wq, ws = params["W_q"], params["W_scale"]
        three_d = x.ndim == 3
        if three_d:  # recurrent [b, f, t] -> [b·t, f] (one MXU gemm)
            b, f, t = x.shape
            x2 = x.transpose(0, 2, 1).reshape(b * t, f)
        else:
            x2 = x
        if self.act_absmax is not None and self.quant_dtype == "int8":
            xq, sx = _act_quantize(x2, self.act_absmax)
            acc = jax.lax.dot_general(
                xq, wq, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            # dequant in f32: the int32 accumulator can exceed bf16's
            # 8 mantissa bits, so the epilogue scales before the cast
            y = (acc.astype(jnp.float32)
                 * (ws.astype(jnp.float32) * jnp.float32(sx))).astype(x.dtype)
        else:
            y = _qmm(x2, wq, ws)
        if self.has_bias:
            y = y + params["b"].astype(y.dtype)
        if three_d:
            y = y.reshape(b, t, -1).transpose(0, 2, 1)
        act = self.activation or Activation.SIGMOID  # DenseLayer default
        return act(y), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class QuantizedConvolutionLayer(ConvolutionLayer):
    """Rewrite product over :class:`ConvolutionLayer`: ``W_q``
    (``[O, I, kH, kW]`` int8/fp8) + per-out-channel ``W_scale`` ``[O]``;
    the conv runs on the quantized kernel directly and the dequant rides
    the bias epilogue (weight-only — conv inputs stay full precision)."""

    quant_dtype: str = "int8"

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        raise RuntimeError(
            "QuantizedConvolutionLayer is a rewrite product — see "
            "QuantizeWeightsPass")

    def apply(self, params: Params, state: State, x: jax.Array,
              ctx: LayerContext) -> Tuple[jax.Array, State]:
        from ...ops import helpers

        x = apply_input_dropout(self, x, ctx)
        pad = _lax_padding(self.convolution_mode, self.padding,
                           self.kernel_size, self.dilation)
        y = helpers.conv2d(x, params["W_q"].astype(x.dtype), self.stride,
                           pad, self.dilation, self._dn())
        scale = _epilogue_scale(params["W_scale"], y)
        if self.data_format == "NCHW":
            y = y * scale[None, :, None, None]
            if self.has_bias:
                y = y + params["b"].astype(y.dtype)[None, :, None, None]
        else:
            y = y * scale
            if self.has_bias:
                y = y + params["b"].astype(y.dtype)
        act = self.activation or Activation.IDENTITY
        return act(y), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class QuantizedSelfAttentionLayer(SelfAttentionLayer):
    """Rewrite product over a projecting :class:`SelfAttentionLayer`:
    Wq/Wk/Wv/Wo each stored quantized (``<name>_q`` + ``<name>_scale``),
    dequant in each projection's epilogue. Attention math itself stays in
    the compute dtype; ``decode_state`` (the KV cache) is inherited."""

    quant_dtype: str = "int8"

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        raise RuntimeError(
            "QuantizedSelfAttentionLayer is a rewrite product — see "
            "QuantizeWeightsPass")

    def apply(self, params: Params, state: State, x: jax.Array,
              ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        xt = x.transpose(0, 2, 1)
        q = _split_heads(_qmm(xt, params["Wq_q"], params["Wq_scale"]),
                         self.n_heads)
        k = _split_heads(_qmm(xt, params["Wk_q"], params["Wk_scale"]),
                         self.n_heads)
        v = _split_heads(_qmm(xt, params["Wv_q"], params["Wv_scale"]),
                         self.n_heads)
        if "cache_k" in state:
            if not self.causal:
                raise ValueError(
                    "KV-cached decode requires causal=True — bidirectional "
                    "attention cannot be decoded incrementally")
            o, new_state = _cached_attention(q, k, v, state, ctx.mask)
        else:
            o = dot_product_attention(q, k, v, mask=ctx.mask,
                                      causal=self.causal)
            new_state = state
        o = _qmm(_merge_heads(o), params["Wo_q"], params["Wo_scale"])
        act = self.activation or Activation.IDENTITY
        return act(o).transpose(0, 2, 1), new_state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class QuantizedTransformerDecoderBlockLayer(TransformerDecoderBlockLayer):
    """Rewrite product over :class:`TransformerDecoderBlockLayer`: all six
    matmul weights (Wq/Wk/Wv/Wo attention projections + W1/W2 FFN) stored
    quantized with per-output-channel scales; LayerNorm params and biases
    untouched. The KV-cache decode path (``decode_state`` /
    ``_cached_attention``) is inherited unchanged, so a quantized LM
    serves through :class:`~deeplearning4j_tpu.generate.session.
    GenerationSession` exactly like its full-precision original."""

    quant_dtype: str = "int8"

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        raise RuntimeError(
            "QuantizedTransformerDecoderBlockLayer is a rewrite product — "
            "see QuantizeWeightsPass")

    def apply(self, params: Params, state: State, x: jax.Array,
              ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        xt = x.transpose(0, 2, 1)
        h1 = self._ln(xt, params["ln1_g"], params["ln1_b"])
        q = _split_heads(_qmm(h1, params["Wq_q"], params["Wq_scale"]),
                         self.n_heads)
        k = _split_heads(_qmm(h1, params["Wk_q"], params["Wk_scale"]),
                         self.n_heads)
        v = _split_heads(_qmm(h1, params["Wv_q"], params["Wv_scale"]),
                         self.n_heads)
        if "cache_k" in state:
            o, new_state = _cached_attention(q, k, v, state, ctx.mask)
        else:
            o = dot_product_attention(q, k, v, mask=ctx.mask, causal=True)
            new_state = state
        r1 = xt + _qmm(_merge_heads(o), params["Wo_q"], params["Wo_scale"])
        h2 = self._ln(r1, params["ln2_g"], params["ln2_b"])
        act = self.activation or Activation.GELU
        ffn = act(_qmm(h2, params["W1_q"], params["W1_scale"])
                  + params["b1"].astype(h2.dtype))
        ffn = _qmm(ffn, params["W2_q"], params["W2_scale"]) \
            + params["b2"].astype(h2.dtype)
        return (r1 + ffn).transpose(0, 2, 1), new_state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class QuantizedMixtureOfExpertsLayer(MixtureOfExpertsLayer):
    """Rewrite product over :class:`MixtureOfExpertsLayer`: the expert
    weight slabs ``We1``/``We2`` (``[E, d, h]``/``[E, h, o]``) stored
    quantized with PER-EXPERT per-output-channel scales (``[E, h]``/
    ``[E, o]`` — experts have independent weight distributions, so a
    shared absmax would let one outlier expert crush the others'
    resolution). The router ``Wg`` stays full precision: it is tiny and
    its argmax decides routing, where rounding flips token assignments
    rather than perturbing them smoothly. Dequant rides each expert
    matmul's epilogue via the ``_expert_kernel`` hook, so all three
    dispatch modes (einsum, sort, grouped) and the explicit
    expert-parallel path serve quantized experts unchanged."""

    quant_dtype: str = "int8"

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        raise RuntimeError(
            "QuantizedMixtureOfExpertsLayer is a rewrite product — see "
            "QuantizeWeightsPass")

    def _expert_kernel(self, params: Params, name: str):
        return params[f"{name}_q"], params[f"{name}_scale"]


_QUANTIZED_TYPES = (QuantizedDenseLayer, QuantizedConvolutionLayer,
                    QuantizedSelfAttentionLayer,
                    QuantizedTransformerDecoderBlockLayer,
                    QuantizedMixtureOfExpertsLayer)


def count_quantized_layers(model) -> int:
    """How many layers of ``model`` are quantization rewrite products
    (the serving gauge ``dl4j_tpu_serving_quantized_live``)."""
    conf = getattr(model, "conf", None)
    if conf is None:
        return 0
    if isinstance(conf, ComputationGraphConfiguration):
        layers = [v.layer for v in conf.vertices if v.layer is not None]
    else:
        layers = list(getattr(conf, "layers", ()))
    return sum(1 for l in layers if isinstance(l, _QUANTIZED_TYPES))


# ---------------------------------------------------------------------------
# the pass
# ---------------------------------------------------------------------------

class QuantizeWeightsPass(RewritePass):
    """Quantize the matmul weights of Dense / Conv / attention-projection
    / MoE-expert layers to ``dtype`` (``"int8"`` or ``"fp8"``),
    per-output-channel absmax scales (per-expert for MoE slabs), dequant
    folded into each op's output epilogue.

    ``act_ranges`` (``{layer_name: input_absmax}``, from
    :func:`calibrate`) additionally turns on int8 activation quantization
    for the named Dense layers — the per-layer range is carried HERE, in
    the pass config, so the model params stay range-free.

    Matching is by exact layer type (quantized products and output/loss
    layers are never re-matched, so the pass is idempotent and the final
    logit matmul keeps full precision). A graph with no matching layer is
    returned byte-identical — the framework no-op contract."""

    training_safe = False

    def __init__(self, dtype: str = "int8",
                 act_ranges: Optional[Mapping[str, float]] = None) -> None:
        if dtype not in QUANT_DTYPES:
            raise ValueError(f"unknown quant dtype {dtype!r}; expected one "
                             f"of {QUANT_DTYPES}")
        _quant_storage_dtype(dtype)  # fail fast on missing fp8 support
        self.dtype = dtype
        self.act_ranges = dict(act_ranges or {})
        self.name = f"quantize_weights_{dtype}"

    # ---- per-layer transforms ----------------------------------------
    def _quantize_named(self, lparams: Dict[str, Any],
                        names_axes: Sequence[Tuple[str, Any]]
                        ) -> Dict[str, Any]:
        """Replace each ``name`` weight with ``name_q``/``name_scale``;
        every other param entry (biases, LN) passes through."""
        out = dict(lparams)
        for pname, axis in names_axes:
            w = out.pop(pname)
            q, s = quantize_weight(w, self.dtype, channel_axis=axis)
            out[f"{pname}_q"] = q
            out[f"{pname}_scale"] = s
        return out

    def _rewrite_layer(self, layer: Layer, name: str,
                       lparams: Dict[str, Any]):
        """(new_layer, new_params) for a matching layer, else None."""
        if type(layer) is DenseLayer and "W" in lparams:
            act_absmax = self.act_ranges.get(name)
            new = QuantizedDenseLayer(
                **{f.name: getattr(layer, f.name)
                   for f in dataclasses.fields(layer)},
                quant_dtype=self.dtype,
                act_absmax=(float(act_absmax)
                            if act_absmax is not None
                            and self.dtype == "int8" else None))
            return new, self._quantize_named(lparams, [("W", 1)])
        if type(layer) is ConvolutionLayer and "W" in lparams:
            new = QuantizedConvolutionLayer(
                **{f.name: getattr(layer, f.name)
                   for f in dataclasses.fields(layer)},
                quant_dtype=self.dtype)
            return new, self._quantize_named(lparams, [("W", 0)])
        if (type(layer) is SelfAttentionLayer and layer.project_input
                and "Wq" in lparams):
            new = QuantizedSelfAttentionLayer(
                **{f.name: getattr(layer, f.name)
                   for f in dataclasses.fields(layer)},
                quant_dtype=self.dtype)
            return new, self._quantize_named(
                lparams, [("Wq", 1), ("Wk", 1), ("Wv", 1), ("Wo", 1)])
        if type(layer) is MixtureOfExpertsLayer and "We1" in lparams:
            new = QuantizedMixtureOfExpertsLayer(
                **{f.name: getattr(layer, f.name)
                   for f in dataclasses.fields(layer)},
                quant_dtype=self.dtype)
            # per-expert (axis 0) × per-output-channel (axis 2) scales;
            # Wg/be1/be2 pass through full precision
            return new, self._quantize_named(
                lparams, [("We1", (0, 2)), ("We2", (0, 2))])
        if type(layer) is TransformerDecoderBlockLayer and "Wq" in lparams:
            new = QuantizedTransformerDecoderBlockLayer(
                **{f.name: getattr(layer, f.name)
                   for f in dataclasses.fields(layer)},
                quant_dtype=self.dtype)
            return new, self._quantize_named(
                lparams, [("Wq", 1), ("Wk", 1), ("Wv", 1), ("Wo", 1),
                          ("W1", 1), ("W2", 1)])
        return None

    # ---- sequential ---------------------------------------------------
    def apply_sequential(self, conf: MultiLayerConfiguration,
                         params: Params, state: State) -> PassResult:
        new_layers: List[Layer] = []
        new_params = dict(params)
        changed = False
        for i, layer in enumerate(conf.layers):
            name = conf.layer_name(i)
            hit = self._rewrite_layer(layer, name, params.get(name, {}))
            if hit is None:
                new_layers.append(layer)
                continue
            new_layer, lparams = hit
            new_layers.append(new_layer)
            new_params[name] = lparams
            changed = True
        if not changed:
            return conf, params, state, False
        new_conf = dataclasses.replace(conf, layers=tuple(new_layers))
        return new_conf, new_params, state, True

    # ---- graph --------------------------------------------------------
    def apply_graph(self, conf: ComputationGraphConfiguration,
                    params: Params, state: State) -> PassResult:
        new_vertices: List[VertexSpec] = []
        new_params = dict(params)
        changed = False
        for spec in conf.vertices:
            if spec.layer is None:
                new_vertices.append(spec)
                continue
            hit = self._rewrite_layer(spec.layer, spec.name,
                                      params.get(spec.name, {}))
            if hit is None:
                new_vertices.append(spec)
                continue
            new_layer, lparams = hit
            new_vertices.append(dataclasses.replace(spec, layer=new_layer))
            new_params[spec.name] = lparams
            changed = True
        if not changed:
            return conf, params, state, False
        new_conf = dataclasses.replace(conf, vertices=tuple(new_vertices))
        return new_conf, new_params, state, True


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def calibrate(model, batches, *, mask=None) -> Dict[str, float]:
    """Sweep representative ``batches`` through ``model`` and record each
    quantizable Dense layer's INPUT absmax — the per-layer ranges the
    activation-quantization variant clips against
    (``QuantizeWeightsPass(act_ranges=calibrate(model, batches))``).

    The ranges live in the returned dict (carried in the pass config),
    never in the model, so the same artifact can be re-calibrated per
    deployment. Sequential models only (the graph family has no Dense
    activation-quant variant yet)."""
    from ..sequential import MultiLayerNetwork

    if not isinstance(model, MultiLayerNetwork):
        raise ValueError(
            "calibrate() sweeps a MultiLayerNetwork; got "
            f"{type(model).__name__}")
    model._check_init()
    from ...core.dtypes import as_input

    names = model.layer_names()
    ranges: Dict[str, float] = {}
    for batch in batches:
        x = as_input(batch, model.dtype, model.keeps_int_input())
        _, _, _, acts = model.forward_pure(
            model.params, model.state, x, train=False, rng=None, mask=mask,
            collect=True)
        inputs = [x] + list(acts[:-1])
        for i, layer in enumerate(model.layers):
            if type(layer) is not DenseLayer:
                continue
            amax = float(jnp.max(jnp.abs(inputs[i])))
            ranges[names[i]] = max(ranges.get(names[i], 0.0), amax)
    return ranges
