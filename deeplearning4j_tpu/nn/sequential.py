"""MultiLayerNetwork — the sequential-stack model.

Reference: org.deeplearning4j.nn.multilayer.MultiLayerNetwork (~4k LoC,
SURVEY.md §2.2, call stack §3.1). Capability-equivalent API: ``init``, ``fit``,
``output``, ``feed_forward``, ``score``, ``evaluate``, ``rnn_time_step``,
truncated BPTT, masks, serialization hooks.

TPU design: where the reference's fit() interprets layers one native call at a
time (hot loops #1/#2 in SURVEY §3.1), here the ENTIRE training iteration —
forward, loss, backward, gradient normalization, updater, param update — is a
single jitted XLA program with donated params (donation ≈ the reference's
workspaces: steady-state allocation is zero). Python only feeds batches.

State model:
* ``params``    — {layer_name: {param_name: array}} trainable pytree
* ``state``     — persistent non-trainable state (BN running stats)
* ``rnn_state`` — streaming-inference carry (h/c), only used by
                  rnn_time_step / TBPTT, never carried across fit batches
                  (reference semantics)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import as_input
from ..core.listeners import ListenerBus, TrainingListener
from ..core.rng import RngState
from .conf import BackpropType, MultiLayerConfiguration
from .input_type import RecurrentType
from .layers.base import Layer, LayerContext, apply_layer as _apply_layer
from .layers.output import BaseOutputLayer


def _layer_reg_score(layer: Layer, params: Dict[str, jax.Array], score_dtype) -> jax.Array:
    """l1/l2 regularization contribution (reference: calcRegularizationScore).
    Weight-decay is decoupled (applied in the updater), not part of the score."""
    score = jnp.asarray(0.0, score_dtype)
    weight_names = set(layer.weight_param_names())
    for name, arr in params.items():
        is_weight = name in weight_names
        l1 = layer.l1 if is_weight else layer.l1_bias
        l2 = layer.l2 if is_weight else layer.l2_bias
        if l1:
            score = score + l1 * jnp.sum(jnp.abs(arr)).astype(score_dtype)
        if l2:
            score = score + 0.5 * l2 * jnp.sum(jnp.square(arr)).astype(score_dtype)
    return score


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration) -> None:
        self.conf = conf
        self.layers: Tuple[Layer, ...] = conf.layers
        if not self.layers:
            raise ValueError("Configuration has no layers")
        self.params: Dict[str, Dict[str, jax.Array]] = {}
        self.state: Dict[str, Dict[str, jax.Array]] = {}
        self.rnn_state: Dict[str, Dict[str, jax.Array]] = {}
        self._persistent_keys: Dict[str, Tuple[str, ...]] = {}
        self.listeners = ListenerBus()
        self.iteration_count = 0
        self.epoch_count = 0
        self.last_batch_size = 0
        self.score_value = float("nan")
        self._rng = RngState(conf.seed)
        self._trainer = None
        self._output_fn_cache: Dict[Any, Any] = {}
        self._initialized = False

    # ------------------------------------------------------------------ init
    @property
    def dtype(self):
        return jnp.dtype(self.conf.dtype)

    def keeps_int_input(self) -> bool:
        """True when the first layer consumes integer indices (embedding):
        inputs then keep their integer dtype through every cast boundary
        (see core.dtypes.as_input)."""
        return bool(self.layers) and getattr(self.layers[0], "consumes_indices", False)

    def _to_compute(self, params, x):
        """Mixed-precision boundary: cast params + input to compute_dtype
        (bf16 on the TPU MXU) while master params stay in ``dtype``.
        No-op when compute_dtype is unset or equals dtype. Idempotent."""
        cd = getattr(self.conf, "compute_dtype", None)
        if not cd or jnp.dtype(cd) == self.dtype:
            return params, x
        from ..core.dtypes import cast_floats

        return cast_floats(params, cd), cast_floats(x, cd)

    def layer_names(self) -> List[str]:
        return [self.conf.layer_name(i) for i in range(len(self.layers))]

    def named_param_layers(self):
        """(name, layer) pairs for layers holding trainable params — the
        updater-block boundaries (used by the Solver's LayerOptimizers)."""
        return [
            (self.conf.layer_name(i), l)
            for i, l in enumerate(self.layers)
            if l.has_params()
        ]

    def init(self, seed: Optional[int] = None) -> "MultiLayerNetwork":
        rng = RngState(self.conf.seed if seed is None else seed)
        dtype = self.dtype
        self.params, self.state, self._persistent_keys = {}, {}, {}
        for i, layer in enumerate(self.layers):
            name = self.conf.layer_name(i)
            self.params[name] = layer.init(rng.next_key(), dtype) if layer.has_params() else {}
            st = layer.init_state(dtype)
            self.state[name] = st
            self._persistent_keys[name] = tuple(st.keys())
        self.rnn_state = {}
        self._initialized = True
        self._output_fn_cache.clear()
        self._trainer = None
        return self

    def _check_init(self) -> None:
        if not self._initialized:
            self.init()

    def migrate_state(self) -> None:
        """Fill persistent-state keys introduced by newer framework versions
        with their ``init_state`` defaults, keeping every existing value
        (BN running stats survive untouched). E.g. PR 3 added
        ``expert_tokens``/``dropped_tokens`` to MixtureOfExpertsLayer state;
        pre-PR-3 state pytrees restored onto this version would otherwise
        break the jitted scan's carry structure. Called automatically at
        Solver construction and ``make_servable`` — a manual
        ``init_state`` re-run is never required."""
        if not self._initialized:
            return
        changed = False
        for i, layer in enumerate(self.layers):
            defaults = layer.init_state(self.dtype)
            if not defaults:
                continue
            name = self.conf.layer_name(i)
            cur = dict(self.state.get(name, {}))
            missing = [k for k in defaults if k not in cur]
            if missing:
                for k in missing:
                    cur[k] = defaults[k]
                self.state[name] = cur
                self._persistent_keys[name] = tuple(cur.keys())
                changed = True
        if changed:
            self._output_fn_cache.clear()

    # -------------------------------------------------------------- forward
    def forward_pure(
        self,
        params: Dict[str, Dict[str, jax.Array]],
        state: Dict[str, Dict[str, jax.Array]],
        x: jax.Array,
        *,
        train: bool,
        rng: Optional[jax.Array],
        mask: Optional[jax.Array] = None,
        rnn_state: Optional[Dict[str, Dict[str, jax.Array]]] = None,
        upto: Optional[int] = None,
        start: int = 0,
        collect: bool = False,
        dist=None,
    ):
        """Pure forward through layers [start, upto). Returns
        (out, new_state, new_rnn_state, activations?).

        With ``start > 0`` (pipeline stages fold a layer RANGE), ``x`` is
        the activation entering layer ``start`` and ``mask`` the mask at
        that boundary; the InputType walk still advances from the input so
        per-layer mask propagation and RNG folds stay index-aligned with
        the full forward."""
        params, x = self._to_compute(params, x)
        new_state: Dict[str, Dict[str, jax.Array]] = {}
        new_rnn: Dict[str, Dict[str, jax.Array]] = {}
        acts: List[jax.Array] = []
        cur_mask = mask
        n = len(self.layers) if upto is None else upto
        # per-layer input types for mask propagation (from config walk)
        it = self.conf.input_type
        for i in range(start):
            if it is not None:
                it = self.layers[i].output_type(it)
        for i in range(start, n):
            layer = self.layers[i]
            name = self.conf.layer_name(i)
            lstate = dict(state.get(name, {}))
            if rnn_state is not None and name in rnn_state:
                lstate.update(rnn_state[name])
            key = jax.random.fold_in(rng, i) if rng is not None else None
            ctx = LayerContext(train=train, rng=key, mask=cur_mask, dist=dist)
            y, lstate_out = _apply_layer(
                layer, params.get(name, {}), lstate, x, ctx,
                remat=self.conf.gradient_checkpointing and train)
            persistent = self._persistent_keys.get(name, ())
            new_state[name] = {k: v for k, v in lstate_out.items() if k in persistent}
            transient = {k: v for k, v in lstate_out.items() if k not in persistent}
            if transient:
                new_rnn[name] = transient
            if it is not None:
                cur_mask = layer.feed_forward_mask(cur_mask, it)
                it = layer.output_type(it)
            x = y
            if collect:
                acts.append(y)
        if collect:
            return x, new_state, new_rnn, acts
        return x, new_state, new_rnn

    def loss_pure(
        self,
        params,
        state,
        x: jax.Array,
        labels: jax.Array,
        *,
        rng: Optional[jax.Array],
        mask: Optional[jax.Array] = None,
        label_mask: Optional[jax.Array] = None,
        rnn_state=None,
        train: bool = True,
        dist=None,
    ):
        """Score = loss + regularization (reference: computeGradientAndScore).
        Returns (score, (new_state, new_rnn_state))."""
        out_layer = self.layers[-1]
        if not isinstance(out_layer, BaseOutputLayer):
            raise ValueError("Last layer must be an output/loss layer to compute a score")
        # regularization is computed on the master (uncast) params below;
        # the compute-dtype cast applies to forward math only
        master_params = params
        params, x = self._to_compute(params, x)
        feat, new_state, new_rnn = self.forward_pure(
            params, state, x, train=train, rng=rng, mask=mask,
            rnn_state=rnn_state, upto=len(self.layers) - 1, dist=dist,
        )
        # mask as transformed by the stack for the output layer
        cur_mask = mask
        it = self.conf.input_type
        if it is not None and cur_mask is not None:
            for i in range(len(self.layers) - 1):
                cur_mask = self.layers[i].feed_forward_mask(cur_mask, it)
                it = self.layers[i].output_type(it)
        name = self.conf.layer_name(len(self.layers) - 1)
        key = jax.random.fold_in(rng, len(self.layers) - 1) if rng is not None else None
        ctx = LayerContext(train=train, rng=key, mask=cur_mask)
        loss = out_layer.compute_loss(params.get(name, {}), feat, labels, ctx, label_mask=label_mask)
        # output layer state passes through unchanged (loss layers are stateless)
        new_state[name] = dict(state.get(name, {}))
        # score in >= float32 precision; float64 models keep float64 (gradcheck)
        score_dtype = jnp.promote_types(self.dtype, jnp.float32)
        reg = jnp.asarray(0.0, score_dtype)
        for i, layer in enumerate(self.layers):
            lname = self.conf.layer_name(i)
            if master_params.get(lname):
                reg = reg + _layer_reg_score(layer, master_params[lname], score_dtype)
            # MoE load-balance aux loss (GShard): the layer computed this
            # batch's aux during forward and stashed it in state
            bl_w = getattr(layer, "balance_loss_weight", 0.0)
            if bl_w:
                aux = new_state.get(lname, {}).get("aux_load_balance")
                if aux is not None:
                    reg = reg + bl_w * aux.astype(score_dtype)
        return loss.astype(score_dtype) + reg, (new_state, new_rnn)

    # -------------------------------------------------------------- user API
    def output(self, x, mask=None):
        """Inference forward (reference: MultiLayerNetwork.output)."""
        self._check_init()
        x = as_input(x, self.dtype, self.keeps_int_input())
        key = ("output", mask is not None)
        if key not in self._output_fn_cache:
            def fn(params, state, xx, mk):
                out, _, _ = self.forward_pure(params, state, xx, train=False, rng=None, mask=mk)
                # user-facing outputs in the model dtype even under a bf16
                # compute_dtype (mixed precision is an internal property)
                return out.astype(self.dtype)

            self._output_fn_cache[key] = jax.jit(fn)
        return self._output_fn_cache[key](self.params, self.state, x,
                                          None if mask is None else jnp.asarray(mask))

    def feed_forward(self, x, train: bool = False, mask=None):
        """All layer activations (reference: feedForward). Host-side list."""
        self._check_init()
        x = as_input(x, self.dtype, self.keeps_int_input())
        rng = self._rng.next_key() if train else None
        _, _, _, acts = self.forward_pure(
            self.params, self.state, x, train=train, rng=rng, mask=mask, collect=True
        )
        return acts

    def score(self, features, labels, mask=None, label_mask=None) -> float:
        self._check_init()
        s, _ = self.loss_pure(
            self.params, self.state,
            as_input(features, self.dtype, self.keeps_int_input()), jnp.asarray(labels),
            rng=None, mask=mask, label_mask=label_mask, train=False,
        )
        return float(s)

    def calculate_gradients(self, features, labels, mask=None, label_mask=None):
        """Full gradient pytree for the given batch — the grad-check entry
        point (reference: computeGradientAndScore + Gradient object)."""
        self._check_init()
        x = as_input(features, self.dtype, self.keeps_int_input())
        y = jnp.asarray(labels)

        def loss_of(p):
            s, _ = self.loss_pure(p, self.state, x, y, rng=None,
                                  mask=mask, label_mask=label_mask, train=True)
            return s

        return jax.grad(loss_of)(self.params)

    # ------------------------------------------------------------------ fit
    def add_listeners(self, *listeners: TrainingListener) -> None:
        for l in listeners:
            self.listeners.add(l)

    # reference spelling
    def set_listeners(self, *listeners: TrainingListener) -> None:
        self.listeners.clear()
        for l in listeners:
            self.listeners.add(l)

    def fit(self, data, labels=None, *, epochs: int = 1, mask=None, label_mask=None):
        """Train (reference: MultiLayerNetwork.fit). ``data`` may be a
        (features, labels) pair, a DataSet, or a DataSetIterator."""
        self._check_init()
        from ..train.solver import Solver

        if self._trainer is None:
            self._trainer = Solver(self)
        self._trainer.fit(data, labels, epochs=epochs, mask=mask, label_mask=label_mask)
        return self

    # ------------------------------------------------------- rnn streaming
    def rnn_time_step(self, x, mask=None):
        """Stateful streaming inference (reference: rnnTimeStep): state (h/c)
        carries across calls."""
        self._check_init()
        x = as_input(x, self.dtype, self.keeps_int_input())
        single_step = False
        if x.ndim == 2 and self._expects_sequence_input():
            x = x[:, :, None]
            single_step = True
        out, _, new_rnn = self.forward_pure(
            self.params, self.state, x, train=False, rng=None, mask=mask,
            rnn_state=self.rnn_state if self.rnn_state else None,
        )
        self.rnn_state = new_rnn
        if single_step and out.ndim == 3:
            out = out[:, :, -1]
        return out

    def rnn_clear_previous_state(self) -> None:
        self.rnn_state = {}

    def rnn_get_previous_state(self) -> Dict[str, Dict[str, jax.Array]]:
        return self.rnn_state

    def rnn_set_previous_state(self, state) -> None:
        self.rnn_state = state

    def _expects_sequence_input(self) -> bool:
        return isinstance(self.conf.input_type, RecurrentType)

    # ------------------------------------------------------------- params
    def num_params(self) -> int:
        leaves = jax.tree_util.tree_leaves(self.params)
        return int(sum(l.size for l in leaves))

    def params_flat(self) -> np.ndarray:
        """Single flat param vector — the reference's contiguous-params
        invariant (coefficients.bin), reproduced for serialization parity."""
        from jax.flatten_util import ravel_pytree

        flat, _ = ravel_pytree(self.params)
        return np.asarray(flat)

    def set_params_flat(self, vec) -> None:
        from jax.flatten_util import ravel_pytree

        _, unravel = ravel_pytree(self.params)
        self.params = jax.tree_util.tree_map(
            lambda a: a, unravel(jnp.asarray(vec))
        )
        self._output_fn_cache.clear()

    def get_layer_params(self, i: int) -> Dict[str, jax.Array]:
        return self.params[self.conf.layer_name(i)]

    def evaluate(self, iterator_or_features, labels=None, mask=None):
        """Classification evaluation (reference: MultiLayerNetwork.evaluate)."""
        from ..train.evaluation import Evaluation

        ev = Evaluation()
        for feats, labs, msk, lmsk in _as_batches(iterator_or_features, labels, mask):
            out = self.output(feats, mask=msk)
            ev.eval(np.asarray(labs), np.asarray(out), mask=None if lmsk is None else np.asarray(lmsk))
        return ev

    def evaluate_regression(self, iterator_or_features, labels=None):
        from ..train.evaluation import RegressionEvaluation

        ev = RegressionEvaluation()
        for feats, labs, msk, _ in _as_batches(iterator_or_features, labels, None):
            out = self.output(feats, mask=msk)
            ev.eval(np.asarray(labs), np.asarray(out))
        return ev

    def summary(self) -> str:
        lines = [f"{'idx':<4}{'name':<28}{'type':<30}{'params':>10}"]
        total = 0
        for i, layer in enumerate(self.layers):
            name = self.conf.layer_name(i)
            n = sum(int(a.size) for a in self.params.get(name, {}).values()) if self._initialized else 0
            total += n
            lines.append(f"{i:<4}{name:<28}{type(layer).__name__:<30}{n:>10}")
        lines.append(f"Total params: {total}")
        return "\n".join(lines)

    def clone(self) -> "MultiLayerNetwork":
        m = MultiLayerNetwork(self.conf)
        if self._initialized:
            m.params = jax.tree_util.tree_map(lambda a: a, self.params)
            m.state = jax.tree_util.tree_map(lambda a: a, self.state)
            m._persistent_keys = dict(self._persistent_keys)
            m._initialized = True
        return m


# Alias with the TPU-native project's own idiom
Sequential = MultiLayerNetwork


def _as_batches(data, labels, mask):
    """Normalize (features, labels) / DataSet / iterator into batch tuples."""
    from ..data.dataset import DataSet

    if labels is not None:
        yield data, labels, mask, None
        return
    if isinstance(data, DataSet):
        yield data.features, data.labels, data.features_mask, data.labels_mask
        return
    for item in data:
        if isinstance(item, DataSet):
            yield item.features, item.labels, item.features_mask, item.labels_mask
        else:
            f, l = item[0], item[1]
            yield f, l, None, None
