"""Weight initialization.

Parity with the reference's ``org.deeplearning4j.nn.weights.WeightInit`` enum
(canonical: deeplearning4j-nn). Fan-in/fan-out semantics follow the reference:
for a dense W of shape [nIn, nOut], fanIn=nIn, fanOut=nOut; for conv kernels
fan includes the receptive field.
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.config import register_config


class WeightInit(enum.Enum):
    ZERO = "ZERO"
    ONES = "ONES"
    IDENTITY = "IDENTITY"
    NORMAL = "NORMAL"  # N(0, 1/sqrt(fanIn))
    UNIFORM = "UNIFORM"  # U(-a, a), a = 1/sqrt(fanIn)
    XAVIER = "XAVIER"  # N(0, 2/(fanIn+fanOut))
    XAVIER_UNIFORM = "XAVIER_UNIFORM"  # U +- sqrt(6/(fanIn+fanOut))
    XAVIER_FAN_IN = "XAVIER_FAN_IN"  # N(0, 1/fanIn)
    RELU = "RELU"  # He normal: N(0, 2/fanIn)
    RELU_UNIFORM = "RELU_UNIFORM"  # U +- sqrt(6/fanIn)
    SIGMOID_UNIFORM = "SIGMOID_UNIFORM"  # U +- 4*sqrt(6/(fanIn+fanOut))
    LECUN_NORMAL = "LECUN_NORMAL"  # N(0, 1/fanIn)
    LECUN_UNIFORM = "LECUN_UNIFORM"  # U +- sqrt(3/fanIn)
    VAR_SCALING_NORMAL_FAN_IN = "VAR_SCALING_NORMAL_FAN_IN"
    VAR_SCALING_NORMAL_FAN_OUT = "VAR_SCALING_NORMAL_FAN_OUT"
    VAR_SCALING_NORMAL_FAN_AVG = "VAR_SCALING_NORMAL_FAN_AVG"
    VAR_SCALING_UNIFORM_FAN_IN = "VAR_SCALING_UNIFORM_FAN_IN"
    VAR_SCALING_UNIFORM_FAN_OUT = "VAR_SCALING_UNIFORM_FAN_OUT"
    VAR_SCALING_UNIFORM_FAN_AVG = "VAR_SCALING_UNIFORM_FAN_AVG"
    DISTRIBUTION = "DISTRIBUTION"

    @classmethod
    def from_any(cls, w) -> "WeightInit":
        if isinstance(w, WeightInit):
            return w
        return cls[str(w).upper()]


@register_config
@dataclasses.dataclass(frozen=True)
class Distribution:
    """Custom distribution for WeightInit.DISTRIBUTION (reference: org.deeplearning4j.nn.conf.distribution.*)."""

    kind: str = "normal"  # normal|uniform|truncated_normal|constant|orthogonal
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0
    value: float = 0.0
    gain: float = 1.0


def init_weights(
    key: jax.Array,
    shape: Sequence[int],
    weight_init: WeightInit,
    fan_in: float,
    fan_out: float,
    distribution: Optional[Distribution] = None,
    dtype=jnp.float32,
) -> jax.Array:
    w = WeightInit.from_any(weight_init)
    shape = tuple(int(s) for s in shape)

    def normal(std: float) -> jax.Array:
        return std * jax.random.normal(key, shape, dtype)

    def uniform(a: float) -> jax.Array:
        return jax.random.uniform(key, shape, dtype, minval=-a, maxval=a)

    if w is WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if w is WeightInit.ONES:
        return jnp.ones(shape, dtype)
    if w is WeightInit.IDENTITY:
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2-D weight")
        return jnp.eye(shape[0], dtype=dtype)
    if w is WeightInit.NORMAL:
        return normal(1.0 / math.sqrt(fan_in))
    if w is WeightInit.UNIFORM:
        return uniform(1.0 / math.sqrt(fan_in))
    if w is WeightInit.XAVIER:
        return normal(math.sqrt(2.0 / (fan_in + fan_out)))
    if w is WeightInit.XAVIER_UNIFORM:
        return uniform(math.sqrt(6.0 / (fan_in + fan_out)))
    if w is WeightInit.XAVIER_FAN_IN:
        return normal(math.sqrt(1.0 / fan_in))
    if w is WeightInit.RELU:
        return normal(math.sqrt(2.0 / fan_in))
    if w is WeightInit.RELU_UNIFORM:
        return uniform(math.sqrt(6.0 / fan_in))
    if w is WeightInit.SIGMOID_UNIFORM:
        return uniform(4.0 * math.sqrt(6.0 / (fan_in + fan_out)))
    if w is WeightInit.LECUN_NORMAL:
        return normal(math.sqrt(1.0 / fan_in))
    if w is WeightInit.LECUN_UNIFORM:
        return uniform(math.sqrt(3.0 / fan_in))
    if w.value.startswith("VAR_SCALING"):
        mode = w.value.rsplit("_", 2)[-2:]
        fan = {"IN": fan_in, "OUT": fan_out, "AVG": 0.5 * (fan_in + fan_out)}[mode[1]]
        if "NORMAL" in w.value:
            return normal(math.sqrt(1.0 / fan))
        return uniform(math.sqrt(3.0 / fan))
    if w is WeightInit.DISTRIBUTION:
        d = distribution or Distribution()
        if d.kind == "normal":
            return d.mean + d.std * jax.random.normal(key, shape, dtype)
        if d.kind == "truncated_normal":
            return d.mean + d.std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
        if d.kind == "uniform":
            return jax.random.uniform(key, shape, dtype, minval=d.lower, maxval=d.upper)
        if d.kind == "constant":
            return jnp.full(shape, d.value, dtype)
        if d.kind == "orthogonal":
            return d.gain * jax.nn.initializers.orthogonal()(key, shape, dtype)
        raise ValueError(f"Unknown distribution kind {d.kind!r}")
    raise ValueError(f"Unhandled weight init {w}")


def conv_fans(kernel: Sequence[int], c_in: int, c_out: int, depth_mult: int = 1) -> Tuple[float, float]:
    """Fan-in/out for conv kernels, matching the reference's convention."""
    rf = 1
    for k in kernel:
        rf *= int(k)
    return float(c_in * rf), float(c_out * rf * depth_mult) / max(1, depth_mult)
