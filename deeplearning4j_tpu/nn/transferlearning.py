"""Transfer learning.

Reference: org.deeplearning4j.nn.transferlearning.{TransferLearning.Builder,
FineTuneConfiguration} (SURVEY.md §2.2 "Core utilities"): freeze layers below
a feature-extraction boundary, replace/append output layers, override training
hyperparameters, keep pretrained weights for retained layers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

import jax

from ..core.config import register_config
from .activations import Activation
from .conf import MultiLayerConfiguration
from .layers.base import Layer
from .sequential import MultiLayerNetwork
from .weights import WeightInit


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class FineTuneConfiguration:
    """Hyperparameter overrides applied to all non-frozen layers
    (reference: FineTuneConfiguration)."""

    updater: Optional[Any] = None
    activation: Optional[Activation] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None

    def apply_to(self, layer: Layer) -> Layer:
        updates = {}
        if self.updater is not None:
            updates["updater"] = self.updater
        if self.activation is not None and layer.activation is not None:
            updates["activation"] = self.activation
        if self.l1 is not None:
            updates["l1"] = self.l1
        if self.l2 is not None:
            updates["l2"] = self.l2
        if self.dropout is not None:
            updates["dropout"] = self.dropout
        return dataclasses.replace(layer, **updates) if updates else layer


class TransferLearningBuilder:
    """Reference: TransferLearning.Builder over a trained MultiLayerNetwork."""

    def __init__(self, model: MultiLayerNetwork) -> None:
        if not model._initialized:
            raise ValueError("Transfer learning requires an initialized model")
        self.model = model
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_until: Optional[int] = None
        self._n_removed = 0
        self._added: List[Layer] = []
        self._replaced_n_out: dict = {}

    def fine_tune_configuration(self, cfg: FineTuneConfiguration) -> "TransferLearningBuilder":
        self._fine_tune = cfg
        return self

    def set_feature_extractor(self, layer_index: int) -> "TransferLearningBuilder":
        """Freeze layers [0..layer_index] (reference: setFeatureExtractor)."""
        self._freeze_until = layer_index
        return self

    def remove_output_layer(self) -> "TransferLearningBuilder":
        self._n_removed += 1
        return self

    def remove_layers_from_output(self, n: int) -> "TransferLearningBuilder":
        self._n_removed += n
        return self

    def n_out_replace(self, layer_index: int, n_out: int,
                      weight_init: WeightInit = WeightInit.XAVIER) -> "TransferLearningBuilder":
        """Change a layer's nOut, re-initializing it and the next layer's nIn
        (reference: nOutReplace)."""
        self._replaced_n_out[layer_index] = (n_out, weight_init)
        return self

    def add_layer(self, layer: Layer) -> "TransferLearningBuilder":
        self._added.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        old_conf = self.model.conf
        layers = list(old_conf.layers)
        keep = len(layers) - self._n_removed
        layers = layers[:keep]
        reinit: set = set()

        for idx, (n_out, winit) in self._replaced_n_out.items():
            layers[idx] = dataclasses.replace(layers[idx], n_out=n_out, weight_init=winit)
            reinit.add(idx)
            # fix the next param layer's n_in
            for j in range(idx + 1, len(layers)):
                if hasattr(layers[j], "n_in"):
                    layers[j] = dataclasses.replace(layers[j], n_in=n_out)
                    reinit.add(j)
                    break

        if self._freeze_until is not None:
            for i in range(min(self._freeze_until + 1, len(layers))):
                layers[i] = dataclasses.replace(layers[i], frozen=True)

        if self._fine_tune is not None:
            for i in range(len(layers)):
                if not layers[i].frozen:
                    layers[i] = self._fine_tune.apply_to(layers[i])

        n_old = len(layers)
        # added layers: resolve shapes from the last retained layer's output
        if self._added:
            cur = old_conf.input_type
            if cur is not None:
                for l in layers:
                    l2 = l.with_input(cur)
                    cur = l2.output_type(cur)
                for add in self._added:
                    add = add.with_input(cur)
                    layers.append(add)
                    cur = add.output_type(cur)
            else:
                layers.extend(self._added)

        new_conf = dataclasses.replace(
            old_conf,
            layers=tuple(layers),
            seed=(self._fine_tune.seed if self._fine_tune and self._fine_tune.seed is not None
                  else old_conf.seed),
            updater=(self._fine_tune.updater if self._fine_tune and self._fine_tune.updater is not None
                     else old_conf.updater),
        )
        new_model = MultiLayerNetwork(new_conf).init()
        # carry over pretrained params for retained, un-reinitialized layers
        for i in range(n_old):
            if i in reinit:
                continue
            old_name = old_conf.layer_name(i)
            new_name = new_conf.layer_name(i)
            if old_name in self.model.params:
                old_p = self.model.params[old_name]
                new_p = new_model.params.get(new_name, {})
                if all(k in new_p and new_p[k].shape == v.shape for k, v in old_p.items()):
                    new_model.params[new_name] = jax.tree_util.tree_map(lambda a: a, old_p)
            if old_name in self.model.state:
                old_s = self.model.state[old_name]
                if old_s and new_model.state.get(new_name):
                    new_model.state[new_name] = jax.tree_util.tree_map(lambda a: a, old_s)
        return new_model


class TransferLearning:
    Builder = TransferLearningBuilder
