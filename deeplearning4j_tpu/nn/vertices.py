"""Graph vertices.

Reference: org.deeplearning4j.nn.conf.graph.{MergeVertex, ElementWiseVertex,
SubsetVertex, StackVertex, UnstackVertex, ScaleVertex, ShiftVertex,
L2NormalizeVertex, L2Vertex, PreprocessorVertex, ReshapeVertex} +
impl in org.deeplearning4j.nn.graph.vertex.impl (SURVEY.md §2.2
"ComputationGraph ... the ResNet-50 path").

A vertex is a param-free multi-input function with shape inference; layer
vertices wrap a Layer. Backprop is jax autodiff — the reference's per-vertex
doBackward code has no equivalent here.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.config import register_config
from .input_type import (
    Convolutional3DType,
    ConvolutionalType,
    FeedForwardType,
    InputType,
    RecurrentType,
)


@dataclasses.dataclass(frozen=True, kw_only=True)
class GraphVertex:
    """Base vertex config."""

    def output_type(self, *input_types: InputType) -> InputType:
        if len(input_types) != 1:
            raise ValueError(f"{type(self).__name__} expects 1 input")
        return input_types[0]

    def apply(self, *inputs: jax.Array) -> jax.Array:
        raise NotImplementedError


def _feature_axis(t: InputType) -> int:
    return 1  # all reference formats are channels/features-first at axis 1


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class MergeVertex(GraphVertex):
    """Concatenate along the feature/channel axis (reference: MergeVertex)."""

    def output_type(self, *input_types: InputType) -> InputType:
        first = input_types[0]
        if isinstance(first, FeedForwardType):
            return FeedForwardType(size=sum(t.size for t in input_types))
        if isinstance(first, RecurrentType):
            return RecurrentType(size=sum(t.size for t in input_types),
                                 timesteps=first.timesteps)
        if isinstance(first, ConvolutionalType):
            for t in input_types:
                if (t.height, t.width) != (first.height, first.width):
                    raise ValueError("MergeVertex: CNN spatial dims must match")
            return ConvolutionalType(height=first.height, width=first.width,
                                     channels=sum(t.channels for t in input_types))
        if isinstance(first, Convolutional3DType):
            return Convolutional3DType(
                depth=first.depth, height=first.height, width=first.width,
                channels=sum(t.channels for t in input_types),
            )
        raise ValueError(f"MergeVertex: unsupported input type {first}")

    def apply(self, *inputs: jax.Array) -> jax.Array:
        return jnp.concatenate(inputs, axis=1)


class ElementWiseOp(enum.Enum):
    ADD = "Add"
    SUBTRACT = "Subtract"
    PRODUCT = "Product"
    AVERAGE = "Average"
    MAX = "Max"


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class ElementWiseVertex(GraphVertex):
    """Element-wise combine (reference: ElementWiseVertex) — the residual-add
    vertex in ResNet."""

    op: ElementWiseOp = ElementWiseOp.ADD

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs: jax.Array) -> jax.Array:
        if self.op is ElementWiseOp.ADD:
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if self.op is ElementWiseOp.SUBTRACT:
            if len(inputs) != 2:
                raise ValueError("Subtract requires exactly 2 inputs")
            return inputs[0] - inputs[1]
        if self.op is ElementWiseOp.PRODUCT:
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if self.op is ElementWiseOp.AVERAGE:
            return sum(inputs) / len(inputs)
        if self.op is ElementWiseOp.MAX:
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(self.op)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class SubsetVertex(GraphVertex):
    """Feature-range subset [from, to] inclusive (reference: SubsetVertex)."""

    range_from: int = 0
    range_to: int = 0

    def output_type(self, *input_types: InputType) -> InputType:
        size = self.range_to - self.range_from + 1
        t = input_types[0]
        if isinstance(t, RecurrentType):
            return RecurrentType(size=size, timesteps=t.timesteps)
        if isinstance(t, ConvolutionalType):
            return ConvolutionalType(height=t.height, width=t.width, channels=size)
        return FeedForwardType(size=size)

    def apply(self, *inputs: jax.Array) -> jax.Array:
        return inputs[0][:, self.range_from : self.range_to + 1]


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class StackVertex(GraphVertex):
    """Stack along batch axis (reference: StackVertex)."""

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs: jax.Array) -> jax.Array:
        return jnp.concatenate(inputs, axis=0)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class UnstackVertex(GraphVertex):
    """Take slice ``from_idx`` of ``stack_size`` equal batch parts
    (reference: UnstackVertex)."""

    from_idx: int = 0
    stack_size: int = 1

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs: jax.Array) -> jax.Array:
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_idx * step : (self.from_idx + 1) * step]


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def apply(self, *inputs: jax.Array) -> jax.Array:
        return inputs[0] * self.scale


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def apply(self, *inputs: jax.Array) -> jax.Array:
        return inputs[0] + self.shift


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def apply(self, *inputs: jax.Array) -> jax.Array:
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + self.eps)
        return x / norm


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs (reference: L2Vertex) —
    triplet/siamese building block."""

    eps: float = 1e-8

    def output_type(self, *input_types: InputType) -> InputType:
        return FeedForwardType(size=1)

    def apply(self, *inputs: jax.Array) -> jax.Array:
        a, b = inputs
        axes = tuple(range(1, a.ndim))
        return jnp.sqrt(jnp.sum(jnp.square(a - b), axis=axes, keepdims=False)[:, None] + self.eps)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class ReshapeVertex(GraphVertex):
    """Reshape to the given per-example shape (reference: ReshapeVertex)."""

    shape: Tuple[int, ...] = ()

    def output_type(self, *input_types: InputType) -> InputType:
        s = self.shape
        if len(s) == 1:
            return FeedForwardType(size=s[0])
        if len(s) == 3:
            return ConvolutionalType(channels=s[0], height=s[1], width=s[2])
        if len(s) == 2:
            return RecurrentType(size=s[0], timesteps=s[1])
        raise ValueError(f"ReshapeVertex: unsupported shape {s}")

    def apply(self, *inputs: jax.Array) -> jax.Array:
        return inputs[0].reshape((inputs[0].shape[0],) + tuple(self.shape))
