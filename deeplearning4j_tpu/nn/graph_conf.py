"""ComputationGraph configuration.

Reference: org.deeplearning4j.nn.conf.ComputationGraphConfiguration +
GraphBuilder (reached via NeuralNetConfiguration.Builder().graphBuilder()).
Same construction surface: addInputs, addLayer(name, layer, *inputs),
addVertex(name, vertex, *inputs), setOutputs, setInputTypes; build() resolves
topology order, runs shape inference, fills nIn and inserts preprocessors.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple, Union

from ..core.config import register_config
from .conf import (
    BackpropType,
    GradientNormalization,
    NeuralNetConfigurationBuilder,
    WorkspaceMode,
    _needs,
    _preprocessor_for,
)
from .input_type import ConvolutionalFlatType, FeedForwardType, InputType, RecurrentType
from .layers.base import Layer
from .layers.output import BaseOutputLayer
from .vertices import GraphVertex


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class VertexSpec:
    """One node: either a layer or a function vertex, with named inputs.
    ``preprocessor`` is auto-inserted format conversion (reference:
    InputPreProcessor attached to a layer vertex)."""

    name: str = ""
    layer: Optional[Layer] = None
    vertex: Optional[GraphVertex] = None
    inputs: Tuple[str, ...] = ()
    preprocessor: Optional[Layer] = None


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class ComputationGraphConfiguration:
    network_inputs: Tuple[str, ...] = ()
    network_outputs: Tuple[str, ...] = ()
    vertices: Tuple[VertexSpec, ...] = ()  # in topological order after build()
    input_types: Tuple[InputType, ...] = ()
    seed: int = 0
    dtype: str = "float32"
    # Mixed precision (see MultiLayerConfiguration.compute_dtype): f32 master
    # params, forward/backward in compute_dtype (bf16 on the TPU MXU).
    compute_dtype: Optional[str] = None
    updater: Optional[object] = None
    backprop_type: BackpropType = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    gradient_normalization: GradientNormalization = GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0
    gradient_checkpointing: bool = False  # see MultiLayerConfiguration
    training_workspace_mode: WorkspaceMode = WorkspaceMode.ENABLED
    inference_workspace_mode: WorkspaceMode = WorkspaceMode.ENABLED

    def spec(self, name: str) -> VertexSpec:
        for v in self.vertices:
            if v.name == name:
                return v
        raise KeyError(name)


class GraphBuilder:
    """Reference: ComputationGraphConfiguration.GraphBuilder."""

    def __init__(self, parent: NeuralNetConfigurationBuilder) -> None:
        self._parent = parent
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._specs: Dict[str, VertexSpec] = {}
        self._input_types: List[InputType] = []
        self._backprop_type = BackpropType.STANDARD
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    addInputs = add_inputs

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        if name in self._specs or name in self._inputs:
            raise ValueError(f"Duplicate vertex name {name!r}")
        self._specs[name] = VertexSpec(name=name, layer=layer, inputs=tuple(inputs))
        return self

    addLayer = add_layer

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        if name in self._specs or name in self._inputs:
            raise ValueError(f"Duplicate vertex name {name!r}")
        self._specs[name] = VertexSpec(name=name, vertex=vertex, inputs=tuple(inputs))
        return self

    addVertex = add_vertex

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    setOutputs = set_outputs

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    setInputTypes = set_input_types

    def backprop_type(self, t: BackpropType) -> "GraphBuilder":
        self._backprop_type = t
        return self

    def tbptt_fwd_length(self, n: int) -> "GraphBuilder":
        self._tbptt_fwd = n
        return self

    def tbptt_back_length(self, n: int) -> "GraphBuilder":
        self._tbptt_back = n
        return self

    def _topo_sort(self) -> List[VertexSpec]:
        order: List[VertexSpec] = []
        placed = set(self._inputs)
        remaining = dict(self._specs)
        while remaining:
            progressed = False
            for name in list(remaining):
                spec = remaining[name]
                if all(i in placed for i in spec.inputs):
                    order.append(spec)
                    placed.add(name)
                    del remaining[name]
                    progressed = True
            if not progressed:
                raise ValueError(
                    f"Graph has a cycle or undefined inputs among: {sorted(remaining)}"
                )
        return order

    def build(self) -> ComputationGraphConfiguration:
        p = self._parent
        if not self._inputs:
            raise ValueError("Graph needs at least one input (add_inputs)")
        if not self._outputs:
            raise ValueError("Graph needs outputs (set_outputs)")
        for out in self._outputs:
            if out not in self._specs:
                raise ValueError(f"Output {out!r} is not a vertex")
        order = self._topo_sort()

        if self._input_types:
            if len(self._input_types) != len(self._inputs):
                raise ValueError("One InputType per network input required")
            types: Dict[str, InputType] = dict(zip(self._inputs, self._input_types))
            resolved: List[VertexSpec] = []
            for spec in order:
                in_types = [types[i] for i in spec.inputs]
                pre: Optional[Layer] = None
                if spec.layer is not None:
                    layer = p._apply_global_defaults(spec.layer)
                    need = _needs(layer)
                    cur = in_types[0]
                    pre = _preprocessor_for(cur, need)
                    if pre is not None:
                        cur = pre.output_type(cur)
                    if isinstance(cur, ConvolutionalFlatType) and need in ("ff", "any"):
                        cur = FeedForwardType(size=cur.flat_size())
                    layer = layer.with_input(cur)
                    out_t = layer.output_type(cur)
                    spec = dataclasses.replace(spec, layer=layer, preprocessor=pre)
                else:
                    out_t = spec.vertex.output_type(*in_types)
                types[spec.name] = out_t
                resolved.append(spec)
            order = resolved
        else:
            order = [
                dataclasses.replace(s, layer=p._apply_global_defaults(s.layer))
                if s.layer is not None else s
                for s in order
            ]

        return ComputationGraphConfiguration(
            network_inputs=tuple(self._inputs),
            network_outputs=tuple(self._outputs),
            vertices=tuple(order),
            input_types=tuple(self._input_types),
            seed=p._seed,
            dtype=p._dtype,
            compute_dtype=p._compute_dtype,
            updater=p._updater,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            gradient_normalization=p._grad_norm,
            gradient_normalization_threshold=p._grad_norm_threshold,
            gradient_checkpointing=p._grad_ckpt,
            training_workspace_mode=p._train_ws,
            inference_workspace_mode=p._infer_ws,
        )
