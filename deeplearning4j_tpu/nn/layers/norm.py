"""Normalization layers.

Reference: org.deeplearning4j.nn.conf.layers.{BatchNormalization,
LocalResponseNormalization} (+ cuDNN helpers CudnnBatchNormalizationHelper,
CudnnLocalResponseNormalizationHelper — here XLA fuses the normalization math
into neighbours, no helper needed).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ...core.config import register_config
from ..activations import Activation
from ..input_type import ConvolutionalType, FeedForwardType, InputType, RecurrentType
from .base import Layer, LayerContext, Params, State


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class BatchNormalizationLayer(Layer):
    """Batch normalization (reference: BatchNormalization).

    Params: gamma/beta [nOut]; state: running mean/var [nOut] updated with the
    reference's decay convention: global = decay*global + (1-decay)*batch.
    Supports FF [b,f], recurrent [b,f,t] and CNN [b,c,h,w] inputs (per-channel).
    """

    n_out: int = 0
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    gamma_init: float = 1.0
    beta_init: float = 0.0
    # Affine-precompute form (set by rewrite.BatchNormAffinePass): apply the
    # normalization as ONE fused multiply-add with per-channel
    # scale = gamma*rsqrt(var+eps), shift = beta - mean*scale, instead of the
    # 4-op subtract/rsqrt/scale/shift chain — same math to float tolerance,
    # but XLA fuses the single FMA into the neighbouring op's epilogue.
    fused: bool = False

    def with_input(self, input_type: InputType) -> "BatchNormalizationLayer":
        if self.n_out:
            return self
        if isinstance(input_type, (ConvolutionalType, RecurrentType)):
            n = input_type.channels if isinstance(input_type, ConvolutionalType) else input_type.size
        else:
            n = input_type.flat_size()
        return dataclasses.replace(self, n_out=n)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return () if self.lock_gamma_beta else ("gamma", "beta")

    def weight_param_names(self) -> Tuple[str, ...]:
        return ()  # reference never regularizes gamma/beta

    def init(self, key: jax.Array, dtype: Any) -> Params:
        if self.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((self.n_out,), self.gamma_init, dtype),
            "beta": jnp.full((self.n_out,), self.beta_init, dtype),
        }

    def init_state(self, dtype: Any) -> State:
        return {
            "mean": jnp.zeros((self.n_out,), dtype),
            "var": jnp.ones((self.n_out,), dtype),
        }

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        # reduce over all axes except the feature axis (1)
        axes = (0,) + tuple(range(2, x.ndim))
        bshape = (1, self.n_out) + (1,) * (x.ndim - 2)
        # statistics always in >= f32: under bf16 mixed precision the batch
        # moments and running stats would otherwise lose too many mantissa
        # bits (running state arrives in the master dtype and stays there)
        stat_dtype = jnp.promote_types(x.dtype, jnp.float32)
        x32 = x.astype(stat_dtype)
        if ctx.train:
            mean = jnp.mean(x32, axis=axes)
            var = jnp.var(x32, axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1.0 - self.decay) * mean.astype(state["mean"].dtype),
                "var": self.decay * state["var"] + (1.0 - self.decay) * var.astype(state["var"].dtype),
            }
        else:
            mean, var = state["mean"].astype(stat_dtype), state["var"].astype(stat_dtype)
            new_state = state
        if self.fused:
            rstd = jax.lax.rsqrt(var + self.eps)
            if self.lock_gamma_beta:
                scale, shift = rstd, -mean * rstd
            else:
                scale = params["gamma"].astype(stat_dtype) * rstd
                shift = params["beta"].astype(stat_dtype) - mean * scale
            xhat = x32 * scale.reshape(bshape) + shift.reshape(bshape)
        else:
            xhat = (x32 - mean.reshape(bshape)) * jax.lax.rsqrt(var.reshape(bshape) + self.eps)
            if not self.lock_gamma_beta:
                xhat = (xhat * params["gamma"].astype(stat_dtype).reshape(bshape)
                        + params["beta"].astype(stat_dtype).reshape(bshape))
        act = self.activation or Activation.IDENTITY
        return act(xhat).astype(x.dtype), new_state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class LocalResponseNormalizationLayer(Layer):
    """Cross-channel LRN over NCHW (reference: LocalResponseNormalization;
    AlexNet-era). y = x / (k + alpha*sum_adjacent(x^2))^beta."""

    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        half = int(self.n) // 2
        sq = x * x
        # sum over a window of channels: pad then reduce_window over axis 1
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            window_dimensions=(1, int(self.n), 1, 1),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (half, half), (0, 0), (0, 0)),
        )
        return x / jnp.power(self.k + self.alpha * summed, self.beta), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class LayerNormLayer(Layer):
    """Layer normalization over the feature axis. The reference exposes this as
    the ``layerNorm`` option on dense/RNN layers and the SameDiff ``layerNorm``
    op; here it is also a standalone layer (transformer building block)."""

    n_out: int = 0
    eps: float = 1e-5

    def with_input(self, input_type: InputType) -> "LayerNormLayer":
        if self.n_out:
            return self
        n = input_type.size if isinstance(input_type, RecurrentType) else input_type.flat_size()
        return dataclasses.replace(self, n_out=n)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("gamma", "beta")

    def weight_param_names(self) -> Tuple[str, ...]:
        return ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        return {
            "gamma": jnp.ones((self.n_out,), dtype),
            "beta": jnp.zeros((self.n_out,), dtype),
        }

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        feat_axis = 1 if x.ndim == 3 else -1  # recurrent [b,f,t] vs ff [b,f]
        # statistics in >= f32 under bf16 mixed precision (same rationale as
        # BatchNormalizationLayer; LN runs 2/block on the transformer path)
        stat_dtype = jnp.promote_types(x.dtype, jnp.float32)
        x32 = x.astype(stat_dtype)
        mean = jnp.mean(x32, axis=feat_axis, keepdims=True)
        var = jnp.var(x32, axis=feat_axis, keepdims=True)
        xhat = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        bshape = (1, self.n_out, 1) if x.ndim == 3 else (1, self.n_out)
        y = (xhat * params["gamma"].astype(stat_dtype).reshape(bshape)
             + params["beta"].astype(stat_dtype).reshape(bshape))
        act = self.activation or Activation.IDENTITY
        return act(y).astype(x.dtype), state
