"""Normalization layers.

Reference: org.deeplearning4j.nn.conf.layers.{BatchNormalization,
LocalResponseNormalization} (+ cuDNN helpers CudnnBatchNormalizationHelper,
CudnnLocalResponseNormalizationHelper — here XLA fuses the normalization math
into neighbours, no helper needed).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.config import register_config
from ..activations import Activation
from ..input_type import ConvolutionalType, FeedForwardType, InputType, RecurrentType
from .base import Layer, LayerContext, Params, State


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class BatchNormalizationLayer(Layer):
    """Batch normalization (reference: BatchNormalization).

    Params: gamma/beta [nOut]; state: running mean/var [nOut] updated with the
    reference's decay convention: global = decay*global + (1-decay)*batch.
    Supports FF [b,f], recurrent [b,f,t] and CNN [b,c,h,w] inputs (per-channel).
    """

    n_out: int = 0
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    gamma_init: float = 1.0
    beta_init: float = 0.0
    # Affine-precompute form (set by rewrite.BatchNormAffinePass): apply the
    # normalization as ONE fused multiply-add with per-channel
    # scale = gamma*rsqrt(var+eps), shift = beta - mean*scale, instead of the
    # 4-op subtract/rsqrt/scale/shift chain — same math to float tolerance,
    # but XLA fuses the single FMA into the neighbouring op's epilogue.
    fused: bool = False
    # Distributed batch norm (MLPerf TPU-pods paper, arxiv 1909.09756):
    # training batch statistics are averaged over groups of this many
    # adjacent data-parallel replicas instead of whichever batch slice one
    # replica sees — the per-chip batch shrinks as DP widens and
    # per-replica moments degrade. None inherits the trainer's
    # bn_group_size= default (and stays fully local outside a
    # DistributedTrainer). Running-stat state keeps its [n_out] shape, so
    # checkpoints are group-size independent.
    stats_axis_group: Optional[int] = None

    def with_input(self, input_type: InputType) -> "BatchNormalizationLayer":
        if self.n_out:
            return self
        if isinstance(input_type, (ConvolutionalType, RecurrentType)):
            n = input_type.channels if isinstance(input_type, ConvolutionalType) else input_type.size
        else:
            n = input_type.flat_size()
        return dataclasses.replace(self, n_out=n)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return () if self.lock_gamma_beta else ("gamma", "beta")

    def weight_param_names(self) -> Tuple[str, ...]:
        return ()  # reference never regularizes gamma/beta

    def init(self, key: jax.Array, dtype: Any) -> Params:
        if self.lock_gamma_beta:
            return {}
        return {
            "gamma": jnp.full((self.n_out,), self.gamma_init, dtype),
            "beta": jnp.full((self.n_out,), self.beta_init, dtype),
        }

    def init_state(self, dtype: Any) -> State:
        return {
            "mean": jnp.zeros((self.n_out,), dtype),
            "var": jnp.ones((self.n_out,), dtype),
        }

    def _stats_group(self, ctx: LayerContext) -> Optional[int]:
        """Resolved statistics group size (replicas per group), or None
        for the classic local spelling. Layer field wins over the
        trainer's ``bn_group_size=`` default; validated against the data
        axis at trace time."""
        dist = ctx.dist
        if dist is None:
            return None
        g = (self.stats_axis_group if self.stats_axis_group is not None
             else dist.bn_group_size)
        if g is None:
            return None
        g = int(g)
        if g < 1 or dist.n_shards % g:
            raise ValueError(
                f"BatchNormalization stats_axis_group={g} must divide the "
                f"data axis ({dist.n_shards} shards)")
        return g

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        # reduce over all axes except the feature axis (1)
        axes = (0,) + tuple(range(2, x.ndim))
        bshape = (1, self.n_out) + (1,) * (x.ndim - 2)
        # statistics always in >= f32: under bf16 mixed precision the batch
        # moments and running stats would otherwise lose too many mantissa
        # bits (running state arrives in the master dtype and stays there)
        stat_dtype = jnp.promote_types(x.dtype, jnp.float32)
        x32 = x.astype(stat_dtype)
        group = self._stats_group(ctx) if ctx.train else None
        if ctx.train and group is not None and ctx.dist.axis is None:
            # GSPMD path: x is the GLOBAL batch; one group = the rows of
            # `group` adjacent replicas (the batch-dim sharding places row
            # blocks on replicas in order), spelled as a reshape so XLA
            # keeps each group's moments on its own devices
            return self._apply_grouped_global(params, state, x, x32,
                                              stat_dtype, group, ctx)
        if ctx.train:
            if group is not None:
                # explicit (shard_map) path: x is this replica's shard —
                # group moments are slice-local sums psummed over the
                # replica groups of the data axis
                dist = ctx.dist
                groups = [list(range(i, i + group))
                          for i in range(0, dist.n_shards, group)]
                s1 = jnp.sum(x32, axis=axes)
                s2 = jnp.sum(jnp.square(x32), axis=axes)
                tot = jax.lax.psum(jnp.stack([s1, s2]), dist.axis,
                                   axis_index_groups=groups)
                denom = float(x32.size // self.n_out) * group
                mean = tot[0] / denom
                var = jnp.maximum(tot[1] / denom - jnp.square(mean), 0.0)
            else:
                mean = jnp.mean(x32, axis=axes)
                var = jnp.var(x32, axis=axes)
            # grouped: each replica folds ITS group's moments into the
            # running stats; the trainer's cross-replica state average
            # then yields the across-group mean (same value the GSPMD
            # spelling writes directly)
            new_state = {
                "mean": self.decay * state["mean"] + (1.0 - self.decay) * mean.astype(state["mean"].dtype),
                "var": self.decay * state["var"] + (1.0 - self.decay) * var.astype(state["var"].dtype),
            }
        else:
            mean, var = state["mean"].astype(stat_dtype), state["var"].astype(stat_dtype)
            new_state = state
        if self.fused:
            rstd = jax.lax.rsqrt(var + self.eps)
            if self.lock_gamma_beta:
                scale, shift = rstd, -mean * rstd
            else:
                scale = params["gamma"].astype(stat_dtype) * rstd
                shift = params["beta"].astype(stat_dtype) - mean * scale
            xhat = x32 * scale.reshape(bshape) + shift.reshape(bshape)
        else:
            xhat = (x32 - mean.reshape(bshape)) * jax.lax.rsqrt(var.reshape(bshape) + self.eps)
            if not self.lock_gamma_beta:
                xhat = (xhat * params["gamma"].astype(stat_dtype).reshape(bshape)
                        + params["beta"].astype(stat_dtype).reshape(bshape))
        act = self.activation or Activation.IDENTITY
        return act(xhat).astype(x.dtype), new_state

    def _apply_grouped_global(self, params: Params, state: State,
                              x: jax.Array, x32: jax.Array, stat_dtype,
                              group: int, ctx: LayerContext) -> Tuple[jax.Array, State]:
        """Grouped statistics over a GLOBAL batch array (the implicit
        GSPMD trainer path): reshape [B, ...] -> [G, B/G, ...] so each
        group of ``group`` adjacent replicas normalizes with its own
        moments (same moments as the explicit path's grouped psum — the
        batch-dim sharding lays contiguous row blocks out in replica
        order). Running stats take the across-group mean, which is what
        the explicit path's per-replica update + trainer state average
        converges to, so both paths write identical state."""
        dist = ctx.dist
        n_groups = dist.n_shards // group
        b = x32.shape[0]
        if b % max(n_groups, 1):
            raise ValueError(
                f"global batch {b} not divisible into {n_groups} "
                f"batch-norm statistics groups")
        xg = x32.reshape((n_groups, b // n_groups) + x32.shape[1:])
        axes_g = (1,) + tuple(range(3, xg.ndim))
        mean_g = jnp.mean(xg, axis=axes_g)  # [G, C]
        var_g = jnp.maximum(
            jnp.mean(jnp.square(xg), axis=axes_g) - jnp.square(mean_g), 0.0)
        gshape = (n_groups, 1, self.n_out) + (1,) * (xg.ndim - 3)
        # per-group affine form: gamma/beta fold into scale/shift like the
        # fused spelling (same math to float tolerance as the 4-op chain)
        rstd_g = jax.lax.rsqrt(var_g + self.eps)
        if self.lock_gamma_beta:
            scale_g, shift_g = rstd_g, -mean_g * rstd_g
        else:
            scale_g = params["gamma"].astype(stat_dtype)[None, :] * rstd_g
            shift_g = params["beta"].astype(stat_dtype)[None, :] - mean_g * scale_g
        yg = xg * scale_g.reshape(gshape) + shift_g.reshape(gshape)
        new_state = {
            "mean": self.decay * state["mean"]
            + (1.0 - self.decay) * jnp.mean(mean_g, axis=0).astype(state["mean"].dtype),
            "var": self.decay * state["var"]
            + (1.0 - self.decay) * jnp.mean(var_g, axis=0).astype(state["var"].dtype),
        }
        act = self.activation or Activation.IDENTITY
        return act(yg.reshape(x32.shape)).astype(x.dtype), new_state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class LocalResponseNormalizationLayer(Layer):
    """Cross-channel LRN over NCHW (reference: LocalResponseNormalization;
    AlexNet-era). y = x / (k + alpha*sum_adjacent(x^2))^beta."""

    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        half = int(self.n) // 2
        sq = x * x
        # sum over a window of channels: pad then reduce_window over axis 1
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            window_dimensions=(1, int(self.n), 1, 1),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (half, half), (0, 0), (0, 0)),
        )
        return x / jnp.power(self.k + self.alpha * summed, self.beta), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class LayerNormLayer(Layer):
    """Layer normalization over the feature axis. The reference exposes this as
    the ``layerNorm`` option on dense/RNN layers and the SameDiff ``layerNorm``
    op; here it is also a standalone layer (transformer building block)."""

    n_out: int = 0
    eps: float = 1e-5

    def with_input(self, input_type: InputType) -> "LayerNormLayer":
        if self.n_out:
            return self
        n = input_type.size if isinstance(input_type, RecurrentType) else input_type.flat_size()
        return dataclasses.replace(self, n_out=n)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("gamma", "beta")

    def weight_param_names(self) -> Tuple[str, ...]:
        return ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        return {
            "gamma": jnp.ones((self.n_out,), dtype),
            "beta": jnp.zeros((self.n_out,), dtype),
        }

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        feat_axis = 1 if x.ndim == 3 else -1  # recurrent [b,f,t] vs ff [b,f]
        # statistics in >= f32 under bf16 mixed precision (same rationale as
        # BatchNormalizationLayer; LN runs 2/block on the transformer path)
        stat_dtype = jnp.promote_types(x.dtype, jnp.float32)
        x32 = x.astype(stat_dtype)
        mean = jnp.mean(x32, axis=feat_axis, keepdims=True)
        var = jnp.var(x32, axis=feat_axis, keepdims=True)
        xhat = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        bshape = (1, self.n_out, 1) if x.ndim == 3 else (1, self.n_out)
        y = (xhat * params["gamma"].astype(stat_dtype).reshape(bshape)
             + params["beta"].astype(stat_dtype).reshape(bshape))
        act = self.activation or Activation.IDENTITY
        return act(y).astype(x.dtype), state
