"""Recurrent layers: LSTM, GravesLSTM, SimpleRnn, Bidirectional + wrappers.

Reference: org.deeplearning4j.nn.conf.layers.{LSTM, GravesLSTM,
GravesBidirectionalLSTM, SimpleRnn}, impl org.deeplearning4j.nn.layers.
recurrent.LSTMHelpers (canonical: deeplearning4j-nn) with the cuDNN LSTM
helper (CudnnLSTMHelper) as the accelerated path.

TPU design (SURVEY.md §7 hard part #2): the whole sequence's input projection
is ONE batched matmul [b*t, nIn]@[nIn, 4n] (MXU-sized), then a ``lax.scan``
carries (h, c) through time with only the [b, n]@[n, 4n] recurrent matmul
inside the loop. XLA unrolls/pipelines the scan; there is no per-timestep
dispatch (the reference pays a JNI round-trip per gate op per step on the
non-cuDNN path).

Conventions preserved from the reference:
* data format [batch, size, time] (NCW)
* gate order in the fused weight columns: [i, f, o, g]
  (input, forget, output, cell-input — reference LSTMParamInitializer)
* weights: W [nIn, 4n], RW [n, 4n] (+3n peephole columns for GravesLSTM), b [4n]
* ``forget_gate_bias_init`` default 1.0
* masked timesteps: state carried through unchanged, output zeroed
* stateful streaming via carried (h, c) — rnnTimeStep / TBPTT semantics
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...core.config import register_config
from ..activations import Activation
from ..input_type import FeedForwardType, InputType, RecurrentType
from ..weights import WeightInit, init_weights
from .base import Layer, LayerContext, Params, State, apply_input_dropout


def _lstm_scan(
    x_proj: jax.Array,  # [b, t, 4n] precomputed x@W + b
    rw: jax.Array,      # [n, 4n]
    h0: jax.Array,      # [b, n]
    c0: jax.Array,      # [b, n]
    mask: Optional[jax.Array],  # [b, t] or None
    gate_act,
    cell_act,
    peephole: Optional[jax.Array] = None,  # [3, n] (pi, pf, po) or None
):
    n = h0.shape[-1]

    def step(carry, inp):
        h, c = carry
        if mask is None:
            xp = inp
            m = None
        else:
            xp, m = inp
        z = xp + h @ rw  # [b, 4n]
        zi, zf, zo, zg = jnp.split(z, 4, axis=-1)
        if peephole is not None:
            zi = zi + peephole[0] * c
            zf = zf + peephole[1] * c
        i = gate_act(zi)
        f = gate_act(zf)
        g = cell_act(zg)
        c_new = f * c + i * g
        if peephole is not None:
            zo = zo + peephole[2] * c_new
        o = gate_act(zo)
        h_new = o * cell_act(c_new)
        if m is not None:
            mm = m[:, None]
            c_new = mm * c_new + (1.0 - mm) * c
            h_out = mm * h_new
            h_new = mm * h_new + (1.0 - mm) * h
        else:
            h_out = h_new
        return (h_new, c_new), h_out

    xs = x_proj.transpose(1, 0, 2)  # [t, b, 4n]
    if mask is not None:
        inputs = (xs, mask.T.astype(x_proj.dtype))
    else:
        inputs = xs
    # helper seam (reference: cuDNN LSTMHelper): "scan" (one compiled
    # loop) by default, "unrolled" for short static sequences
    from ...ops import helpers

    (h_f, c_f), hs = helpers.rnn_sequence(inputs, step, (h0, c0))
    return hs.transpose(1, 2, 0), h_f, c_f  # [b, n, t]


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class LSTMLayer(Layer):
    """Standard LSTM, no peepholes (reference: conf.layers.LSTM)."""

    n_in: int = 0
    n_out: int = 0
    forget_gate_bias_init: float = 1.0
    gate_activation: Activation = Activation.SIGMOID

    peephole: bool = dataclasses.field(default=False, repr=False)

    def output_type(self, input_type: InputType) -> InputType:
        ts = input_type.timesteps if isinstance(input_type, RecurrentType) else None
        return RecurrentType(size=self.n_out, timesteps=ts)

    def with_input(self, input_type: InputType) -> "LSTMLayer":
        if self.n_in or not isinstance(input_type, RecurrentType):
            return self
        return dataclasses.replace(self, n_in=input_type.size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "RW", "b") + (("P",) if self.peephole else ())

    def init(self, key: jax.Array, dtype: Any) -> Params:
        k1, k2, k3 = jax.random.split(key, 3)
        wi = self.weight_init or WeightInit.XAVIER
        w = init_weights(k1, (self.n_in, 4 * self.n_out), wi,
                         self.n_in, 4 * self.n_out, self.weight_init_distribution, dtype)
        rw = init_weights(k2, (self.n_out, 4 * self.n_out), wi,
                          self.n_out, 4 * self.n_out, self.weight_init_distribution, dtype)
        b = jnp.zeros((4 * self.n_out,), dtype)
        # forget-gate bias block = columns [n, 2n)
        b = b.at[self.n_out : 2 * self.n_out].set(self.forget_gate_bias_init)
        p: Params = {"W": w, "RW": rw, "b": b}
        if self.peephole:
            p["P"] = 0.01 * jax.random.normal(k3, (3, self.n_out), dtype)
        return p

    def decode_state(self, batch: int, max_len: int, dtype: Any) -> State:
        # the LSTM decode carry is just (h, c) — no per-position cache
        return {"h": jnp.zeros((batch, self.n_out), dtype),
                "c": jnp.zeros((batch, self.n_out), dtype)}

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        b, _, t = x.shape
        xt = x.transpose(0, 2, 1)  # [b, t, nIn]
        x_proj = xt.reshape(b * t, self.n_in) @ params["W"] + params["b"]
        x_proj = x_proj.reshape(b, t, 4 * self.n_out)
        h0 = state.get("h")
        c0 = state.get("c")
        if h0 is None:
            h0 = jnp.zeros((b, self.n_out), x.dtype)
            c0 = jnp.zeros((b, self.n_out), x.dtype)
        cell_act = self.activation or Activation.TANH
        hs, h_f, c_f = _lstm_scan(
            x_proj, params["RW"], h0, c0, ctx.mask,
            self.gate_activation, cell_act,
            peephole=params.get("P"),
        )
        return hs, {"h": h_f, "c": c_f}


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class GravesLSTMLayer(LSTMLayer):
    """LSTM with peephole connections per Graves (2013) — reference:
    GravesLSTM, the char-RNN benchmark layer (BASELINE.json:9)."""

    peephole: bool = dataclasses.field(default=True, repr=False)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class GRULayer(Layer):
    """Gated recurrent unit (Cho 2014). The reference's layer zoo has no
    GRU (SURVEY.md:129 lists LSTM/GravesLSTM/SimpleRnn), but its Keras
    importer maps KerasGRU (SURVEY.md:137 '~60 KerasLayer subclasses') —
    this layer exists for that import path and as a first-class layer.

    Same TPU shape as LSTMLayer: the whole sequence's input projection is
    one [b*t, nIn]@[nIn, 3n] matmul, then lax.scan carries h with only the
    [b, n]@[n, 3n] recurrent matmul in the loop.

    Conventions (keras-compatible so import is a direct weight copy):
    * fused gate columns ordered [z, r, h~] (update, reset, candidate)
    * ``reset_after=True`` (keras v2+ default): candidate uses
      r * (h@RW_h + rb_h) — bias ``b`` has shape [2, 3n] (input row 0,
      recurrent row 1). ``reset_after=False`` (CuDNN-incompatible classic
      form): candidate uses (r*h)@RW_h — bias is [3n].
    * masked timesteps: state carried through unchanged, output zeroed
      (same contract as LSTMLayer/SimpleRnnLayer).
    """

    n_in: int = 0
    n_out: int = 0
    reset_after: bool = True
    gate_activation: Activation = Activation.SIGMOID

    def output_type(self, input_type: InputType) -> InputType:
        ts = input_type.timesteps if isinstance(input_type, RecurrentType) else None
        return RecurrentType(size=self.n_out, timesteps=ts)

    def with_input(self, input_type: InputType) -> "GRULayer":
        if self.n_in or not isinstance(input_type, RecurrentType):
            return self
        return dataclasses.replace(self, n_in=input_type.size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "RW", "b")

    def init(self, key: jax.Array, dtype: Any) -> Params:
        k1, k2 = jax.random.split(key)
        wi = self.weight_init or WeightInit.XAVIER
        n = self.n_out
        w = init_weights(k1, (self.n_in, 3 * n), wi, self.n_in, 3 * n,
                         self.weight_init_distribution, dtype)
        rw = init_weights(k2, (n, 3 * n), wi, n, 3 * n,
                          self.weight_init_distribution, dtype)
        b_shape = (2, 3 * n) if self.reset_after else (3 * n,)
        return {"W": w, "RW": rw, "b": jnp.zeros(b_shape, dtype)}

    def decode_state(self, batch: int, max_len: int, dtype: Any) -> State:
        return {"h": jnp.zeros((batch, self.n_out), dtype)}

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        b, _, t = x.shape
        n = self.n_out
        gate = self.gate_activation
        act = self.activation or Activation.TANH
        bias = params["b"]
        in_bias = bias[0] if self.reset_after else bias
        rec_bias = bias[1] if self.reset_after else None
        xt = x.transpose(0, 2, 1)  # [b, t, nIn]
        x_proj = (xt.reshape(b * t, self.n_in) @ params["W"]
                  + in_bias).reshape(b, t, 3 * n)
        h0 = state.get("h")
        if h0 is None:
            h0 = jnp.zeros((b, n), x.dtype)
        rw = params["RW"]
        mask = ctx.mask

        def step(h, inp):
            if mask is None:
                xp, m = inp, None
            else:
                xp, m = inp
            xz, xr, xh = jnp.split(xp, 3, axis=-1)
            if self.reset_after:
                rec = h @ rw + rec_bias  # [b, 3n]
                rz, rr, rh = jnp.split(rec, 3, axis=-1)
                z = gate(xz + rz)
                r = gate(xr + rr)
                hh = act(xh + r * rh)
            else:
                rec_zr = h @ rw[:, : 2 * n]
                z = gate(xz + rec_zr[:, :n])
                r = gate(xr + rec_zr[:, n:])
                hh = act(xh + (r * h) @ rw[:, 2 * n:])
            h_new = z * h + (1.0 - z) * hh
            if m is not None:
                mm = m[:, None]
                h_out = mm * h_new
                h_new = mm * h_new + (1.0 - mm) * h
            else:
                h_out = h_new
            return h_new, h_out

        xs = x_proj.transpose(1, 0, 2)
        inputs = (xs, mask.T.astype(x.dtype)) if mask is not None else xs
        from ...ops import helpers

        h_f, hs = helpers.rnn_sequence(inputs, step, h0)
        return hs.transpose(1, 2, 0), {"h": h_f}


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class SimpleRnnLayer(Layer):
    """Vanilla RNN: h_t = act(x_t W + h_{t-1} RW + b) (reference: SimpleRnn)."""

    n_in: int = 0
    n_out: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        ts = input_type.timesteps if isinstance(input_type, RecurrentType) else None
        return RecurrentType(size=self.n_out, timesteps=ts)

    def with_input(self, input_type: InputType) -> "SimpleRnnLayer":
        if self.n_in or not isinstance(input_type, RecurrentType):
            return self
        return dataclasses.replace(self, n_in=input_type.size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "RW", "b")

    def init(self, key: jax.Array, dtype: Any) -> Params:
        k1, k2 = jax.random.split(key)
        wi = self.weight_init or WeightInit.XAVIER
        return {
            "W": init_weights(k1, (self.n_in, self.n_out), wi, self.n_in, self.n_out,
                              self.weight_init_distribution, dtype),
            "RW": init_weights(k2, (self.n_out, self.n_out), wi, self.n_out, self.n_out,
                               self.weight_init_distribution, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
        }

    def decode_state(self, batch: int, max_len: int, dtype: Any) -> State:
        return {"h": jnp.zeros((batch, self.n_out), dtype)}

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        b, _, t = x.shape
        act = self.activation or Activation.TANH
        xt = x.transpose(0, 2, 1)
        x_proj = (xt.reshape(b * t, self.n_in) @ params["W"] + params["b"]).reshape(b, t, self.n_out)
        h0 = state.get("h")
        if h0 is None:
            h0 = jnp.zeros((b, self.n_out), x.dtype)
        mask = ctx.mask

        def step(h, inp):
            if mask is None:
                xp = inp
                m = None
            else:
                xp, m = inp
            h_new = act(xp + h @ params["RW"])
            if m is not None:
                mm = m[:, None]
                h_out = mm * h_new
                h_new = mm * h_new + (1.0 - mm) * h
            else:
                h_out = h_new
            return h_new, h_out

        xs = x_proj.transpose(1, 0, 2)
        inputs = (xs, mask.T.astype(x.dtype)) if mask is not None else xs
        h_f, hs = lax.scan(step, h0, inputs)
        return hs.transpose(1, 2, 0), {"h": h_f}


class BidirectionalMode(enum.Enum):
    CONCAT = "CONCAT"
    ADD = "ADD"
    MUL = "MUL"
    AVERAGE = "AVERAGE"


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class BidirectionalLayer(Layer):
    """Bidirectional wrapper around any recurrent layer (reference:
    conf.layers.recurrent.Bidirectional). GravesBidirectionalLSTM ==
    Bidirectional(GravesLSTM, CONCAT)."""

    fwd: Optional[Layer] = None
    mode: BidirectionalMode = BidirectionalMode.CONCAT

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.fwd.output_type(input_type)
        if self.mode is BidirectionalMode.CONCAT:
            return RecurrentType(size=inner.size * 2, timesteps=inner.timesteps)
        return inner

    def with_input(self, input_type: InputType) -> "BidirectionalLayer":
        return dataclasses.replace(self, fwd=self.fwd.with_input(input_type))

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return tuple(f"f_{n}" for n in self.fwd.trainable_param_names()) + tuple(
            f"b_{n}" for n in self.fwd.trainable_param_names()
        )

    def weight_param_names(self) -> Tuple[str, ...]:
        return tuple(f"f_{n}" for n in self.fwd.weight_param_names()) + tuple(
            f"b_{n}" for n in self.fwd.weight_param_names()
        )

    def init(self, key: jax.Array, dtype: Any) -> Params:
        kf, kb = jax.random.split(key)
        pf = self.fwd.init(kf, dtype)
        pb = self.fwd.init(kb, dtype)
        out = {f"f_{k}": v for k, v in pf.items()}
        out.update({f"b_{k}": v for k, v in pb.items()})
        return out

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        pf = {k[2:]: v for k, v in params.items() if k.startswith("f_")}
        pb = {k[2:]: v for k, v in params.items() if k.startswith("b_")}
        yf, _ = self.fwd.apply(pf, {}, x, ctx)
        # reverse time respecting mask (valid steps are left-aligned in DL4J)
        x_rev = jnp.flip(x, axis=2)
        ctx_rev = dataclasses.replace(
            ctx, mask=None if ctx.mask is None else jnp.flip(ctx.mask, axis=1)
        )
        yb, _ = self.fwd.apply(pb, {}, x_rev, ctx_rev)
        yb = jnp.flip(yb, axis=2)
        if self.mode is BidirectionalMode.CONCAT:
            y = jnp.concatenate([yf, yb], axis=1)
        elif self.mode is BidirectionalMode.ADD:
            y = yf + yb
        elif self.mode is BidirectionalMode.MUL:
            y = yf * yb
        else:
            y = 0.5 * (yf + yb)
        return y, state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class LastTimeStepLayer(Layer):
    """Extract the last (unmasked) timestep: [b, f, t] -> [b, f]
    (reference: recurrent.LastTimeStep wrapper)."""

    underlying: Optional[Layer] = None

    def output_type(self, input_type: InputType) -> InputType:
        it = self.underlying.output_type(input_type) if self.underlying else input_type
        return FeedForwardType(size=it.size)

    def with_input(self, input_type: InputType) -> "LastTimeStepLayer":
        if self.underlying is None:
            return self
        return dataclasses.replace(self, underlying=self.underlying.with_input(input_type))

    def has_params(self) -> bool:
        return self.underlying is not None and self.underlying.has_params()

    def trainable_param_names(self) -> Tuple[str, ...]:
        return self.underlying.trainable_param_names() if self.underlying else ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        return self.underlying.init(key, dtype) if self.underlying else {}

    def init_state(self, dtype: Any) -> State:
        return self.underlying.init_state(dtype) if self.underlying else {}

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        if self.underlying is not None:
            x, state = self.underlying.apply(params, state, x, ctx)
        if ctx.mask is not None:
            lengths = jnp.sum(ctx.mask.astype(jnp.int32), axis=1)
            idx = jnp.maximum(lengths - 1, 0)
            y = jnp.take_along_axis(x, idx[:, None, None], axis=2).squeeze(2)
        else:
            y = x[:, :, -1]
        return y, state

    def feed_forward_mask(self, mask, input_type):
        return None


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class MaskZeroLayer(Layer):
    """Sets input timesteps matching ``mask_value`` to zero and masks them
    downstream (reference: recurrent.MaskZeroLayer)."""

    underlying: Optional[Layer] = None
    mask_value: float = 0.0

    def output_type(self, input_type: InputType) -> InputType:
        return self.underlying.output_type(input_type) if self.underlying else input_type

    def with_input(self, input_type: InputType) -> "MaskZeroLayer":
        if self.underlying is None:
            return self
        return dataclasses.replace(self, underlying=self.underlying.with_input(input_type))

    def has_params(self) -> bool:
        return self.underlying is not None and self.underlying.has_params()

    def trainable_param_names(self) -> Tuple[str, ...]:
        return self.underlying.trainable_param_names() if self.underlying else ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        return self.underlying.init(key, dtype) if self.underlying else {}

    def init_state(self, dtype: Any) -> State:
        return self.underlying.init_state(dtype) if self.underlying else {}

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        not_masked = jnp.any(x != self.mask_value, axis=1)  # [b, t]
        mask = not_masked.astype(x.dtype)
        x = x * mask[:, None, :]
        ctx = dataclasses.replace(ctx, mask=mask)
        if self.underlying is None:
            return x, state
        return self.underlying.apply(params, state, x, ctx)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class TimeDistributedLayer(Layer):
    """Applies a feed-forward layer independently at every timestep
    (reference: recurrent.TimeDistributed). [b, f, t] -> [b, f', t]."""

    underlying: Optional[Layer] = None

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.underlying.output_type(FeedForwardType(size=input_type.size))
        return RecurrentType(size=inner.flat_size(), timesteps=input_type.timesteps)

    def with_input(self, input_type: InputType) -> "TimeDistributedLayer":
        return dataclasses.replace(
            self, underlying=self.underlying.with_input(FeedForwardType(size=input_type.size))
        )

    def has_params(self) -> bool:
        return self.underlying.has_params()

    def trainable_param_names(self) -> Tuple[str, ...]:
        return self.underlying.trainable_param_names()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        return self.underlying.init(key, dtype)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        b, f, t = x.shape
        flat = x.transpose(0, 2, 1).reshape(b * t, f)
        y, state = self.underlying.apply(params, state, flat, dataclasses.replace(ctx, mask=None))
        return y.reshape(b, t, -1).transpose(0, 2, 1), state
