"""SameDiff custom-layer escape hatch.

Reference: org.deeplearning4j.nn.conf.layers.samediff.{SameDiffLayer,
SameDiffLambdaLayer} (SURVEY.md §2.2 "Layer implementations" — the
user-defined-op seam): a layer whose forward is built from SameDiff ops
instead of a built-in implementation, usable inside MultiLayerNetwork and
ComputationGraph like any other layer.

TPU design: the user graph is evaluated through SameDiff._eval_graph INSIDE
the model's traced forward, so it fuses into the same single XLA program as
the built-in layers — no interpreter boundary, unlike the reference where a
SameDiffLayer drops into the op-by-op SameDiff session per call. Gradients
come from jax autodiff over the traced ops; defineGradient does not exist.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.config import register_config
from ..input_type import FeedForwardType, InputType
from ..weights import WeightInit, init_weights
from .base import Layer, LayerContext, Params, State, apply_input_dropout


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class SameDiffLambdaLayer(Layer):
    """Parameterless custom op (reference: SameDiffLambdaLayer).

    ``fn(sd, x) -> SDVariable`` builds the forward from SameDiff ops; a
    plain jnp function ``fn(x) -> array`` is also accepted (the TPU-native
    shortcut — both trace into the same program).
    """

    fn: Optional[Callable] = None
    # output shape relative to input; None = unchanged
    output_size: Optional[int] = None
    # full shape-inference override: InputType -> InputType (for ops that
    # change spatial structure, e.g. a space-to-depth reorg)
    output_type_fn: Optional[Callable] = None

    def output_type(self, input_type: InputType) -> InputType:
        if self.output_type_fn is not None:
            return self.output_type_fn(input_type)
        if self.output_size is not None:
            return FeedForwardType(size=self.output_size)
        return input_type

    def apply(self, params: Params, state: State, x: jax.Array,
              ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        out = _run_user_graph(self.fn, x, {})
        return out, state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class SameDiffLayer(Layer):
    """Parameterized custom layer (reference: SameDiffLayer).

    * ``param_shapes``: name -> shape (reference: defineParameters +
      SDLayerParams.addWeightParam)
    * ``define_layer(sd, x, params) -> SDVariable``: the forward, built
      from SameDiff ops on the ``sd`` handle; params arrive as SDVariables
      keyed by name. A plain-jnp ``define_layer(x, params)`` (no sd arg,
      by arity) is also accepted.
    * ``n_out``: declared output size (shape inference).
    """

    param_shapes: Optional[Dict[str, Tuple[int, ...]]] = None
    define_layer: Optional[Callable] = None
    n_out: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return FeedForwardType(size=self.n_out) if self.n_out else input_type

    def has_params(self) -> bool:
        return bool(self.param_shapes)

    def trainable_param_names(self) -> Tuple[str, ...]:
        return tuple(self.param_shapes or ())

    def init(self, key: jax.Array, dtype: Any) -> Params:
        out: Params = {}
        shapes = self.param_shapes or {}
        keys = jax.random.split(key, max(1, len(shapes)))
        for k, (name, shape) in zip(keys, sorted(shapes.items())):
            if len(shape) >= 2:
                out[name] = init_weights(
                    k, tuple(shape), self.weight_init or WeightInit.XAVIER,
                    fan_in=shape[-2], fan_out=shape[-1],
                    distribution=self.weight_init_distribution, dtype=dtype)
            else:  # vectors (biases) start at bias_init
                out[name] = jnp.full(tuple(shape), self.bias_init, dtype)
        return out

    def apply(self, params: Params, state: State, x: jax.Array,
              ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        out = _run_user_graph(self.define_layer, x, params)
        return out, state


def _run_user_graph(fn: Callable, x: jax.Array, params: Params) -> jax.Array:
    """Dispatch by arity: SameDiff-graph builders get (sd, x[, params]),
    plain jnp functions get (x[, params]). Both run inside the outer jit
    trace, compiling into the model's single XLA program."""
    import inspect

    if fn is None:
        raise ValueError("SameDiffLayer needs define_layer/fn")
    n_args = len(inspect.signature(fn).parameters)
    takes_params = bool(params)
    if n_args == (3 if takes_params else 2):
        from ...samediff.samediff import SameDiff

        sd = SameDiff.create()
        xv = sd.placeholder("input")
        pvars = {k: sd.placeholder(f"param_{k}") for k in params}
        out_var = fn(sd, xv, pvars) if takes_params else fn(sd, xv)
        feeds = {"input": x}
        feeds.update({f"param_{k}": v for k, v in params.items()})
        res = sd._eval_graph(feeds, dict(sd._values), [out_var.name])
        return res[out_var.name]
    return fn(x, params) if takes_params else fn(x)
