"""Input preprocessors — format conversions between layer families.

Reference: org.deeplearning4j.nn.conf.preprocessor.{CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor,
FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor, RnnToCnnPreProcessor}.
The config builder auto-inserts these at format boundaries during the
``setInputType`` walk, exactly like the reference. They are param-free layers.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax

from ...core.config import register_config
from ..input_type import (
    ConvolutionalType,
    FeedForwardType,
    InputType,
    RecurrentType,
)
from .base import Layer, LayerContext, Params, State


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class CnnToFeedForwardPreProcessor(Layer):
    """[b, c, h, w] -> [b, c*h*w] (reference flattening order)."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return FeedForwardType(size=self.channels * self.height * self.width)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        return x.reshape(x.shape[0], -1), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class FeedForwardToCnnPreProcessor(Layer):
    """[b, c*h*w] -> [b, c, h, w]."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return ConvolutionalType(height=self.height, width=self.width, channels=self.channels)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        return x.reshape(x.shape[0], self.channels, self.height, self.width), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class RnnToFeedForwardPreProcessor(Layer):
    """[b, f, t] -> [b*t, f] (time folded into batch, reference order)."""

    def output_type(self, input_type: InputType) -> InputType:
        return FeedForwardType(size=input_type.size)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        b, f, t = x.shape
        return x.transpose(0, 2, 1).reshape(b * t, f), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class FeedForwardToRnnPreProcessor(Layer):
    """[b*t, f] -> [b, f, t]; timesteps restored from config."""

    timesteps: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return RecurrentType(size=input_type.flat_size(), timesteps=self.timesteps or None)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        bt, f = x.shape
        t = self.timesteps
        return x.reshape(bt // t, t, f).transpose(0, 2, 1), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class CnnToRnnPreProcessor(Layer):
    """[b, c, h, w] -> [b, c*h*w, 1]-style sequence (reference: CnnToRnnPreProcessor
    treats each example as one timestep of size c*h*w; used for video via
    TimeDistributed in practice)."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return RecurrentType(size=self.channels * self.height * self.width, timesteps=1)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        return x.reshape(x.shape[0], -1, 1), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class RnnToCnnPreProcessor(Layer):
    """[b, c*h*w, t] -> [b*t, c, h, w]."""

    height: int = 0
    width: int = 0
    channels: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        return ConvolutionalType(height=self.height, width=self.width, channels=self.channels)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        b, f, t = x.shape
        flat = x.transpose(0, 2, 1).reshape(b * t, f)
        return flat.reshape(b * t, self.channels, self.height, self.width), state
