"""Feed-forward layers: Dense, Activation, Dropout, Embedding, PReLU.

Reference configs: org.deeplearning4j.nn.conf.layers.{DenseLayer,
ActivationLayer, DropoutLayer, EmbeddingLayer, EmbeddingSequenceLayer,
PReLULayer} (canonical: deeplearning4j-nn).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.config import register_config
from ..activations import Activation
from ..input_type import FeedForwardType, InputType, RecurrentType
from ..weights import WeightInit, init_weights
from .base import Layer, LayerContext, Params, State, apply_input_dropout


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class DenseLayer(Layer):
    """Fully connected layer: y = act(xW + b). Params W:[nIn,nOut] b:[1,nOut]."""

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, RecurrentType):
            # time-distributed path (3D apply branch): sequence in, sequence
            # out. In the builder pipeline dense normally sees FF input (a
            # RnnToFeedForward preprocessor is auto-inserted first).
            return RecurrentType(size=self.n_out, timesteps=input_type.timesteps)
        return FeedForwardType(size=self.n_out)

    def with_input(self, input_type: InputType) -> "DenseLayer":
        if self.n_in:
            return self
        if isinstance(input_type, RecurrentType):
            return dataclasses.replace(self, n_in=input_type.size)
        return dataclasses.replace(self, n_in=input_type.flat_size())

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        w = init_weights(
            key, (self.n_in, self.n_out), self.weight_init or WeightInit.XAVIER,
            fan_in=self.n_in, fan_out=self.n_out,
            distribution=self.weight_init_distribution, dtype=dtype,
        )
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        if x.ndim == 3:
            # time-distributed dense over recurrent [b, f, t] input (the
            # transformer FFN): one einsum the MXU tiles over batch*time
            y = jnp.einsum("bft,fg->bgt", x, params["W"])
            if self.has_bias:
                y = y + params["b"][None, :, None]
        else:
            y = x @ params["W"]
            if self.has_bias:
                y = y + params["b"]
        act = self.activation or Activation.SIGMOID  # reference default
        return act(y), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class ActivationLayer(Layer):
    """Applies an activation only (reference: ActivationLayer).

    ``alpha`` overrides the fixed slope/scale for LEAKYRELU (default 0.01)
    and ELU (default 1.0) — needed by the Keras importer, whose
    LeakyReLU/ELU layers carry arbitrary alphas (keras LeakyReLU default
    is 0.3)."""

    alpha: Optional[float] = None

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        act = self.activation or Activation.IDENTITY
        if self.alpha is not None:
            if act is Activation.LEAKYRELU:
                return jax.nn.leaky_relu(x, self.alpha), state
            if act is Activation.ELU:
                return jax.nn.elu(x, self.alpha), state
        return act(x), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class DropoutLayer(Layer):
    """Standalone dropout layer (reference: DropoutLayer). ``dropout`` is the
    retain probability, matching the reference's convention."""

    def __post_init__(self):
        if self.dropout is None:
            object.__setattr__(self, "dropout", 0.5)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        return apply_input_dropout(self, x, ctx), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class EmbeddingLayer(Layer):
    """Index -> embedding row lookup for single indices (reference:
    EmbeddingLayer). Input: [batch] or [batch, 1] integer ids. On TPU the
    lookup is a gather, which XLA maps efficiently; there is no sparse-update
    special path (full-dense grads are fine at TPU HBM bandwidth)."""

    n_in: int = 0  # vocab size
    n_out: int = 0
    has_bias: bool = False
    consumes_indices = True

    def output_type(self, input_type: InputType) -> InputType:
        return FeedForwardType(size=self.n_out)

    def with_input(self, input_type: InputType) -> "EmbeddingLayer":
        return self  # vocab size cannot be inferred from input shape

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        w = init_weights(
            key, (self.n_in, self.n_out), self.weight_init or WeightInit.XAVIER,
            fan_in=self.n_in, fan_out=self.n_out,
            distribution=self.weight_init_distribution, dtype=dtype,
        )
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx.squeeze(-1)
        y = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            y = y + params["b"]
        act = self.activation or Activation.IDENTITY
        return act(y), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class EmbeddingSequenceLayer(Layer):
    """Sequence of ids -> sequence of embeddings (reference:
    EmbeddingSequenceLayer). Input [batch, time] (or [batch, 1, time]) ids;
    output recurrent format [batch, n_out, time]."""

    n_in: int = 0
    n_out: int = 0
    has_bias: bool = False
    inference_mode: bool = False
    consumes_indices = True

    def output_type(self, input_type: InputType) -> InputType:
        ts = input_type.timesteps if isinstance(input_type, RecurrentType) else None
        return RecurrentType(size=self.n_out, timesteps=ts)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        w = init_weights(
            key, (self.n_in, self.n_out), self.weight_init or WeightInit.XAVIER,
            fan_in=self.n_in, fan_out=self.n_out,
            distribution=self.weight_init_distribution, dtype=dtype,
        )
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        idx = x.astype(jnp.int32)
        if idx.ndim == 3:  # [batch, 1, time]
            idx = idx.squeeze(1)
        emb = jnp.take(params["W"], idx, axis=0)  # [batch, time, n_out]
        if self.has_bias:
            emb = emb + params["b"]
        act = self.activation or Activation.IDENTITY
        return act(emb).transpose(0, 2, 1), state  # -> [batch, n_out, time]


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class PositionalEmbeddingLayer(Layer):
    """Learned absolute position embeddings added to a recurrent-format
    sequence: x[b, f, t] + P[:t].T. Transformer building block (the reference
    reaches BERT via SameDiff TF import — SURVEY.md §2.2 "TF import"; this is
    the native-layer equivalent used by the zoo BertEncoder)."""

    n_out: int = 0
    max_len: int = 512

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def with_input(self, input_type: InputType) -> "PositionalEmbeddingLayer":
        if self.n_out or not isinstance(input_type, RecurrentType):
            return self
        return dataclasses.replace(self, n_out=input_type.size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("P",)

    def weight_param_names(self) -> Tuple[str, ...]:
        return ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        return {"P": 0.02 * jax.random.normal(key, (self.max_len, self.n_out), dtype)}

    def decode_state(self, batch: int, max_len: int, dtype: Any) -> State:
        # incremental decode needs each row's absolute position to pick the
        # right embedding for a single-token step
        return {"pos": jnp.zeros((batch,), jnp.int32)}

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        t = x.shape[-1]
        pos = state.get("pos")
        if pos is None:
            return x + params["P"][:t].T[None], state
        pos = pos.astype(jnp.int32)
        idx = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # [b, t]
        pe = jnp.take(params["P"], jnp.clip(idx, 0, self.max_len - 1), axis=0)
        valid = (jnp.asarray(t, jnp.int32) if ctx.mask is None
                 else jnp.sum(ctx.mask > 0, axis=1).astype(jnp.int32))
        return x + pe.transpose(0, 2, 1), {"pos": pos + valid}


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class PReLULayer(Layer):
    """Parametric ReLU with learned per-element alpha (reference: PReLULayer)."""

    input_shape: Tuple[int, ...] = ()
    shared_axes: Tuple[int, ...] = ()  # 1-indexed feature axes to share alpha over

    def with_input(self, input_type: InputType) -> "PReLULayer":
        if self.input_shape:
            return self
        return dataclasses.replace(self, input_shape=tuple(input_type.shape(1)[1:]))

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        shape = list(self.input_shape)
        for ax in self.shared_axes:
            shape[ax - 1] = 1
        return {"W": jnp.zeros(tuple(shape), dtype)}

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        alpha = params["W"]
        return jnp.where(x >= 0, x, alpha * x), state
