"""Layer protocol.

The reference splits a layer into a config class (org.deeplearning4j.nn.conf.
layers.*) and an implementation class (org.deeplearning4j.nn.layers.*) bound to
a param view in the model's flat buffer. Here a layer is ONE immutable config
dataclass with pure functions:

* ``output_type(input)``   — InputType shape inference (reference: getOutputType)
* ``with_input(input)``    — returns a config with nIn/shape fields resolved
                             (reference: setNIn during setInputType walk)
* ``init(key, dtype)``     — build the param pytree (dict of named arrays,
                             names matching the reference's param keys W/b/RW/
                             gamma/beta... for checkpoint familiarity)
* ``init_state(dtype)``    — non-trainable state (BN running stats, RNN carry)
* ``apply(params, state, x, ctx)`` -> (y, new_state)

``apply`` is trace-friendly: no Python branching on array values; ``train`` is
a static Python bool baked into the jitted train/infer programs.

Backprop does not exist as a method — jax reverse-mode AD differentiates
``apply`` directly, which removes the reference's entire backpropGradient
codepath (and its class of fwd/bwd mismatch bugs).
"""

from __future__ import annotations

import dataclasses
from typing import Any, ClassVar, Dict, Optional, Tuple

import jax

from ...core.config import register_config
from ..activations import Activation
from ..input_type import InputType
from ..weights import Distribution, WeightInit

Params = Dict[str, Any]
State = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class DistContext:
    """Static data-parallel execution context for layers that compute
    cross-replica statistics (distributed batch norm). Set by
    ``DistributedTrainer`` when building its step; ``None`` everywhere
    else (single-device Solver, inference), so layers fall back to their
    local spelling.

    ``axis`` is the named data-mesh axis when the forward runs inside
    ``shard_map`` (the explicit strategy path — collectives like
    ``lax.psum`` may bind it); ``None`` on the implicit GSPMD path,
    where the batch array is GLOBAL and group statistics are spelled as
    a sharding-friendly reshape instead. ``n_shards`` is the data-axis
    width either way, and ``bn_group_size`` the trainer-level default
    statistics group size (overridable per layer).

    ``ep_axis``/``ep_shards`` name the expert-parallel mesh axis on the
    explicit path (``DistributedTrainer`` with
    ``moe_expert_parallel_rules`` and an explicit strategy): expert-dim
    params arrive sliced over that axis and MoE layers combine local
    expert outputs with collectives bound to it. ``None``/1 everywhere
    else (the implicit path shards experts through GSPMD instead)."""

    axis: Optional[str] = None
    n_shards: int = 1
    bn_group_size: Optional[int] = None
    ep_axis: Optional[str] = None
    ep_shards: int = 1


@dataclasses.dataclass(frozen=True)
class LayerContext:
    """Per-call dynamic context threaded through layer application."""

    train: bool = False
    rng: Optional[jax.Array] = None  # dropout/noise key (None in inference)
    mask: Optional[jax.Array] = None  # sequence mask [batch, time] where applicable
    dist: Optional[DistContext] = None  # data-parallel context (trainer only)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Layer:
    """Base layer config. Fields set to None inherit the network's global
    defaults (reference: NeuralNetConfiguration.Builder global conf)."""

    name: Optional[str] = None
    activation: Optional[Activation] = None
    weight_init: Optional[WeightInit] = None
    weight_init_distribution: Optional[Distribution] = None
    bias_init: float = 0.0
    dropout: Optional[float] = None  # retain-input semantics? see note below
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    weight_decay: Optional[float] = None
    updater: Optional[Any] = None  # per-layer updater config override
    frozen: bool = False  # transfer-learning freeze (reference: FrozenLayer)

    # True on layers whose input is integer INDICES (embedding lookups).
    # Inputs feeding such layers keep their integer dtype end-to-end: a
    # float cast — especially the bf16 compute cast — corrupts ids > 256
    # (bf16 has 8 mantissa bits). All other inputs are promoted to the
    # model float dtype as the reference does.
    consumes_indices: ClassVar[bool] = False

    # NOTE on dropout: the reference's layer-level ``dropOut(p)`` keeps each
    # input unit with probability p and scales by 1/p (inverted dropout with
    # p = RETAIN probability, applied to the layer INPUT). We preserve that
    # convention: ``dropout=0.8`` keeps 80% of inputs.

    # ---- shape inference ---------------------------------------------------
    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def with_input(self, input_type: InputType) -> "Layer":
        return self

    # ---- parameters --------------------------------------------------------
    def init(self, key: jax.Array, dtype: Any) -> Params:
        return {}

    def init_state(self, dtype: Any) -> State:
        return {}

    def decode_state(self, batch: int, max_len: int, dtype: Any) -> State:
        """Transient per-sequence carry for incremental autoregressive
        decode: a static-shape KV cache + position counter for attention
        layers, the (h, c) recurrent carry for RNN layers, a position
        offset for positional embeddings. Threaded through ``apply`` via
        the ``rnn_state`` channel (never persisted), so one preallocated
        pytree serves an entire generation — shapes depend only on
        ``(batch, max_len)``, never on how far decoding has advanced.
        Layers without decode-time state return {} (stateless layers are
        applied per step as-is)."""
        return {}

    def has_params(self) -> bool:
        return False

    # ---- forward -----------------------------------------------------------
    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        raise NotImplementedError

    # ---- mask propagation (reference: feedForwardMaskArray) ----------------
    def feed_forward_mask(self, mask: Optional[jax.Array], input_type: InputType) -> Optional[jax.Array]:
        return mask

    # ---- regularization contribution for the score (reference: calcRegularizationScore)
    def trainable_param_names(self) -> Tuple[str, ...]:
        return tuple()

    def weight_param_names(self) -> Tuple[str, ...]:
        """Params that l1/l2/weight-decay apply to (biases excluded)."""
        return tuple(n for n in self.trainable_param_names() if n not in ("b", "gb", "bb"))


def resolve(value, default):
    return default if value is None else value


def apply_input_dropout(cfg: Layer, x: jax.Array, ctx: LayerContext) -> jax.Array:
    """Inverted dropout on layer input, reference retain-probability semantics."""
    if cfg.dropout is None or not ctx.train or ctx.rng is None:
        return x
    retain = float(cfg.dropout)
    if retain >= 1.0:
        return x
    keep = jax.random.bernoulli(ctx.rng, retain, x.shape)
    return jax.numpy.where(keep, x / retain, 0.0).astype(x.dtype)


def apply_layer(layer, lparams, lstate, x, ctx, *, remat: bool = False):
    """Layer apply, optionally under jax.checkpoint: the backward then
    recomputes this layer's intermediates (attention probs, FFN hidden)
    instead of holding them in HBM — SURVEY §7's remat trade. Homed here
    next to LayerContext so both network classes import it cycle-free."""
    if not remat:
        return layer.apply(lparams, lstate, x, ctx)

    def fn(p, s, xx, key, mask):
        # dist is static config (axis name / group sizes), safe to close over
        c = LayerContext(train=ctx.train, rng=key, mask=mask, dist=ctx.dist)
        return layer.apply(p, s, xx, c)

    return jax.checkpoint(fn)(lparams, lstate, x, ctx.rng, ctx.mask)
