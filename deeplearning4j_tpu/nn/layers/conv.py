"""Convolution layers: 1D/2D/3D, depthwise, separable, transposed.

Reference configs: org.deeplearning4j.nn.conf.layers.{ConvolutionLayer,
Convolution1DLayer, Convolution3D, Deconvolution2D, DepthwiseConvolution2D,
SeparableConvolution2D} (canonical: deeplearning4j-nn); kernels in libnd4j
``ops/declarable/generic/nn/convo/`` with cuDNN platform helpers.

TPU design: every variant lowers to ONE ``lax.conv_general_dilated`` call that
XLA tiles onto the MXU — there is no helper/builtin split to manage (the
reference's cuDNN-vs-builtin seam exists because its builtin im2col path is
slow; XLA's conv emitter IS the fast path). Weight layouts kept in the
reference's shapes for checkpoint familiarity, reshaped at trace time (free —
XLA folds transposes into the conv).

Data format: NCHW at the API (reference default); ``data_format`` switches to
NHWC per-layer. XLA re-lays-out for the TPU either way.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...core.config import register_config
from ..activations import Activation
from ..input_type import Convolutional3DType, ConvolutionalType, InputType, RecurrentType
from ..weights import WeightInit, init_weights
from .base import Layer, LayerContext, Params, State, apply_input_dropout


class ConvolutionMode(enum.Enum):
    """Reference: org.deeplearning4j.nn.conf.ConvolutionMode."""

    STRICT = "Strict"      # like Truncate but errors if input not exactly covered
    TRUNCATE = "Truncate"  # floor((in + 2p - k)/s) + 1, explicit padding
    SAME = "Same"          # ceil(in/s), padding auto-computed
    CAUSAL = "Causal"      # 1D only: left-pad so output depends only on past


def _out_size(in_size: int, k: int, s: int, p: int, d: int, mode: ConvolutionMode) -> int:
    eff_k = (k - 1) * d + 1
    if mode is ConvolutionMode.SAME:
        return -(-in_size // s)  # ceil
    if mode is ConvolutionMode.CAUSAL:
        return -(-in_size // s)
    if mode is ConvolutionMode.STRICT:
        if (in_size + 2 * p - eff_k) % s != 0:
            raise ValueError(
                f"ConvolutionMode.STRICT: size {in_size} with k={k},s={s},p={p},d={d} "
                f"does not divide exactly; use TRUNCATE or SAME"
            )
    return (in_size + 2 * p - eff_k) // s + 1


def _lax_padding(mode: ConvolutionMode, pads: Sequence[int], ks: Sequence[int], ds: Sequence[int]):
    if mode is ConvolutionMode.SAME:
        return "SAME"
    if mode is ConvolutionMode.CAUSAL:
        return [((k - 1) * d, 0) for k, d in zip(ks, ds)]
    return [(p, p) for p in pads]


def _deconv_out_size(in_size: int, k: int, s: int, p: int, d: int, mode: ConvolutionMode) -> int:
    eff_k = (k - 1) * d + 1
    if mode is ConvolutionMode.SAME:
        return in_size * s
    return s * (in_size - 1) + eff_k - 2 * p


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class ConvolutionLayer(Layer):
    """2-D convolution. Weights: W [nOut, nIn, kH, kW], b [nOut]
    (reference layout, org.deeplearning4j.nn.params.ConvolutionParamInitializer)."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True
    data_format: str = "NCHW"

    def output_type(self, input_type: InputType) -> InputType:
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError(f"{type(self).__name__} needs convolutional input, got {input_type}")
        h = _out_size(input_type.height, self.kernel_size[0], self.stride[0],
                      self.padding[0], self.dilation[0], self.convolution_mode)
        w = _out_size(input_type.width, self.kernel_size[1], self.stride[1],
                      self.padding[1], self.dilation[1], self.convolution_mode)
        return ConvolutionalType(height=h, width=w, channels=self.n_out)

    def with_input(self, input_type: InputType) -> "ConvolutionLayer":
        if self.n_in or not isinstance(input_type, ConvolutionalType):
            return self
        return dataclasses.replace(self, n_in=input_type.channels)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        kh, kw = self.kernel_size
        fan_in = self.n_in * kh * kw
        fan_out = self.n_out * kh * kw
        w = init_weights(key, (self.n_out, self.n_in, kh, kw),
                         self.weight_init or WeightInit.XAVIER, fan_in, fan_out,
                         self.weight_init_distribution, dtype)
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def _dn(self):
        if self.data_format == "NCHW":
            return ("NCHW", "OIHW", "NCHW")
        return ("NHWC", "OIHW", "NHWC")

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        from ...ops import helpers

        x = apply_input_dropout(self, x, ctx)
        pad = _lax_padding(self.convolution_mode, self.padding, self.kernel_size, self.dilation)
        # helper seam (reference: cuDNN ConvolutionHelper consulted before
        # builtin): "xla" conv emitter by default, "im2col" explicit-GEMM
        y = helpers.conv2d(x, params["W"], self.stride, pad, self.dilation,
                           self._dn())
        if self.has_bias:
            b = params["b"]
            y = y + (b[None, :, None, None] if self.data_format == "NCHW" else b)
        act = self.activation or Activation.IDENTITY
        return act(y), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Convolution1DLayer(Layer):
    """1-D convolution over recurrent-format input [batch, nIn, time].
    Weights stored [nOut, nIn, k] (reference: Convolution1DLayer)."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if not isinstance(input_type, RecurrentType):
            raise ValueError("Convolution1DLayer needs recurrent input")
        ts = input_type.timesteps
        if ts is not None:
            ts = _out_size(ts, self.kernel_size, self.stride, self.padding,
                           self.dilation, self.convolution_mode)
        return RecurrentType(size=self.n_out, timesteps=ts)

    def with_input(self, input_type: InputType) -> "Convolution1DLayer":
        if self.n_in or not isinstance(input_type, RecurrentType):
            return self
        return dataclasses.replace(self, n_in=input_type.size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        k = self.kernel_size
        w = init_weights(key, (self.n_out, self.n_in, k),
                         self.weight_init or WeightInit.XAVIER,
                         self.n_in * k, self.n_out * k,
                         self.weight_init_distribution, dtype)
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        pad = _lax_padding(self.convolution_mode, (self.padding,), (self.kernel_size,), (self.dilation,))
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(self.stride,), padding=pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NCH", "OIH", "NCH"),
        )
        if self.has_bias:
            y = y + params["b"][None, :, None]
        act = self.activation or Activation.IDENTITY
        return act(y), state

    def feed_forward_mask(self, mask, input_type):
        if mask is None or (self.stride == 1 and self.convolution_mode in (ConvolutionMode.SAME, ConvolutionMode.CAUSAL)):
            return mask
        # subsample the time mask the way the conv subsamples time
        return mask[:, :: self.stride][:, : self.output_type(input_type).timesteps or None]


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Convolution3DLayer(Layer):
    """3-D convolution over [batch, nIn, d, h, w]. Weights [nOut, nIn, kD, kH, kW]."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: Tuple[int, int, int] = (3, 3, 3)
    stride: Tuple[int, int, int] = (1, 1, 1)
    padding: Tuple[int, int, int] = (0, 0, 0)
    dilation: Tuple[int, int, int] = (1, 1, 1)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if not isinstance(input_type, Convolutional3DType):
            raise ValueError("Convolution3DLayer needs convolutional3d input")
        d, h, w = (
            _out_size(s, k, st, p, dl, self.convolution_mode)
            for s, k, st, p, dl in zip(
                (input_type.depth, input_type.height, input_type.width),
                self.kernel_size, self.stride, self.padding, self.dilation,
            )
        )
        return Convolutional3DType(depth=d, height=h, width=w, channels=self.n_out)

    def with_input(self, input_type: InputType) -> "Convolution3DLayer":
        if self.n_in or not isinstance(input_type, Convolutional3DType):
            return self
        return dataclasses.replace(self, n_in=input_type.channels)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        kd, kh, kw = self.kernel_size
        rf = kd * kh * kw
        w = init_weights(key, (self.n_out, self.n_in, kd, kh, kw),
                         self.weight_init or WeightInit.XAVIER,
                         self.n_in * rf, self.n_out * rf,
                         self.weight_init_distribution, dtype)
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        pad = _lax_padding(self.convolution_mode, self.padding, self.kernel_size, self.dilation)
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=self.stride, padding=pad,
            rhs_dilation=self.dilation,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        )
        if self.has_bias:
            y = y + params["b"][None, :, None, None, None]
        act = self.activation or Activation.IDENTITY
        return act(y), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Deconvolution2DLayer(Layer):
    """Transposed 2-D convolution (reference: Deconvolution2D).
    Weights [nIn, nOut, kH, kW] (reference layout)."""

    n_in: int = 0
    n_out: int = 0
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if not isinstance(input_type, ConvolutionalType):
            raise ValueError("Deconvolution2DLayer needs convolutional input")
        h = _deconv_out_size(input_type.height, self.kernel_size[0], self.stride[0],
                             self.padding[0], self.dilation[0], self.convolution_mode)
        w = _deconv_out_size(input_type.width, self.kernel_size[1], self.stride[1],
                             self.padding[1], self.dilation[1], self.convolution_mode)
        return ConvolutionalType(height=h, width=w, channels=self.n_out)

    def with_input(self, input_type: InputType) -> "Deconvolution2DLayer":
        if self.n_in or not isinstance(input_type, ConvolutionalType):
            return self
        return dataclasses.replace(self, n_in=input_type.channels)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        kh, kw = self.kernel_size
        rf = kh * kw
        w = init_weights(key, (self.n_in, self.n_out, kh, kw),
                         self.weight_init or WeightInit.XAVIER,
                         self.n_in * rf, self.n_out * rf,
                         self.weight_init_distribution, dtype)
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            # conv_transpose applies explicit pads to the lhs-DILATED
            # input; gradient-of-conv semantics for forward padding p and
            # effective kernel ek need (ek - 1 - p) each side, giving
            # out = s*(in-1) + ek - 2p — the shape output_type() promises
            # (p = 0 reduces to the "VALID" string's padding).
            pad = []
            for p_i, k_i, d_i in zip(self.padding, self.kernel_size,
                                     self.dilation):
                ek = (k_i - 1) * d_i + 1
                pad.append((ek - 1 - p_i, ek - 1 - p_i))
        # transpose_kernel=True: TRUE gradient-of-conv semantics (spatial
        # flip + in/out swap) — torch ConvTranspose2d / Keras
        # Conv2DTranspose / reference Deconvolution2D parity. W layout
        # [nIn, nOut, kH, kW] is the transposed forward conv's OIHW.
        y = lax.conv_transpose(
            x, params["W"], strides=self.stride, padding=pad,
            rhs_dilation=self.dilation,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            transpose_kernel=True,
        )
        if self.has_bias:
            y = y + params["b"][None, :, None, None]
        act = self.activation or Activation.IDENTITY
        return act(y), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class DepthwiseConvolution2DLayer(Layer):
    """Depthwise 2-D conv (reference: DepthwiseConvolution2D).
    Weights [kH, kW, nIn, depthMultiplier] (reference layout); lowered via
    feature_group_count=nIn."""

    n_in: int = 0
    n_out: int = 0  # derived: n_in * depth_multiplier
    depth_multiplier: int = 1
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        h = _out_size(input_type.height, self.kernel_size[0], self.stride[0],
                      self.padding[0], self.dilation[0], self.convolution_mode)
        w = _out_size(input_type.width, self.kernel_size[1], self.stride[1],
                      self.padding[1], self.dilation[1], self.convolution_mode)
        return ConvolutionalType(height=h, width=w, channels=self.n_in * self.depth_multiplier)

    def with_input(self, input_type: InputType) -> "DepthwiseConvolution2DLayer":
        if self.n_in or not isinstance(input_type, ConvolutionalType):
            return self
        return dataclasses.replace(
            self, n_in=input_type.channels, n_out=input_type.channels * self.depth_multiplier
        )

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        kh, kw = self.kernel_size
        rf = kh * kw
        w = init_weights(key, (kh, kw, self.n_in, self.depth_multiplier),
                         self.weight_init or WeightInit.XAVIER,
                         self.n_in * rf, self.n_in * self.depth_multiplier * rf,
                         self.weight_init_distribution, dtype)
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_in * self.depth_multiplier,), self.bias_init, dtype)
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        kh, kw = self.kernel_size
        # [kH,kW,nIn,mult] -> OIHW with O=nIn*mult, I=1
        w = params["W"].transpose(2, 3, 0, 1).reshape(self.n_in * self.depth_multiplier, 1, kh, kw)
        pad = _lax_padding(self.convolution_mode, self.padding, self.kernel_size, self.dilation)
        y = lax.conv_general_dilated(
            x, w, window_strides=self.stride, padding=pad,
            rhs_dilation=self.dilation,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_in,
        )
        if self.has_bias:
            y = y + params["b"][None, :, None, None]
        act = self.activation or Activation.IDENTITY
        return act(y), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class SeparableConvolution2DLayer(Layer):
    """Depthwise-separable 2-D conv (reference: SeparableConvolution2D).
    Depthwise W [kH,kW,nIn,mult] + pointwise pW [nOut, nIn*mult, 1, 1]."""

    n_in: int = 0
    n_out: int = 0
    depth_multiplier: int = 1
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        h = _out_size(input_type.height, self.kernel_size[0], self.stride[0],
                      self.padding[0], self.dilation[0], self.convolution_mode)
        w = _out_size(input_type.width, self.kernel_size[1], self.stride[1],
                      self.padding[1], self.dilation[1], self.convolution_mode)
        return ConvolutionalType(height=h, width=w, channels=self.n_out)

    def with_input(self, input_type: InputType) -> "SeparableConvolution2DLayer":
        if self.n_in or not isinstance(input_type, ConvolutionalType):
            return self
        return dataclasses.replace(self, n_in=input_type.channels)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "pW", "b") if self.has_bias else ("W", "pW")

    def init(self, key: jax.Array, dtype: Any) -> Params:
        kh, kw = self.kernel_size
        rf = kh * kw
        k1, k2 = jax.random.split(key)
        dw = init_weights(k1, (kh, kw, self.n_in, self.depth_multiplier),
                          self.weight_init or WeightInit.XAVIER,
                          self.n_in * rf, self.n_in * self.depth_multiplier * rf,
                          self.weight_init_distribution, dtype)
        pw = init_weights(k2, (self.n_out, self.n_in * self.depth_multiplier, 1, 1),
                          self.weight_init or WeightInit.XAVIER,
                          self.n_in * self.depth_multiplier, self.n_out,
                          self.weight_init_distribution, dtype)
        p: Params = {"W": dw, "pW": pw}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        kh, kw = self.kernel_size
        dw = params["W"].transpose(2, 3, 0, 1).reshape(self.n_in * self.depth_multiplier, 1, kh, kw)
        pad = _lax_padding(self.convolution_mode, self.padding, self.kernel_size, self.dilation)
        y = lax.conv_general_dilated(
            x, dw, window_strides=self.stride, padding=pad,
            rhs_dilation=self.dilation,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=self.n_in,
        )
        y = lax.conv_general_dilated(
            y, params["pW"], window_strides=(1, 1), padding=[(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        if self.has_bias:
            y = y + params["b"][None, :, None, None]
        act = self.activation or Activation.IDENTITY
        return act(y), state
