from .base import Layer, LayerContext, Params, State
from .attention import (
    LearnedSelfAttentionLayer,
    RecurrentAttentionLayer,
    SelfAttentionLayer,
    TransformerDecoderBlockLayer,
    dot_product_attention,
)
from .conv import (
    Convolution1DLayer,
    Convolution3DLayer,
    ConvolutionLayer,
    ConvolutionMode,
    Deconvolution2DLayer,
    DepthwiseConvolution2DLayer,
    SeparableConvolution2DLayer,
)
from .feedforward import (
    ActivationLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    EmbeddingSequenceLayer,
    PositionalEmbeddingLayer,
    PReLULayer,
)
from .norm import (
    BatchNormalizationLayer,
    LayerNormLayer,
    LocalResponseNormalizationLayer,
)
from .output import (
    BaseOutputLayer,
    CnnLossLayer,
    LossLayer,
    OutputLayer,
    RnnLossLayer,
    RnnOutputLayer,
)
from .pooling import (
    Cropping2DLayer,
    GlobalPoolingLayer,
    PoolingType,
    SpaceToDepthLayer,
    Subsampling1DLayer,
    Subsampling3DLayer,
    SubsamplingLayer,
    Upsampling1DLayer,
    Upsampling2DLayer,
    Upsampling3DLayer,
    ZeroPadding1DLayer,
    ZeroPaddingLayer,
)
from .preprocessors import (
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    RnnToCnnPreProcessor,
    RnnToFeedForwardPreProcessor,
)
from .moe import MixtureOfExpertsLayer
from .samediff_layer import SameDiffLambdaLayer, SameDiffLayer
from .recurrent import (
    BidirectionalLayer,
    BidirectionalMode,
    GravesLSTMLayer,
    GRULayer,
    LSTMLayer,
    LastTimeStepLayer,
    MaskZeroLayer,
    SimpleRnnLayer,
    TimeDistributedLayer,
)

__all__ = [n for n in dir() if not n.startswith("_")]
