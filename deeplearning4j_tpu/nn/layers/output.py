"""Output / loss layers.

Reference: org.deeplearning4j.nn.conf.layers.{OutputLayer, RnnOutputLayer,
RnnLossLayer, LossLayer, CnnLossLayer, CenterLossOutputLayer}. An output layer
= (optional dense projection) + ILossFunction; the model calls
``compute_loss`` during fit and ``apply`` during output().
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.config import register_config
from ..activations import Activation
from ..input_type import ConvolutionalType, FeedForwardType, InputType, RecurrentType
from ..losses import LossFunction
from ..weights import WeightInit, init_weights
from .base import Layer, LayerContext, Params, State, apply_input_dropout


class BaseOutputLayer(Layer):
    """Marker base for layers that terminate a network with a loss."""

    def preoutput(self, params: Params, x: jax.Array, ctx: LayerContext) -> jax.Array:
        raise NotImplementedError

    def compute_loss(
        self,
        params: Params,
        x: jax.Array,
        labels: jax.Array,
        ctx: LayerContext,
        label_mask: Optional[jax.Array] = None,
    ) -> jax.Array:
        raise NotImplementedError


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class OutputLayer(BaseOutputLayer):
    """Dense + loss on feed-forward input (reference: OutputLayer).
    Default activation SOFTMAX + MCXENT, matching the reference."""

    n_in: int = 0
    n_out: int = 0
    loss: LossFunction = LossFunction.MCXENT
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        return FeedForwardType(size=self.n_out)

    def with_input(self, input_type: InputType) -> "OutputLayer":
        if self.n_in:
            return self
        return dataclasses.replace(self, n_in=input_type.flat_size())

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        w = init_weights(key, (self.n_in, self.n_out),
                         self.weight_init or WeightInit.XAVIER,
                         self.n_in, self.n_out, self.weight_init_distribution, dtype)
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def preoutput(self, params: Params, x: jax.Array, ctx: LayerContext) -> jax.Array:
        x = apply_input_dropout(self, x, ctx)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        act = self.activation or Activation.SOFTMAX
        return act(self.preoutput(params, x, ctx)), state

    def compute_loss(self, params, x, labels, ctx, label_mask=None):
        pre = self.preoutput(params, x, ctx)
        act = self.activation or Activation.SOFTMAX
        return self.loss.score(labels, pre, act, mask=label_mask)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class LossLayer(BaseOutputLayer):
    """Loss without params (reference: LossLayer). Activation default IDENTITY."""

    loss: LossFunction = LossFunction.MCXENT

    def preoutput(self, params: Params, x: jax.Array, ctx: LayerContext) -> jax.Array:
        return x

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        act = self.activation or Activation.IDENTITY
        return act(x), state

    def compute_loss(self, params, x, labels, ctx, label_mask=None):
        act = self.activation or Activation.IDENTITY
        return self.loss.score(labels, x, act, mask=label_mask)


def _rnn_to_ff(a: jax.Array) -> jax.Array:
    """[b, f, t] -> [b*t, f] preserving the reference's flattening order."""
    b, f, t = a.shape
    return a.transpose(0, 2, 1).reshape(b * t, f)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep dense + loss (reference: RnnOutputLayer). Input [b, nIn, t],
    labels [b, nOut, t], mask [b, t]."""

    n_in: int = 0
    n_out: int = 0
    loss: LossFunction = LossFunction.MCXENT
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        ts = input_type.timesteps if isinstance(input_type, RecurrentType) else None
        return RecurrentType(size=self.n_out, timesteps=ts)

    def with_input(self, input_type: InputType) -> "RnnOutputLayer":
        if self.n_in or not isinstance(input_type, RecurrentType):
            return self
        return dataclasses.replace(self, n_in=input_type.size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "b") if self.has_bias else ("W",)

    def init(self, key: jax.Array, dtype: Any) -> Params:
        w = init_weights(key, (self.n_in, self.n_out),
                         self.weight_init or WeightInit.XAVIER,
                         self.n_in, self.n_out, self.weight_init_distribution, dtype)
        p: Params = {"W": w}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def preoutput(self, params: Params, x: jax.Array, ctx: LayerContext) -> jax.Array:
        x = apply_input_dropout(self, x, ctx)
        flat = _rnn_to_ff(x)
        y = flat @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y  # [b*t, nOut]

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        b, _, t = x.shape
        act = self.activation or Activation.SOFTMAX
        y = act(self.preoutput(params, x, ctx))
        return y.reshape(b, t, self.n_out).transpose(0, 2, 1), state

    def compute_loss(self, params, x, labels, ctx, label_mask=None):
        pre = self.preoutput(params, x, ctx)  # [b*t, nOut]
        # sparse integer labels [b, t] (SPARSE_MCXENT) flatten in the same
        # (batch, time) order as _rnn_to_ff; dense labels are [b, nOut, t]
        lab = _rnn_to_ff(labels) if labels.ndim == 3 else labels.reshape(-1)
        act = self.activation or Activation.SOFTMAX
        mask = None
        if label_mask is not None:
            mask = label_mask.reshape(-1)
        elif ctx.mask is not None:
            mask = ctx.mask.reshape(-1)
        return self.loss.score(lab, pre, act, mask=mask)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class RnnLossLayer(BaseOutputLayer):
    """Per-timestep loss without params (reference: RnnLossLayer)."""

    loss: LossFunction = LossFunction.MCXENT

    def preoutput(self, params: Params, x: jax.Array, ctx: LayerContext) -> jax.Array:
        return x

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        act = self.activation or Activation.IDENTITY
        b, f, t = x.shape
        y = act(_rnn_to_ff(x))
        return y.reshape(b, t, f).transpose(0, 2, 1), state

    def compute_loss(self, params, x, labels, ctx, label_mask=None):
        pre = _rnn_to_ff(x)
        lab = _rnn_to_ff(labels)
        act = self.activation or Activation.IDENTITY
        mask = None
        if label_mask is not None:
            mask = label_mask.reshape(-1)
        elif ctx.mask is not None:
            mask = ctx.mask.reshape(-1)
        return self.loss.score(lab, pre, act, mask=mask)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class CnnLossLayer(BaseOutputLayer):
    """Per-pixel loss on CNN output [b, c, h, w] (reference: CnnLossLayer).
    Labels same shape; mask [b, 1, h, w] or [b, h, w] optional."""

    loss: LossFunction = LossFunction.MCXENT

    def preoutput(self, params: Params, x: jax.Array, ctx: LayerContext) -> jax.Array:
        return x

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        act = self.activation or Activation.IDENTITY
        # activation applied over channel axis: move C last, apply, move back
        y = act(x.transpose(0, 2, 3, 1)).transpose(0, 3, 1, 2)
        return y, state

    def compute_loss(self, params, x, labels, ctx, label_mask=None):
        b, c, h, w = x.shape
        pre = x.transpose(0, 2, 3, 1).reshape(b * h * w, c)
        lab = labels.transpose(0, 2, 3, 1).reshape(b * h * w, c)
        act = self.activation or Activation.IDENTITY
        mask = None
        if label_mask is not None:
            mask = label_mask.reshape(-1)
        return self.loss.score(lab, pre, act, mask=mask)
