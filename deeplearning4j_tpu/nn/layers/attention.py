"""Attention layers.

Reference: org.deeplearning4j.nn.conf.layers.{SelfAttentionLayer,
LearnedSelfAttentionLayer, RecurrentAttentionLayer} and the SameDiff
``multiHeadDotProductAttention`` op (SURVEY.md §5.7).

TPU design: attention is expressed as einsums that XLA maps to MXU matmuls.
The masked-softmax uses an additive -inf bias (no data-dependent shapes). A
Pallas flash-attention kernel can be slotted in as the accelerated helper for
long sequences (ops/pallas) — the layer semantics here are the reference ones.

Data format follows the recurrent convention [batch, features, time]; heads
are split internally.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.config import register_config
from ..activations import Activation
from ..input_type import InputType, RecurrentType
from ..weights import WeightInit, init_weights
from .base import Layer, LayerContext, Params, State, apply_input_dropout


def dot_product_attention(
    q: jax.Array,  # [b, h, tq, d]
    k: jax.Array,  # [b, h, tk, d]
    v: jax.Array,  # [b, h, tk, dv]
    mask: Optional[jax.Array] = None,  # [b, tk]
    scaled: bool = True,
) -> jax.Array:
    # Routed through the helper seam (ops.mha_attention): builtin XLA einsum
    # path or the Pallas flash kernel, mirroring the reference's per-layer
    # cuDNN-helper probe (SURVEY.md §2.2 "Helper SPI").
    from ...ops import mha_attention

    scale = 1.0 / math.sqrt(q.shape[-1]) if scaled else 1.0
    return mha_attention(q, k, v, mask=mask, scale=scale)


def _split_heads(x: jax.Array, n_heads: int) -> jax.Array:
    b, t, f = x.shape
    return x.reshape(b, t, n_heads, f // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(x: jax.Array) -> jax.Array:
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class SelfAttentionLayer(Layer):
    """Multi-head dot-product self-attention (reference: SelfAttentionLayer).
    Input/output [b, f, t]. With ``project_input`` learns Wq/Wk/Wv/Wo."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    head_size: int = 0
    project_input: bool = True

    def __post_init__(self):
        if self.n_out and not self.head_size:
            object.__setattr__(self, "head_size", self.n_out // self.n_heads)

    def output_type(self, input_type: InputType) -> InputType:
        size = self.n_out if self.project_input else input_type.size
        return RecurrentType(size=size, timesteps=input_type.timesteps)

    def with_input(self, input_type: InputType) -> "SelfAttentionLayer":
        out = self
        if not out.n_in:
            out = dataclasses.replace(out, n_in=input_type.size)
        if not out.n_out and not out.project_input:
            out = dataclasses.replace(out, n_out=input_type.size)
        if out.n_out and not out.head_size:
            out = dataclasses.replace(out, head_size=out.n_out // out.n_heads)
        return out

    def has_params(self) -> bool:
        return self.project_input

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("Wq", "Wk", "Wv", "Wo") if self.project_input else ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        if not self.project_input:
            return {}
        wi = self.weight_init or WeightInit.XAVIER
        hs = self.n_heads * self.head_size
        ks = jax.random.split(key, 4)
        return {
            "Wq": init_weights(ks[0], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
            "Wk": init_weights(ks[1], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
            "Wv": init_weights(ks[2], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
            "Wo": init_weights(ks[3], (hs, self.n_out), wi, hs, self.n_out, None, dtype),
        }

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        xt = x.transpose(0, 2, 1)  # [b, t, f]
        if self.project_input:
            q = _split_heads(xt @ params["Wq"], self.n_heads)
            k = _split_heads(xt @ params["Wk"], self.n_heads)
            v = _split_heads(xt @ params["Wv"], self.n_heads)
        else:
            q = k = v = _split_heads(xt, self.n_heads)
        o = dot_product_attention(q, k, v, mask=ctx.mask)
        o = _merge_heads(o)
        if self.project_input:
            o = o @ params["Wo"]
        act = self.activation or Activation.IDENTITY
        return act(o).transpose(0, 2, 1), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class LearnedSelfAttentionLayer(Layer):
    """Attention with learned query vectors (reference:
    LearnedSelfAttentionLayer): output has fixed n_queries timesteps."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    head_size: int = 0
    n_queries: int = 1
    project_input: bool = True

    def __post_init__(self):
        if self.n_out and not self.head_size:
            object.__setattr__(self, "head_size", self.n_out // self.n_heads)

    def output_type(self, input_type: InputType) -> InputType:
        size = self.n_out if self.project_input else input_type.size
        return RecurrentType(size=size, timesteps=self.n_queries)

    def with_input(self, input_type: InputType) -> "LearnedSelfAttentionLayer":
        out = self
        if not out.n_in:
            out = dataclasses.replace(out, n_in=input_type.size)
        if not out.n_out and not out.project_input:
            out = dataclasses.replace(out, n_out=input_type.size)
        if out.n_out and not out.head_size:
            out = dataclasses.replace(out, head_size=out.n_out // out.n_heads)
        return out

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        base = ("Q",)
        return base + (("Wq", "Wk", "Wv", "Wo") if self.project_input else ())

    def init(self, key: jax.Array, dtype: Any) -> Params:
        wi = self.weight_init or WeightInit.XAVIER
        hs = self.n_heads * self.head_size if self.project_input else self.n_in
        ks = jax.random.split(key, 5)
        p: Params = {"Q": init_weights(ks[4], (self.n_queries, hs), wi, hs, hs, None, dtype)}
        if self.project_input:
            p.update({
                "Wq": init_weights(ks[0], (hs, hs), wi, hs, hs, None, dtype),
                "Wk": init_weights(ks[1], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
                "Wv": init_weights(ks[2], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
                "Wo": init_weights(ks[3], (hs, self.n_out), wi, hs, self.n_out, None, dtype),
            })
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        b = x.shape[0]
        xt = x.transpose(0, 2, 1)
        queries = jnp.broadcast_to(params["Q"], (b,) + params["Q"].shape)
        if self.project_input:
            q = _split_heads(queries @ params["Wq"], self.n_heads)
            k = _split_heads(xt @ params["Wk"], self.n_heads)
            v = _split_heads(xt @ params["Wv"], self.n_heads)
        else:
            q = _split_heads(queries, self.n_heads)
            k = v = _split_heads(xt, self.n_heads)
        o = _merge_heads(dot_product_attention(q, k, v, mask=ctx.mask))
        if self.project_input:
            o = o @ params["Wo"]
        act = self.activation or Activation.IDENTITY
        return act(o).transpose(0, 2, 1), state

    def feed_forward_mask(self, mask, input_type):
        return None  # output timesteps are the learned queries — all valid


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class RecurrentAttentionLayer(Layer):
    """Recurrent cell attending over the full input sequence at each step
    (reference: RecurrentAttentionLayer): h_t = act(x_t W + h_{t-1} RW +
    attn(h_{t-1}, X) Wa + b)."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return RecurrentType(size=self.n_out, timesteps=input_type.timesteps)

    def with_input(self, input_type: InputType) -> "RecurrentAttentionLayer":
        if self.n_in:
            return self
        return dataclasses.replace(self, n_in=input_type.size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "RW", "Wa", "b")

    def init(self, key: jax.Array, dtype: Any) -> Params:
        wi = self.weight_init or WeightInit.XAVIER
        ks = jax.random.split(key, 3)
        return {
            "W": init_weights(ks[0], (self.n_in, self.n_out), wi, self.n_in, self.n_out, None, dtype),
            "RW": init_weights(ks[1], (self.n_out, self.n_out), wi, self.n_out, self.n_out, None, dtype),
            "Wa": init_weights(ks[2], (self.n_in, self.n_out), wi, self.n_in, self.n_out, None, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
        }

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        b, f, t = x.shape
        act = self.activation or Activation.TANH
        xt = x.transpose(2, 0, 1)  # [t, b, f]
        x_proj = jnp.einsum("tbf,fo->tbo", xt, params["W"]) + params["b"]
        keys = x.transpose(0, 2, 1)  # [b, t, f]
        mask = ctx.mask

        def step(h, xp):
            # attention of h over the input sequence
            scores = jnp.einsum("bo,fo,btf->bt", h, params["Wa"], keys) / math.sqrt(f)
            if mask is not None:
                neg = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
                scores = jnp.where(mask > 0, scores, neg)
            w = jax.nn.softmax(scores, axis=-1)
            attended = jnp.einsum("bt,btf->bf", w, keys)  # [b, f]
            h_new = act(xp + h @ params["RW"] + attended @ params["Wa"])
            return h_new, h_new

        h0 = jnp.zeros((b, self.n_out), x.dtype)
        _, hs = jax.lax.scan(step, h0, x_proj)
        return hs.transpose(1, 2, 0), state
