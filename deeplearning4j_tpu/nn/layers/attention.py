"""Attention layers.

Reference: org.deeplearning4j.nn.conf.layers.{SelfAttentionLayer,
LearnedSelfAttentionLayer, RecurrentAttentionLayer} and the SameDiff
``multiHeadDotProductAttention`` op (SURVEY.md §5.7).

TPU design: attention is expressed as einsums that XLA maps to MXU matmuls.
The masked-softmax uses an additive -inf bias (no data-dependent shapes). A
Pallas flash-attention kernel can be slotted in as the accelerated helper for
long sequences (ops/pallas) — the layer semantics here are the reference ones.

Data format follows the recurrent convention [batch, features, time]; heads
are split internally.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.config import register_config
from ..activations import Activation
from ..input_type import InputType, RecurrentType
from ..weights import WeightInit, init_weights
from .base import Layer, LayerContext, Params, State, apply_input_dropout


def dot_product_attention(
    q: jax.Array,  # [b, h, tq, d]
    k: jax.Array,  # [b, h, tk, d]
    v: jax.Array,  # [b, h, tk, dv]
    mask: Optional[jax.Array] = None,  # [b, tk]
    scaled: bool = True,
    causal: bool = False,
) -> jax.Array:
    # Routed through the helper seam (ops.mha_attention): builtin XLA einsum
    # path or the Pallas flash kernel, mirroring the reference's per-layer
    # cuDNN-helper probe (SURVEY.md §2.2 "Helper SPI").
    from ...ops import mha_attention

    scale = 1.0 / math.sqrt(q.shape[-1]) if scaled else 1.0
    return mha_attention(q, k, v, mask=mask, scale=scale, causal=causal)


def _cache_write(cache: jax.Array, new: jax.Array, pos: jax.Array) -> jax.Array:
    """Write ``new`` [b, h, t, d] into the static-shape cache [b, h, L, d]
    at per-row positions ``pos + [0, t)`` — the position-indexed
    ``lax.dynamic_update_slice`` that keeps every decode step the same
    compiled shape regardless of how far each sequence has advanced."""
    def row(c, n, p):
        z = jnp.zeros((), p.dtype)  # homogeneous index dtypes (x64-safe)
        return jax.lax.dynamic_update_slice(c, n, (z, p, z))

    return jax.vmap(row)(cache, new.astype(cache.dtype),
                         pos.astype(jnp.int32))


def _scale_write(scales: jax.Array, new: jax.Array, pos: jax.Array) -> jax.Array:
    """Per-slot scale counterpart of :func:`_cache_write`: write ``new``
    [b, h, t] into the scale plane [b, h, L] at ``pos + [0, t)``."""
    def row(c, n, p):
        z = jnp.zeros((), p.dtype)
        return jax.lax.dynamic_update_slice(c, n, (z, p))

    return jax.vmap(row)(scales, new.astype(scales.dtype),
                         pos.astype(jnp.int32))


def quantize_kv_rows(x: jax.Array, eps: float = 1e-8):
    """Symmetric per-(row, head, position) int8 quantization of a K/V
    write [b, h, t, d]: the scale is the absmax over the head dim, so one
    f32 scale rides each cached slot. Returns ``(q int8, scale f32
    [b, h, t])``; dequant is ``q * scale[..., None]``."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, eps) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _cached_attention(q, k_new, v_new, state, mask):
    """Shared KV-cache attention step: write this call's K/V into the
    cache at each row's position, then attend causally against the cache.
    Returns (output, new_state). ``mask`` (the prompt's [b, t] validity
    mask) bounds how far ``pos`` advances, so right-padded prefill rows
    keep their true length and the pad slots are overwritten by later
    decode steps before anything ever attends to them.

    An int8 cache (``cache_dtype="int8"`` on the session/engine — the
    state then carries ``cache_k_scale``/``cache_v_scale`` planes) writes
    quantized slots with per-slot/per-head scales and dequantizes inside
    :func:`~deeplearning4j_tpu.ops.decode_attention`'s reference path —
    the resident cache holds ~1/2 the bytes of an fp16 cache (1/4 of
    f32), so the same HBM budget fits ~2× the concurrent sequences."""
    from ...ops import decode_attention

    t = q.shape[2]
    pos = state["pos"].astype(jnp.int32)
    valid = (jnp.asarray(t, jnp.int32) if mask is None
             else jnp.sum(mask > 0, axis=1).astype(jnp.int32))
    if "block_table" in state:  # paged KV cache (shared block pools)
        from ...ops import paged_cache_write, paged_decode_attention

        table = state["block_table"]
        if "cache_k_scale" in state:  # int8 blocks + f32 scale pools
            kq, ks = quantize_kv_rows(k_new)
            vq, vs = quantize_kv_rows(v_new)
            cache_k = paged_cache_write(state["cache_k"], kq, table, pos)
            cache_v = paged_cache_write(state["cache_v"], vq, table, pos)
            k_scale = paged_cache_write(state["cache_k_scale"], ks,
                                        table, pos)
            v_scale = paged_cache_write(state["cache_v_scale"], vs,
                                        table, pos)
            o = paged_decode_attention(q, cache_k, cache_v, table, pos,
                                       k_scale=k_scale, v_scale=v_scale)
            new_state = {"cache_k": cache_k, "cache_v": cache_v,
                         "cache_k_scale": k_scale, "cache_v_scale": v_scale,
                         "block_table": table, "pos": pos + valid}
            return o, new_state
        cache_k = paged_cache_write(state["cache_k"], k_new, table, pos)
        cache_v = paged_cache_write(state["cache_v"], v_new, table, pos)
        o = paged_decode_attention(q, cache_k, cache_v, table, pos)
        new_state = {"cache_k": cache_k, "cache_v": cache_v,
                     "block_table": table, "pos": pos + valid}
        return o, new_state
    if "cache_k_scale" in state:  # int8 KV cache
        kq, ks = quantize_kv_rows(k_new)
        vq, vs = quantize_kv_rows(v_new)
        cache_k = _cache_write(state["cache_k"], kq, pos)
        cache_v = _cache_write(state["cache_v"], vq, pos)
        k_scale = _scale_write(state["cache_k_scale"], ks, pos)
        v_scale = _scale_write(state["cache_v_scale"], vs, pos)
        o = decode_attention(q, cache_k, cache_v, pos,
                             k_scale=k_scale, v_scale=v_scale)
        new_state = {"cache_k": cache_k, "cache_v": cache_v,
                     "cache_k_scale": k_scale, "cache_v_scale": v_scale,
                     "pos": pos + valid}
        return o, new_state
    cache_k = _cache_write(state["cache_k"], k_new, pos)
    cache_v = _cache_write(state["cache_v"], v_new, pos)
    # query i at absolute position pos+i attends cache [0, pos+i]; the
    # single-token hot path (t == 1) dispatches to the flash decode kernel
    o = decode_attention(q, cache_k, cache_v, pos)
    new_state = {"cache_k": cache_k, "cache_v": cache_v, "pos": pos + valid}
    return o, new_state


def _split_heads(x: jax.Array, n_heads: int) -> jax.Array:
    b, t, f = x.shape
    return x.reshape(b, t, n_heads, f // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(x: jax.Array) -> jax.Array:
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class SelfAttentionLayer(Layer):
    """Multi-head dot-product self-attention (reference: SelfAttentionLayer).
    Input/output [b, f, t]. With ``project_input`` learns Wq/Wk/Wv/Wo.

    ``causal=True`` masks attention to positions <= the query's (an
    autoregressive decoder block) and unlocks the KV-cached incremental
    decode path: when the per-sequence decode carry from
    :meth:`decode_state` is threaded in through ``apply``'s state, each
    call writes its K/V into the static-shape cache and attends against
    it instead of re-running the prefix."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    head_size: int = 0
    project_input: bool = True
    causal: bool = False

    def __post_init__(self):
        if self.n_out and not self.head_size:
            object.__setattr__(self, "head_size", self.n_out // self.n_heads)

    def output_type(self, input_type: InputType) -> InputType:
        size = self.n_out if self.project_input else input_type.size
        return RecurrentType(size=size, timesteps=input_type.timesteps)

    def with_input(self, input_type: InputType) -> "SelfAttentionLayer":
        out = self
        if not out.n_in:
            out = dataclasses.replace(out, n_in=input_type.size)
        if not out.n_out and not out.project_input:
            out = dataclasses.replace(out, n_out=input_type.size)
        if out.n_out and not out.head_size:
            out = dataclasses.replace(out, head_size=out.n_out // out.n_heads)
        return out

    def has_params(self) -> bool:
        return self.project_input

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("Wq", "Wk", "Wv", "Wo") if self.project_input else ()

    def init(self, key: jax.Array, dtype: Any) -> Params:
        if not self.project_input:
            return {}
        wi = self.weight_init or WeightInit.XAVIER
        hs = self.n_heads * self.head_size
        ks = jax.random.split(key, 4)
        return {
            "Wq": init_weights(ks[0], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
            "Wk": init_weights(ks[1], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
            "Wv": init_weights(ks[2], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
            "Wo": init_weights(ks[3], (hs, self.n_out), wi, hs, self.n_out, None, dtype),
        }

    def decode_state(self, batch: int, max_len: int, dtype: Any) -> State:
        if not self.causal:
            return {}  # bidirectional attention has no incremental decode
        d = (self.head_size if self.project_input
             else self.n_in // self.n_heads)
        shape = (batch, self.n_heads, max_len, d)
        return {"cache_k": jnp.zeros(shape, dtype),
                "cache_v": jnp.zeros(shape, dtype),
                "pos": jnp.zeros((batch,), jnp.int32)}

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        xt = x.transpose(0, 2, 1)  # [b, t, f]
        if self.project_input:
            q = _split_heads(xt @ params["Wq"], self.n_heads)
            k = _split_heads(xt @ params["Wk"], self.n_heads)
            v = _split_heads(xt @ params["Wv"], self.n_heads)
        else:
            q = k = v = _split_heads(xt, self.n_heads)
        if "cache_k" in state:
            if not self.causal:
                raise ValueError(
                    "KV-cached decode requires causal=True — bidirectional "
                    "attention cannot be decoded incrementally")
            o, new_state = _cached_attention(q, k, v, state, ctx.mask)
        else:
            o = dot_product_attention(q, k, v, mask=ctx.mask,
                                      causal=self.causal)
            new_state = state
        o = _merge_heads(o)
        if self.project_input:
            o = o @ params["Wo"]
        act = self.activation or Activation.IDENTITY
        return act(o).transpose(0, 2, 1), new_state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class LearnedSelfAttentionLayer(Layer):
    """Attention with learned query vectors (reference:
    LearnedSelfAttentionLayer): output has fixed n_queries timesteps."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    head_size: int = 0
    n_queries: int = 1
    project_input: bool = True

    def __post_init__(self):
        if self.n_out and not self.head_size:
            object.__setattr__(self, "head_size", self.n_out // self.n_heads)

    def output_type(self, input_type: InputType) -> InputType:
        size = self.n_out if self.project_input else input_type.size
        return RecurrentType(size=size, timesteps=self.n_queries)

    def with_input(self, input_type: InputType) -> "LearnedSelfAttentionLayer":
        out = self
        if not out.n_in:
            out = dataclasses.replace(out, n_in=input_type.size)
        if not out.n_out and not out.project_input:
            out = dataclasses.replace(out, n_out=input_type.size)
        if out.n_out and not out.head_size:
            out = dataclasses.replace(out, head_size=out.n_out // out.n_heads)
        return out

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        base = ("Q",)
        return base + (("Wq", "Wk", "Wv", "Wo") if self.project_input else ())

    def init(self, key: jax.Array, dtype: Any) -> Params:
        wi = self.weight_init or WeightInit.XAVIER
        hs = self.n_heads * self.head_size if self.project_input else self.n_in
        ks = jax.random.split(key, 5)
        p: Params = {"Q": init_weights(ks[4], (self.n_queries, hs), wi, hs, hs, None, dtype)}
        if self.project_input:
            p.update({
                "Wq": init_weights(ks[0], (hs, hs), wi, hs, hs, None, dtype),
                "Wk": init_weights(ks[1], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
                "Wv": init_weights(ks[2], (self.n_in, hs), wi, self.n_in, hs, None, dtype),
                "Wo": init_weights(ks[3], (hs, self.n_out), wi, hs, self.n_out, None, dtype),
            })
        return p

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        b = x.shape[0]
        xt = x.transpose(0, 2, 1)
        queries = jnp.broadcast_to(params["Q"], (b,) + params["Q"].shape)
        if self.project_input:
            q = _split_heads(queries @ params["Wq"], self.n_heads)
            k = _split_heads(xt @ params["Wk"], self.n_heads)
            v = _split_heads(xt @ params["Wv"], self.n_heads)
        else:
            q = _split_heads(queries, self.n_heads)
            k = v = _split_heads(xt, self.n_heads)
        o = _merge_heads(dot_product_attention(q, k, v, mask=ctx.mask))
        if self.project_input:
            o = o @ params["Wo"]
        act = self.activation or Activation.IDENTITY
        return act(o).transpose(0, 2, 1), state

    def feed_forward_mask(self, mask, input_type):
        return None  # output timesteps are the learned queries — all valid


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class RecurrentAttentionLayer(Layer):
    """Recurrent cell attending over the input sequence at each step
    (reference: RecurrentAttentionLayer): h_t = act(x_t W + h_{t-1} RW +
    attn(h_{t-1}, X) Wa + b).

    The ``h`` carry threads through ``apply`` state (rnnTimeStep
    semantics — streaming calls resume instead of re-running the prefix).
    ``causal=True`` restricts step t's attention to inputs [0, t] — the
    autoregressive mode required for incremental decode, where the decode
    carry from :meth:`decode_state` additionally caches past inputs so a
    single-step call attends over everything seen so far."""

    n_in: int = 0
    n_out: int = 0
    n_heads: int = 1
    causal: bool = False

    def output_type(self, input_type: InputType) -> InputType:
        return RecurrentType(size=self.n_out, timesteps=input_type.timesteps)

    def with_input(self, input_type: InputType) -> "RecurrentAttentionLayer":
        if self.n_in:
            return self
        return dataclasses.replace(self, n_in=input_type.size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("W", "RW", "Wa", "b")

    def init(self, key: jax.Array, dtype: Any) -> Params:
        wi = self.weight_init or WeightInit.XAVIER
        ks = jax.random.split(key, 3)
        return {
            "W": init_weights(ks[0], (self.n_in, self.n_out), wi, self.n_in, self.n_out, None, dtype),
            "RW": init_weights(ks[1], (self.n_out, self.n_out), wi, self.n_out, self.n_out, None, dtype),
            "Wa": init_weights(ks[2], (self.n_in, self.n_out), wi, self.n_in, self.n_out, None, dtype),
            "b": jnp.full((self.n_out,), self.bias_init, dtype),
        }

    def decode_state(self, batch: int, max_len: int, dtype: Any) -> State:
        if not self.causal:
            return {}  # future-peeking attention has no incremental decode
        return {"h": jnp.zeros((batch, self.n_out), dtype),
                "cache_x": jnp.zeros((batch, max_len, self.n_in), dtype),
                "pos": jnp.zeros((batch,), jnp.int32)}

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        b, f, t = x.shape
        act = self.activation or Activation.TANH
        xt = x.transpose(2, 0, 1)  # [t, b, f]
        x_proj = jnp.einsum("tbf,fo->tbo", xt, params["W"]) + params["b"]
        mask = ctx.mask
        cache = state.get("cache_x")
        if cache is not None and not self.causal:
            raise ValueError("cached decode requires causal=True — a step "
                             "cannot attend inputs that do not exist yet")
        pos = None
        if cache is None:
            keys = x.transpose(0, 2, 1)  # [b, t, f]
        else:
            pos = state["pos"].astype(jnp.int32)
            keys = jax.vmap(lambda c, n, p: jax.lax.dynamic_update_slice(
                c, n, (p, jnp.zeros((), p.dtype))))(
                    cache, x.transpose(0, 2, 1).astype(cache.dtype), pos)
        t_keys = keys.shape[1]
        neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)

        # freeze h through right-pad steps only on the cached (prefill)
        # path: the full-sequence training path keeps its semantics; the
        # padding-key mask only applies when keys == this call's input
        # (cached keys are masked by the causal frontier instead)
        use_m = mask is not None and cache is not None

        def step(h, inp):
            if use_m:
                xp, i, m = inp
            else:
                (xp, i), m = inp, None
            # attention of h over the (cached) input sequence
            scores = jnp.einsum("bo,fo,btf->bt", h, params["Wa"], keys) / math.sqrt(f)
            if mask is not None and cache is None:
                scores = jnp.where(mask > 0, scores, neg)
            if self.causal:
                limit = i if pos is None else pos[:, None] + i
                ids = jnp.arange(t_keys, dtype=jnp.int32)[None, :]
                scores = jnp.where(ids <= limit, scores, neg)
            w = jax.nn.softmax(scores, axis=-1)
            attended = jnp.einsum("bt,btf->bf", w, keys)  # [b, f]
            h_new = act(xp + h @ params["RW"] + attended @ params["Wa"])
            if m is not None:
                mm = m[:, None]
                h_new = mm * h_new + (1.0 - mm) * h
            return h_new, h_new

        h0 = state.get("h")
        if h0 is None:
            h0 = jnp.zeros((b, self.n_out), x.dtype)
        steps = jnp.arange(t, dtype=jnp.int32)
        xs = ((x_proj, steps, mask.T.astype(x.dtype)) if use_m
              else (x_proj, steps))
        h_f, hs = jax.lax.scan(step, h0, xs)
        out_state: State = {"h": h_f}
        if cache is not None:
            valid = (jnp.asarray(t, jnp.int32) if mask is None
                     else jnp.sum(mask > 0, axis=1).astype(jnp.int32))
            out_state.update({"cache_x": keys, "pos": pos + valid})
        return hs.transpose(1, 2, 0), out_state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class TransformerDecoderBlockLayer(Layer):
    """Pre-LN causal transformer decoder block as ONE sequential layer:
    x + CausalAttn(LN(x)), then x + FFN(LN(x)) — residuals internal, so
    autoregressive stacks compose in a MultiLayerNetwork (whose
    ``rnn_state`` channel threads the KV cache; ComputationGraph has no
    transient-state carry). Input/output [b, n_in, t].

    Decode: :meth:`decode_state` preallocates the static-shape
    ``[b, heads, max_len, head_dim]`` K/V cache + position counter; with
    it threaded in, each ``apply`` writes the new K/V at the per-row
    position (``lax.dynamic_update_slice``) and runs single-query flash
    decode attention against the cache — the prefix is never re-run."""

    n_in: int = 0
    n_heads: int = 1
    ffn_size: int = 0
    eps: float = 1e-5

    def output_type(self, input_type: InputType) -> InputType:
        return RecurrentType(size=self.n_in, timesteps=input_type.timesteps)

    def with_input(self, input_type: InputType) -> "TransformerDecoderBlockLayer":
        out = self
        if not out.n_in:
            out = dataclasses.replace(out, n_in=input_type.size)
        if not out.ffn_size:
            out = dataclasses.replace(out, ffn_size=4 * out.n_in)
        return out

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("ln1_g", "ln1_b", "Wq", "Wk", "Wv", "Wo",
                "ln2_g", "ln2_b", "W1", "b1", "W2", "b2")

    def weight_param_names(self) -> Tuple[str, ...]:
        return ("Wq", "Wk", "Wv", "Wo", "W1", "W2")

    def init(self, key: jax.Array, dtype: Any) -> Params:
        wi = self.weight_init or WeightInit.XAVIER
        h, ffn = self.n_in, self.ffn_size
        ks = jax.random.split(key, 6)
        return {
            "ln1_g": jnp.ones((h,), dtype), "ln1_b": jnp.zeros((h,), dtype),
            "Wq": init_weights(ks[0], (h, h), wi, h, h, None, dtype),
            "Wk": init_weights(ks[1], (h, h), wi, h, h, None, dtype),
            "Wv": init_weights(ks[2], (h, h), wi, h, h, None, dtype),
            "Wo": init_weights(ks[3], (h, h), wi, h, h, None, dtype),
            "ln2_g": jnp.ones((h,), dtype), "ln2_b": jnp.zeros((h,), dtype),
            "W1": init_weights(ks[4], (h, ffn), wi, h, ffn, None, dtype),
            "b1": jnp.zeros((ffn,), dtype),
            "W2": init_weights(ks[5], (ffn, h), wi, ffn, h, None, dtype),
            "b2": jnp.zeros((h,), dtype),
        }

    def decode_state(self, batch: int, max_len: int, dtype: Any) -> State:
        d = self.n_in // self.n_heads
        shape = (batch, self.n_heads, max_len, d)
        return {"cache_k": jnp.zeros(shape, dtype),
                "cache_v": jnp.zeros(shape, dtype),
                "pos": jnp.zeros((batch,), jnp.int32)}

    def _ln(self, x, g, b):
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + self.eps) * g + b

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        xt = x.transpose(0, 2, 1)  # [b, t, h]
        h1 = self._ln(xt, params["ln1_g"], params["ln1_b"])
        q = _split_heads(h1 @ params["Wq"], self.n_heads)
        k = _split_heads(h1 @ params["Wk"], self.n_heads)
        v = _split_heads(h1 @ params["Wv"], self.n_heads)
        if "cache_k" in state:
            o, new_state = _cached_attention(q, k, v, state, ctx.mask)
        else:
            o = dot_product_attention(q, k, v, mask=ctx.mask, causal=True)
            new_state = state
        r1 = xt + _merge_heads(o) @ params["Wo"]
        h2 = self._ln(r1, params["ln2_g"], params["ln2_b"])
        act = self.activation or Activation.GELU
        ffn = act(h2 @ params["W1"] + params["b1"]) @ params["W2"] + params["b2"]
        return (r1 + ffn).transpose(0, 2, 1), new_state
