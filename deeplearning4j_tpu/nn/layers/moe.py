"""Mixture-of-Experts layer with expert parallelism.

Beyond-reference capability (SURVEY §2.3 lists EP as absent upstream;
"on TPU the absent rows come nearly free from pjit"): a Switch/GShard-style
sparse FFN whose expert weights carry a leading expert dimension that
shards over a mesh axis via ``DistributedTrainer(param_sharding_rules=
moe_expert_parallel_rules())`` — XLA then partitions the expert MLP and
inserts the all-to-alls.

Two dispatch formulations, selected by ``dispatch_mode``:

* ``"sort"`` (default) — sort-based gather/scatter dispatch
  (ops/moe_dispatch.py): one ``lax.top_k`` route, capacity slots from a
  per-expert cumsum over the flat assignment list, one gather into the
  ``[E, C, d]`` expert buffer, gate-weighted gather back. Static shapes,
  no one-hot contractions; the routing cost is O(tokens·E) index math
  instead of the einsum path's O(tokens·E·capacity·d).
* ``"einsum"`` — the classic dense Mesh-TF/GShard formulation (one-hot
  ``[tokens, E, capacity]`` dispatch/combine contractions). Kept for
  equivalence testing and as the reference semantics.

Both modes implement the exact GShard capacity contract: slots are granted
first-come-first-served in (round, token) order and tokens over an
expert's capacity are dropped (their combine weight is 0 — the residual
path carries them), so outputs and gradients agree between modes up to
float reduction order.

Observability: every ``apply`` refreshes ``state["expert_tokens"]`` ([E]
kept assignments per expert) and ``state["dropped_tokens"]`` (overflow
drops), which ``obs.record_moe_metrics``/``MoEMetricsListener`` feed into
``dl4j_tpu_moe_expert_tokens_total{layer=,expert=}`` and
``dl4j_tpu_moe_dropped_tokens_total{layer=}``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.config import register_config
from ...ops.moe_dispatch import (
    gather_dispatch,
    make_dispatch_plan,
    scatter_combine,
    top_k_routing,
)
from ..activations import Activation
from ..input_type import FeedForwardType, InputType, RecurrentType
from ..weights import WeightInit, init_weights
from .base import Layer, LayerContext, Params, State, apply_input_dropout

_DISPATCH_MODES = ("sort", "einsum")


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class MixtureOfExpertsLayer(Layer):
    """Sparse MoE FFN: router -> top-k experts (2-layer MLPs) -> combine.

    Params: router ``Wg [nIn, E]``; per-expert ``We1 [E, nIn, hidden]``,
    ``be1 [E, hidden]``, ``We2 [E, hidden, nOut]``, ``be2 [E, nOut]``.
    The leading ``E`` dim is the expert-parallel sharding axis.
    """

    n_in: int = 0
    n_out: int = 0
    num_experts: int = 4
    hidden: int = 0            # defaults to 4 * n_in
    top_k: int = 2
    capacity_factor: float = 1.5
    # GShard aux load-balance loss weight: when > 0, the training score
    # adds balance_loss_weight * (E * sum(frac_e * mass_e)) so the router
    # is PUSHED toward uniform expert load, not merely observed. 0 keeps
    # it diagnostic-only (read from state["aux_load_balance"]).
    balance_loss_weight: float = 0.0
    # "sort" (gather/scatter, default) or "einsum" (dense one-hot
    # contractions — the legacy GShard formulation, kept for equivalence
    # testing). Identical capacity/drop semantics either way.
    dispatch_mode: str = "sort"

    def __post_init__(self) -> None:
        if self.top_k < 1 or self.top_k > self.num_experts:
            raise ValueError(
                f"top_k={self.top_k} must be in [1, num_experts="
                f"{self.num_experts}]")
        if self.dispatch_mode not in _DISPATCH_MODES:
            raise ValueError(
                f"dispatch_mode={self.dispatch_mode!r} must be one of "
                f"{_DISPATCH_MODES}")

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, RecurrentType):
            return RecurrentType(size=self.n_out,
                                 timesteps=input_type.timesteps)
        return FeedForwardType(size=self.n_out)

    def with_input(self, input_type: InputType) -> "MixtureOfExpertsLayer":
        if self.n_in:
            return self
        size = input_type.size if isinstance(
            input_type, (FeedForwardType, RecurrentType)) \
            else input_type.flat_size()
        return dataclasses.replace(self, n_in=size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("Wg", "We1", "be1", "We2", "be2")

    def _hidden(self) -> int:
        return self.hidden or 4 * self.n_in

    def init_state(self, dtype: Any) -> State:
        # declared up-front so the state pytree structure is stable across
        # jitted steps (apply refreshes the values every call). Counts live
        # in float32 regardless of the compute dtype: bf16 can't represent
        # integers above 256 exactly.
        return {"aux_load_balance": jnp.zeros((), dtype),
                "expert_tokens": jnp.zeros((self.num_experts,), jnp.float32),
                "dropped_tokens": jnp.zeros((), jnp.float32)}

    def init(self, key: jax.Array, dtype: Any) -> Params:
        e, d, h, o = self.num_experts, self.n_in, self._hidden(), self.n_out
        kg, k1, k2 = jax.random.split(key, 3)
        wi = self.weight_init or WeightInit.XAVIER
        return {
            "Wg": init_weights(kg, (d, e), wi, fan_in=d, fan_out=e,
                               distribution=self.weight_init_distribution,
                               dtype=dtype),
            "We1": init_weights(k1, (e, d, h), wi, fan_in=d, fan_out=h,
                                distribution=self.weight_init_distribution,
                                dtype=dtype),
            "be1": jnp.zeros((e, h), dtype),
            "We2": init_weights(k2, (e, h, o), wi, fan_in=h, fan_out=o,
                                distribution=self.weight_init_distribution,
                                dtype=dtype),
            "be2": jnp.zeros((e, o), dtype),
        }

    def _route(self, gates: jax.Array, capacity: int,
               token_mask: Optional[jax.Array] = None):
        """Dense top-k dispatch (``dispatch_mode="einsum"``): returns
        (dispatch [b, E, C] 0/1, combine [b, E, C] gate-weighted).
        Position assignment is first-come-first-served per expert in
        (round, batch) order (GShard). Routing is ONE ``lax.top_k`` —
        round ``r``'s selection is column ``r`` of its result, replacing
        the legacy k-round argmax-and-remask loop with identical
        semantics (descending gate, ties to the lower expert index).
        ``token_mask`` [b] excludes padding tokens entirely: they claim no
        capacity slot and contribute nothing to dispatch/combine."""
        b, e = gates.shape
        gate_vals, idx = top_k_routing(gates, self.top_k)        # [b, k]
        dispatch = jnp.zeros((b, e, capacity), gates.dtype)
        combine = jnp.zeros((b, e, capacity), gates.dtype)
        # running per-expert fill across the k rounds
        fill = jnp.zeros((1, e), gates.dtype)
        for r in range(self.top_k):
            sel = jax.nn.one_hot(idx[:, r], e, dtype=gates.dtype)  # [b, E]
            if token_mask is not None:
                sel = sel * token_mask[:, None]
            # position of each token within its chosen expert's buffer,
            # counting earlier rounds' fills
            pos = (jnp.cumsum(sel, axis=0) - 1.0 + fill) * sel   # [b, E]
            pos_idx = jnp.sum(pos, axis=-1).astype(jnp.int32)    # [b]
            keep = (pos_idx < capacity).astype(gates.dtype)
            slot = jax.nn.one_hot(pos_idx, capacity,
                                  dtype=gates.dtype)             # [b, C]
            d_i = sel[:, :, None] * slot[:, None, :] * keep[:, None, None]
            dispatch = dispatch + d_i
            combine = combine + d_i * gate_vals[:, r][:, None, None]
            fill = fill + jnp.sum(sel * keep[:, None], axis=0,
                                  keepdims=True)
        # renormalize combine weights over the k selected experts
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
        return dispatch, combine

    def _experts(self, params: Params, expert_in: jax.Array) -> jax.Array:
        """Batched expert MLPs over the [E, C, d] buffer — the leading E
        dim is what expert-parallel sharding rules partition."""
        h = jnp.einsum("ecd,edh->ech", expert_in, params["We1"]) \
            + params["be1"][:, None, :]
        act = self.activation or Activation.RELU
        h = act(h)
        return jnp.einsum("ech,eho->eco", h, params["We2"]) \
            + params["be2"][:, None, :]

    def apply(self, params: Params, state: State, x: jax.Array,
              ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        recurrent = x.ndim == 3
        if recurrent:  # [b, f, t] -> tokens [b*t, f]
            b_, f_, t_ = x.shape
            x2 = jnp.transpose(x, (0, 2, 1)).reshape(b_ * t_, f_)
        else:
            x2 = x
        n_tok = x2.shape[0]
        e = self.num_experts
        capacity = max(1, int(math.ceil(
            self.top_k * n_tok / e * self.capacity_factor)))

        token_mask = None
        if recurrent and ctx.mask is not None:  # [b, t] -> [b*t]
            token_mask = jnp.reshape(
                jnp.asarray(ctx.mask, x2.dtype), (b_ * t_,))

        gates = jax.nn.softmax(x2 @ params["Wg"], axis=-1)       # [b, E]

        if self.dispatch_mode == "sort":
            gate_vals, expert_idx = top_k_routing(gates, self.top_k)
            plan = make_dispatch_plan(expert_idx, e, capacity,
                                      token_mask=token_mask)
            expert_in = gather_dispatch(x2, plan, e, capacity)   # [E, C, d]
            out_e = self._experts(params, expert_in)
            y = scatter_combine(out_e, gate_vals, plan)          # [b, o]
            expert_tokens = plan.expert_tokens.astype(jnp.float32)
            dropped = plan.dropped_tokens.astype(jnp.float32)
        else:
            dispatch, combine = self._route(gates, capacity, token_mask)
            expert_in = jnp.einsum("bec,bd->ecd", dispatch, x2)  # [E, C, d]
            out_e = self._experts(params, expert_in)
            y = jnp.einsum("bec,eco->bo", combine, out_e)        # [b, o]
            # count in f32: a bf16 sum of 0/1s goes inexact past 256
            expert_tokens = jnp.sum(dispatch.astype(jnp.float32),
                                    axis=(0, 2))
            requested = self.top_k * (
                jnp.sum(token_mask.astype(jnp.float32))
                if token_mask is not None else jnp.float32(n_tok))
            dropped = requested - jnp.sum(expert_tokens)

        # load-balance aux (GShard): fraction routed per expert x mean gate
        # mass per expert, E-scaled. Exposed via state for listeners; added
        # to the training score iff balance_loss_weight > 0 (the loss paths
        # in sequential.py/graph.py read it back). Real tokens only.
        if token_mask is not None:
            denom_tok = jnp.maximum(jnp.sum(token_mask), 1.0)
            mass = jnp.sum(gates * token_mask[:, None], axis=0) / denom_tok
        else:
            denom_tok = jnp.asarray(n_tok, gates.dtype)
            mass = jnp.mean(gates, axis=0)
        frac = expert_tokens.astype(gates.dtype) / denom_tok
        new_state = dict(state)
        new_state["aux_load_balance"] = e * jnp.sum(frac * mass)
        new_state["expert_tokens"] = expert_tokens
        new_state["dropped_tokens"] = dropped

        if recurrent:
            y = jnp.transpose(y.reshape(b_, t_, self.n_out), (0, 2, 1))
        return y, new_state
