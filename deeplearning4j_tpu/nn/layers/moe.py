"""Mixture-of-Experts layer with expert parallelism.

Beyond-reference capability (SURVEY §2.3 lists EP as absent upstream;
"on TPU the absent rows come nearly free from pjit"): a Switch/GShard-style
sparse FFN whose expert weights carry a leading expert dimension that
shards over a mesh axis via ``DistributedTrainer(param_sharding_rules=
moe_expert_parallel_rules())`` — XLA then partitions the expert MLP and
inserts the all-to-alls.

Three dispatch formulations, selected by ``dispatch_mode``:

* ``"sort"`` (default) — sort-based gather/scatter dispatch
  (ops/moe_dispatch.py): one ``lax.top_k`` route, capacity slots from a
  per-expert cumsum over the flat assignment list, one gather into the
  ``[E, C, d]`` expert buffer, gate-weighted gather back. Static shapes,
  no one-hot contractions; the routing cost is O(tokens·E) index math
  instead of the einsum path's O(tokens·E·capacity·d). The expert MLP
  still pays dense ``[E, C]`` MXU time over *capacity* slots.
* ``"grouped"`` — the fast path: the same ``DispatchPlan``, but the sort
  permutation (argsort of ``buffer_idx`` — already the by-expert order)
  feeds both expert MLP matmuls through ``ops.grouped_matmul``, grouped
  over the *actual* per-expert counts (``expert_tokens``), so padded
  capacity slots stop costing FLOPs (the Pallas kernel skips m-tiles past
  each group's frontier). The combine unsorts through the inverse
  permutation into the same gate arithmetic (``ops.combine_rows``).
* ``"einsum"`` — the classic dense Mesh-TF/GShard formulation (one-hot
  ``[tokens, E, capacity]`` dispatch/combine contractions). Kept for
  equivalence testing and as the reference semantics.

All modes implement the exact GShard capacity contract: slots are granted
first-come-first-served in (round, token) order and tokens over an
expert's capacity are dropped (their combine weight is 0 — the residual
path carries them), so outputs and gradients agree between modes up to
float reduction order.

Explicit expert parallelism: inside the ``DistributedTrainer`` explicit
shard_map path (``ctx.dist.ep_axis`` set and expert params sliced over
the mesh's model axis), each shard routes the full token set with the
replicated router, computes its local experts only — ``"sort"`` over the
local ``[E/n, C]`` buffer slice, ``"grouped"`` over the locally-sorted
rows — and combines with ``psum_scatter`` over the expert axis. Tensors
entering the local branch carry a psum-in-backward wrapper so replicated
params (router, upstream layers) receive the full cross-shard gradient.

Observability: every ``apply`` refreshes ``state["expert_tokens"]`` ([E]
kept assignments per expert) and ``state["dropped_tokens"]`` (overflow
drops), which ``obs.record_moe_metrics``/``MoEMetricsListener`` feed into
``dl4j_tpu_moe_expert_tokens_total{layer=,expert=}`` and
``dl4j_tpu_moe_dropped_tokens_total{layer=}``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ...core.config import register_config
from ...ops.grouped_matmul import grouped_matmul
from ...ops.moe_dispatch import (
    combine_rows,
    gather_dispatch,
    make_dispatch_plan,
    scatter_combine,
    top_k_routing,
)
from ..activations import Activation
from ..input_type import FeedForwardType, InputType, RecurrentType
from ..weights import WeightInit, init_weights
from .base import Layer, LayerContext, Params, State, apply_input_dropout

_DISPATCH_MODES = ("sort", "einsum", "grouped")


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _psum_in_bwd(x, axis):
    """Identity forward; psums the cotangent over ``axis`` in backward.

    Under explicit expert parallelism a replicated tensor (tokens, gates)
    enters a per-shard local-expert branch; each shard backprops only its
    own experts' contribution, so the gradient flowing back to replicated
    producers (router, upstream layers) must be summed across expert
    shards to stay replicated-consistent."""
    return x


def _psum_in_bwd_fwd(x, axis):
    return x, None


def _psum_in_bwd_bwd(axis, _, g):
    return (jax.lax.psum(g, axis),)


_psum_in_bwd.defvjp(_psum_in_bwd_fwd, _psum_in_bwd_bwd)


def _ep_sum(y_local, axis, n_shards):
    if y_local.shape[0] % n_shards == 0:
        # reduce-scatter over tokens, gather back: the psum spelled so a
        # token-sharded consumer could elide the all_gather
        return jax.lax.all_gather(
            jax.lax.psum_scatter(y_local, axis, scatter_dimension=0,
                                 tiled=True),
            axis, axis=0, tiled=True)
    return jax.lax.psum(y_local, axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _ep_combine(y_local, axis, n_shards):
    """Sum per-shard expert contributions over the expert axis, with an
    IDENTITY backward. Each shard seeds its own (replicated-identical)
    loss cotangent, so the correct per-loss cotangent of ``y_local`` is
    ``g`` unchanged; psum's default transpose would re-psum it and scale
    every expert-local gradient by the expert-axis size."""
    return _ep_sum(y_local, axis, n_shards)


def _ep_combine_fwd(y_local, axis, n_shards):
    return _ep_sum(y_local, axis, n_shards), None


def _ep_combine_bwd(axis, n_shards, _, g):
    return (g,)


_ep_combine.defvjp(_ep_combine_fwd, _ep_combine_bwd)


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class MixtureOfExpertsLayer(Layer):
    """Sparse MoE FFN: router -> top-k experts (2-layer MLPs) -> combine.

    Params: router ``Wg [nIn, E]``; per-expert ``We1 [E, nIn, hidden]``,
    ``be1 [E, hidden]``, ``We2 [E, hidden, nOut]``, ``be2 [E, nOut]``.
    The leading ``E`` dim is the expert-parallel sharding axis.
    """

    n_in: int = 0
    n_out: int = 0
    num_experts: int = 4
    hidden: int = 0            # defaults to 4 * n_in
    top_k: int = 2
    capacity_factor: float = 1.5
    # GShard aux load-balance loss weight: when > 0, the training score
    # adds balance_loss_weight * (E * sum(frac_e * mass_e)) so the router
    # is PUSHED toward uniform expert load, not merely observed. 0 keeps
    # it diagnostic-only (read from state["aux_load_balance"]).
    balance_loss_weight: float = 0.0
    # "sort" (gather/scatter, default), "grouped" (sorted grouped expert
    # matmul over actual per-expert counts — the Pallas fast path), or
    # "einsum" (dense one-hot contractions — the legacy GShard
    # formulation, kept for equivalence testing). Identical capacity/drop
    # semantics in every mode.
    dispatch_mode: str = "sort"

    def __post_init__(self) -> None:
        if self.top_k < 1 or self.top_k > self.num_experts:
            raise ValueError(
                f"top_k={self.top_k} must be in [1, num_experts="
                f"{self.num_experts}]")
        if self.dispatch_mode not in _DISPATCH_MODES:
            raise ValueError(
                f"dispatch_mode={self.dispatch_mode!r} must be one of "
                f"{_DISPATCH_MODES}")

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, RecurrentType):
            return RecurrentType(size=self.n_out,
                                 timesteps=input_type.timesteps)
        return FeedForwardType(size=self.n_out)

    def with_input(self, input_type: InputType) -> "MixtureOfExpertsLayer":
        if self.n_in:
            return self
        size = input_type.size if isinstance(
            input_type, (FeedForwardType, RecurrentType)) \
            else input_type.flat_size()
        return dataclasses.replace(self, n_in=size)

    def has_params(self) -> bool:
        return True

    def trainable_param_names(self) -> Tuple[str, ...]:
        return ("Wg", "We1", "be1", "We2", "be2")

    def _hidden(self) -> int:
        return self.hidden or 4 * self.n_in

    def init_state(self, dtype: Any) -> State:
        # declared up-front so the state pytree structure is stable across
        # jitted steps (apply refreshes the values every call). Counts live
        # in float32 regardless of the compute dtype: bf16 can't represent
        # integers above 256 exactly.
        return {"aux_load_balance": jnp.zeros((), dtype),
                "expert_tokens": jnp.zeros((self.num_experts,), jnp.float32),
                "dropped_tokens": jnp.zeros((), jnp.float32),
                "capacity_slots": jnp.zeros((), jnp.float32)}

    def init(self, key: jax.Array, dtype: Any) -> Params:
        e, d, h, o = self.num_experts, self.n_in, self._hidden(), self.n_out
        kg, k1, k2 = jax.random.split(key, 3)
        wi = self.weight_init or WeightInit.XAVIER
        return {
            "Wg": init_weights(kg, (d, e), wi, fan_in=d, fan_out=e,
                               distribution=self.weight_init_distribution,
                               dtype=dtype),
            "We1": init_weights(k1, (e, d, h), wi, fan_in=d, fan_out=h,
                                distribution=self.weight_init_distribution,
                                dtype=dtype),
            "be1": jnp.zeros((e, h), dtype),
            "We2": init_weights(k2, (e, h, o), wi, fan_in=h, fan_out=o,
                                distribution=self.weight_init_distribution,
                                dtype=dtype),
            "be2": jnp.zeros((e, o), dtype),
        }

    def _route(self, gates: jax.Array, capacity: int,
               token_mask: Optional[jax.Array] = None):
        """Dense top-k dispatch (``dispatch_mode="einsum"``): returns
        (dispatch [b, E, C] 0/1, combine [b, E, C] gate-weighted).
        Position assignment is first-come-first-served per expert in
        (round, batch) order (GShard). Routing is ONE ``lax.top_k`` —
        round ``r``'s selection is column ``r`` of its result, replacing
        the legacy k-round argmax-and-remask loop with identical
        semantics (descending gate, ties to the lower expert index).
        ``token_mask`` [b] excludes padding tokens entirely: they claim no
        capacity slot and contribute nothing to dispatch/combine."""
        b, e = gates.shape
        gate_vals, idx = top_k_routing(gates, self.top_k)        # [b, k]
        dispatch = jnp.zeros((b, e, capacity), gates.dtype)
        combine = jnp.zeros((b, e, capacity), gates.dtype)
        # running per-expert fill across the k rounds
        fill = jnp.zeros((1, e), gates.dtype)
        for r in range(self.top_k):
            sel = jax.nn.one_hot(idx[:, r], e, dtype=gates.dtype)  # [b, E]
            if token_mask is not None:
                sel = sel * token_mask[:, None]
            # position of each token within its chosen expert's buffer,
            # counting earlier rounds' fills
            pos = (jnp.cumsum(sel, axis=0) - 1.0 + fill) * sel   # [b, E]
            pos_idx = jnp.sum(pos, axis=-1).astype(jnp.int32)    # [b]
            keep = (pos_idx < capacity).astype(gates.dtype)
            slot = jax.nn.one_hot(pos_idx, capacity,
                                  dtype=gates.dtype)             # [b, C]
            d_i = sel[:, :, None] * slot[:, None, :] * keep[:, None, None]
            dispatch = dispatch + d_i
            combine = combine + d_i * gate_vals[:, r][:, None, None]
            fill = fill + jnp.sum(sel * keep[:, None], axis=0,
                                  keepdims=True)
        # renormalize combine weights over the k selected experts
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)
        return dispatch, combine

    def _expert_kernel(self, params: Params,
                       name: str) -> Tuple[jax.Array, Optional[jax.Array]]:
        """Expert weight-slab view hook: returns ``(weights, scale)``.

        The full-precision layer stores weights directly (``scale`` is
        None); ``QuantizedMixtureOfExpertsLayer`` overrides this to return
        the int8/fp8 slab plus its per-expert per-output-channel scale,
        which the matmul epilogues below fold in — so every dispatch mode
        (einsum buffer, sort buffer, grouped rows) serves quantized
        experts through the same code path."""
        return params[name], None

    def _experts(self, params: Params, expert_in: jax.Array) -> jax.Array:
        """Batched expert MLPs over the [E, C, d] buffer — the leading E
        dim is what expert-parallel sharding rules partition."""
        w1, s1 = self._expert_kernel(params, "We1")
        h = jnp.einsum("ecd,edh->ech", expert_in, w1.astype(expert_in.dtype))
        if s1 is not None:
            h = h * s1[:, None, :].astype(h.dtype)
        h = h + params["be1"][:, None, :]
        act = self.activation or Activation.RELU
        h = act(h)
        w2, s2 = self._expert_kernel(params, "We2")
        out = jnp.einsum("ech,eho->eco", h, w2.astype(h.dtype))
        if s2 is not None:
            out = out * s2[:, None, :].astype(out.dtype)
        return out + params["be2"][:, None, :]

    def _experts_grouped(self, params: Params, rows: jax.Array,
                         group_sizes: jax.Array, row_expert: jax.Array,
                         capacity: int) -> jax.Array:
        """Both expert MLP matmuls over rows pre-sorted by expert
        (``ops.grouped_matmul`` — compute proportional to actual
        per-expert counts, capacity only bounds the kernel tile).
        ``row_expert`` [N] (clipped to the local expert range) gathers
        per-row biases and quantization scales."""
        w1, s1 = self._expert_kernel(params, "We1")
        h = grouped_matmul(rows, group_sizes, w1.astype(rows.dtype),
                           max_group_size=capacity)
        if s1 is not None:
            h = h * jnp.take(s1, row_expert, axis=0).astype(h.dtype)
        h = h + jnp.take(params["be1"], row_expert, axis=0)
        act = self.activation or Activation.RELU
        h = act(h)
        w2, s2 = self._expert_kernel(params, "We2")
        out = grouped_matmul(h, group_sizes, w2.astype(h.dtype),
                             max_group_size=capacity)
        if s2 is not None:
            out = out * jnp.take(s2, row_expert, axis=0).astype(out.dtype)
        return out + jnp.take(params["be2"], row_expert, axis=0)

    def _grouped_rows(self, params: Params, x2: jax.Array,
                      buffer_idx: jax.Array, group_sizes: jax.Array,
                      n_local: int, capacity: int) -> jax.Array:
        """Sorted grouped expert compute returning per-assignment output
        rows [k*n, o] in round-major flat order (ready for
        ``ops.combine_rows``).

        ``buffer_idx`` sorts kept assignments by (expert, slot) with
        dropped/non-local assignments on a past-the-end sentinel, so its
        argsort IS the by-expert order and rows past
        ``sum(group_sizes)`` come back zero from the grouped matmul
        (their bias-path values are discarded by the combine's zero gate,
        exactly like the sort path's empty buffer slots)."""
        kn = buffer_idx.shape[0]
        n_tok = x2.shape[0]
        k = kn // n_tok
        order = jnp.argsort(buffer_idx)                     # by-expert order
        flat_token = jnp.tile(jnp.arange(n_tok, dtype=jnp.int32), k)
        rows_in = jnp.take(x2, flat_token[order], axis=0)   # [k*n, d]
        sizes = group_sizes.astype(jnp.int32)
        ends = jnp.cumsum(sizes)
        row_expert = jnp.minimum(
            jnp.searchsorted(ends, jnp.arange(kn, dtype=ends.dtype),
                             side="right"),
            n_local - 1).astype(jnp.int32)
        out_rows = self._experts_grouped(params, rows_in, sizes, row_expert,
                                         capacity)
        # inverse permutation: back to round-major assignment order
        inv = jnp.zeros((kn,), jnp.int32).at[order].set(
            jnp.arange(kn, dtype=jnp.int32))
        return jnp.take(out_rows, inv, axis=0)

    def _ep_forward(self, params: Params, x2: jax.Array,
                    gate_vals: jax.Array, plan, capacity: int,
                    ep_axis: str) -> jax.Array:
        """Explicit expert parallelism inside shard_map: this shard holds
        ``E/n`` experts (params sliced over the model axis by the
        trainer), routes the full replicated token set, computes only the
        assignments its experts own, and combines with ``psum_scatter``
        over the expert axis. Replicated inputs to the local branch are
        wrapped so their gradients psum across shards (see
        ``_psum_in_bwd``)."""
        e = self.num_experts
        e_loc = self._expert_kernel(params, "We1")[0].shape[0]
        n_shards = e // e_loc
        x2w = _psum_in_bwd(x2, ep_axis)
        gate_w = _psum_in_bwd(gate_vals, ep_axis)
        shard = jax.lax.axis_index(ep_axis)
        first_slot = shard * (e_loc * capacity)
        local_idx = plan.buffer_idx - first_slot
        in_local = (local_idx >= 0) & (local_idx < e_loc * capacity)
        local_idx = jnp.where(in_local, local_idx,
                              e_loc * capacity).astype(jnp.int32)
        if self.dispatch_mode == "grouped":
            sizes_local = jax.lax.dynamic_slice_in_dim(
                plan.expert_tokens, shard * e_loc, e_loc)
            rows = self._grouped_rows(params, x2w, local_idx, sizes_local,
                                      e_loc, capacity)
            # non-local assignments carry real gates: their rows must be
            # exactly zero so only the owning shard contributes
            rows = rows * in_local[:, None].astype(rows.dtype)
        else:  # "sort" over the local [E/n, C] buffer slice
            slot_local = jax.lax.dynamic_slice_in_dim(
                plan.slot_token, first_slot, e_loc * capacity)
            expert_in = jnp.take(x2w, slot_local, axis=0, mode="fill",
                                 fill_value=0).reshape(e_loc, capacity,
                                                       x2.shape[-1])
            out_e = self._experts(params, expert_in)
            rows = jnp.take(out_e.reshape(e_loc * capacity, -1), local_idx,
                            axis=0, mode="fill", fill_value=0)
        y_local = combine_rows(rows, gate_w, plan.keep)
        return _ep_combine(y_local, ep_axis, n_shards)

    def apply(self, params: Params, state: State, x: jax.Array,
              ctx: LayerContext) -> Tuple[jax.Array, State]:
        x = apply_input_dropout(self, x, ctx)
        recurrent = x.ndim == 3
        if recurrent:  # [b, f, t] -> tokens [b*t, f]
            b_, f_, t_ = x.shape
            x2 = jnp.transpose(x, (0, 2, 1)).reshape(b_ * t_, f_)
        else:
            x2 = x
        n_tok = x2.shape[0]
        e = self.num_experts
        capacity = max(1, int(math.ceil(
            self.top_k * n_tok / e * self.capacity_factor)))

        token_mask = None
        if recurrent and ctx.mask is not None:  # [b, t] -> [b*t]
            token_mask = jnp.reshape(
                jnp.asarray(ctx.mask, x2.dtype), (b_ * t_,))

        gates = jax.nn.softmax(x2 @ params["Wg"], axis=-1)       # [b, E]

        e_loc = self._expert_kernel(params, "We1")[0].shape[0]
        ep_axis = getattr(ctx.dist, "ep_axis", None) if ctx.dist else None
        ep = ep_axis is not None and e_loc != e
        if ep:
            if self.dispatch_mode == "einsum":
                raise ValueError(
                    "dispatch_mode='einsum' has no explicit expert-parallel "
                    "spelling; use 'sort' or 'grouped'")
            if e % e_loc != 0:
                raise ValueError(
                    f"num_experts={e} must divide evenly over the expert-"
                    f"parallel axis (local shard holds {e_loc})")

        if self.dispatch_mode in ("sort", "grouped"):
            gate_vals, expert_idx = top_k_routing(gates, self.top_k)
            plan = make_dispatch_plan(expert_idx, e, capacity,
                                      token_mask=token_mask)
            if ep:
                y = self._ep_forward(params, x2, gate_vals, plan, capacity,
                                     ep_axis)
            elif self.dispatch_mode == "grouped":
                rows = self._grouped_rows(params, x2, plan.buffer_idx,
                                          plan.expert_tokens, e, capacity)
                y = combine_rows(rows, gate_vals, plan.keep)     # [b, o]
            else:
                expert_in = gather_dispatch(x2, plan, e, capacity)
                out_e = self._experts(params, expert_in)         # [E, C, o]
                y = scatter_combine(out_e, gate_vals, plan)      # [b, o]
            expert_tokens = plan.expert_tokens.astype(jnp.float32)
            dropped = plan.dropped_tokens.astype(jnp.float32)
        else:
            dispatch, combine = self._route(gates, capacity, token_mask)
            expert_in = jnp.einsum("bec,bd->ecd", dispatch, x2)  # [E, C, d]
            out_e = self._experts(params, expert_in)
            y = jnp.einsum("bec,eco->bo", combine, out_e)        # [b, o]
            # count in f32: a bf16 sum of 0/1s goes inexact past 256
            expert_tokens = jnp.sum(dispatch.astype(jnp.float32),
                                    axis=(0, 2))
            requested = self.top_k * (
                jnp.sum(token_mask.astype(jnp.float32))
                if token_mask is not None else jnp.float32(n_tok))
            dropped = requested - jnp.sum(expert_tokens)

        # load-balance aux (GShard): fraction routed per expert x mean gate
        # mass per expert, E-scaled. Exposed via state for listeners; added
        # to the training score iff balance_loss_weight > 0 (the loss paths
        # in sequential.py/graph.py read it back). Real tokens only.
        if token_mask is not None:
            denom_tok = jnp.maximum(jnp.sum(token_mask), 1.0)
            mass = jnp.sum(gates * token_mask[:, None], axis=0) / denom_tok
        else:
            denom_tok = jnp.asarray(n_tok, gates.dtype)
            mass = jnp.mean(gates, axis=0)
        frac = expert_tokens.astype(gates.dtype) / denom_tok
        new_state = dict(state)
        new_state["aux_load_balance"] = e * jnp.sum(frac * mass)
        new_state["expert_tokens"] = expert_tokens
        new_state["dropped_tokens"] = dropped
        # total granted capacity slots (E * C) this batch — lets listeners
        # derive occupancy/drop pressure without re-deriving the GShard
        # capacity formula client-side
        new_state["capacity_slots"] = jnp.asarray(e * capacity, jnp.float32)

        if recurrent:
            y = jnp.transpose(y.reshape(b_, t_, self.n_out), (0, 2, 1))
        return y, new_state
