"""Pooling and spatial reshape layers.

Reference configs: SubsamplingLayer / Subsampling1DLayer / Subsampling3DLayer,
GlobalPoolingLayer, Upsampling1D/2D/3D, ZeroPaddingLayer, Cropping2D,
SpaceToDepthLayer (canonical: org.deeplearning4j.nn.conf.layers.*). All lower
to ``lax.reduce_window`` / reshape — XLA maps these directly onto the VPU.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ...core.config import register_config
from ..input_type import (
    Convolutional3DType,
    ConvolutionalType,
    FeedForwardType,
    InputType,
    RecurrentType,
)
from .base import Layer, LayerContext, Params, State
from .conv import ConvolutionMode, _lax_padding, _out_size


class PoolingType(enum.Enum):
    MAX = "MAX"
    AVG = "AVG"
    SUM = "SUM"
    PNORM = "PNORM"


def _pool(x, pooling, window, strides, padding, pnorm: int = 2, spatial_axes=None):
    """reduce_window pooling over the given spatial window (full-shape specs)."""
    if pooling is PoolingType.MAX:
        init = -jnp.inf
        y = lax.reduce_window(x, init, lax.max, window, strides, padding)
        return y
    if pooling in (PoolingType.AVG, PoolingType.SUM):
        y = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
        if pooling is PoolingType.SUM:
            return y
        if padding == "SAME" or (isinstance(padding, (list, tuple)) and any(p != (0, 0) for p in padding)):
            # divide by the actual (unpadded) window count per position
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
            return y / counts
        denom = 1
        for w in window:
            denom *= w
        return y / denom
    if pooling is PoolingType.PNORM:
        p = float(pnorm)
        y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, padding)
        return y ** (1.0 / p)
    raise ValueError(f"Unhandled pooling {pooling}")


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class SubsamplingLayer(Layer):
    """2-D pooling over NCHW (reference: SubsamplingLayer)."""

    pooling_type: PoolingType = PoolingType.MAX
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        h = _out_size(input_type.height, self.kernel_size[0], self.stride[0],
                      self.padding[0], self.dilation[0], self.convolution_mode)
        w = _out_size(input_type.width, self.kernel_size[1], self.stride[1],
                      self.padding[1], self.dilation[1], self.convolution_mode)
        return ConvolutionalType(height=h, width=w, channels=input_type.channels)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        pad = _lax_padding(self.convolution_mode, self.padding, self.kernel_size, self.dilation)
        if isinstance(pad, list):
            pad = [(0, 0), (0, 0)] + pad
        window = (1, 1) + tuple(self.kernel_size)
        strides = (1, 1) + tuple(self.stride)
        return _pool(x, self.pooling_type, window, strides, pad, self.pnorm), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Subsampling1DLayer(Layer):
    """1-D pooling over [batch, channels, time] (reference: Subsampling1DLayer)."""

    pooling_type: PoolingType = PoolingType.MAX
    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        ts = input_type.timesteps
        if ts is not None:
            ts = _out_size(ts, self.kernel_size, self.stride, self.padding, 1, self.convolution_mode)
        return RecurrentType(size=input_type.size, timesteps=ts)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        pad = _lax_padding(self.convolution_mode, (self.padding,), (self.kernel_size,), (1,))
        if isinstance(pad, list):
            pad = [(0, 0), (0, 0)] + pad
        window = (1, 1, self.kernel_size)
        strides = (1, 1, self.stride)
        return _pool(x, self.pooling_type, window, strides, pad, self.pnorm), state

    def feed_forward_mask(self, mask, input_type):
        if mask is None:
            return None
        return mask[:, :: self.stride]


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Subsampling3DLayer(Layer):
    """3-D pooling over NCDHW (reference: Subsampling3DLayer)."""

    pooling_type: PoolingType = PoolingType.MAX
    kernel_size: Tuple[int, int, int] = (2, 2, 2)
    stride: Tuple[int, int, int] = (2, 2, 2)
    padding: Tuple[int, int, int] = (0, 0, 0)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE

    def output_type(self, input_type: InputType) -> InputType:
        d, h, w = (
            _out_size(s, k, st, p, 1, self.convolution_mode)
            for s, k, st, p in zip(
                (input_type.depth, input_type.height, input_type.width),
                self.kernel_size, self.stride, self.padding,
            )
        )
        return Convolutional3DType(depth=d, height=h, width=w, channels=input_type.channels)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        pad = _lax_padding(self.convolution_mode, self.padding, self.kernel_size, (1, 1, 1))
        if isinstance(pad, list):
            pad = [(0, 0), (0, 0)] + pad
        window = (1, 1) + tuple(self.kernel_size)
        strides = (1, 1) + tuple(self.stride)
        return _pool(x, self.pooling_type, window, strides, pad), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class GlobalPoolingLayer(Layer):
    """Global pooling over spatial/time dims with mask support (reference:
    GlobalPoolingLayer). CNN input -> [batch, channels]; recurrent input
    [batch, size, time] -> [batch, size] honoring the time mask."""

    pooling_type: PoolingType = PoolingType.MAX
    pnorm: int = 2
    collapse_dimensions: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if isinstance(input_type, RecurrentType):
            return FeedForwardType(size=input_type.size)
        if isinstance(input_type, ConvolutionalType):
            return FeedForwardType(size=input_type.channels)
        if isinstance(input_type, Convolutional3DType):
            return FeedForwardType(size=input_type.channels)
        return input_type

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        axes = tuple(range(2, x.ndim))
        mask = ctx.mask
        if mask is not None and x.ndim == 3:  # recurrent [b, c, t], mask [b, t]
            m = mask[:, None, :].astype(x.dtype)
            if self.pooling_type is PoolingType.MAX:
                neg = jnp.asarray(jnp.finfo(x.dtype).min, x.dtype)
                return jnp.max(jnp.where(m > 0, x, neg), axis=2), state
            if self.pooling_type in (PoolingType.AVG, PoolingType.SUM):
                s = jnp.sum(x * m, axis=2)
                if self.pooling_type is PoolingType.SUM:
                    return s, state
                return s / jnp.maximum(jnp.sum(m, axis=2), 1.0), state
            p = float(self.pnorm)
            s = jnp.sum((jnp.abs(x) * m) ** p, axis=2)
            return s ** (1.0 / p), state
        if self.pooling_type is PoolingType.MAX:
            return jnp.max(x, axis=axes), state
        if self.pooling_type is PoolingType.AVG:
            return jnp.mean(x, axis=axes), state
        if self.pooling_type is PoolingType.SUM:
            return jnp.sum(x, axis=axes), state
        p = float(self.pnorm)
        return jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p), state

    def feed_forward_mask(self, mask, input_type):
        return None  # time dimension is consumed


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Upsampling2DLayer(Layer):
    """Nearest-neighbor upsampling (reference: Upsampling2D)."""

    size: Tuple[int, int] = (2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        return ConvolutionalType(
            height=input_type.height * self.size[0],
            width=input_type.width * self.size[1],
            channels=input_type.channels,
        )

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        y = jnp.repeat(jnp.repeat(x, self.size[0], axis=2), self.size[1], axis=3)
        return y, state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Upsampling1DLayer(Layer):
    size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        ts = input_type.timesteps
        return RecurrentType(size=input_type.size, timesteps=None if ts is None else ts * self.size)

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        return jnp.repeat(x, self.size, axis=2), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Upsampling3DLayer(Layer):
    size: Tuple[int, int, int] = (2, 2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        return Convolutional3DType(
            depth=input_type.depth * self.size[0],
            height=input_type.height * self.size[1],
            width=input_type.width * self.size[2],
            channels=input_type.channels,
        )

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        for ax, s in zip((2, 3, 4), self.size):
            x = jnp.repeat(x, s, axis=ax)
        return x, state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class ZeroPaddingLayer(Layer):
    """Zero padding for NCHW (reference: ZeroPaddingLayer). padding =
    (top, bottom, left, right)."""

    padding: Tuple[int, int, int, int] = (1, 1, 1, 1)

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self.padding
        return ConvolutionalType(
            height=input_type.height + t + b,
            width=input_type.width + l + r,
            channels=input_type.channels,
        )

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        t, b, l, r = self.padding
        return jnp.pad(x, ((0, 0), (0, 0), (t, b), (l, r))), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class ZeroPadding1DLayer(Layer):
    padding: Tuple[int, int] = (1, 1)

    def output_type(self, input_type: InputType) -> InputType:
        ts = input_type.timesteps
        return RecurrentType(
            size=input_type.size,
            timesteps=None if ts is None else ts + self.padding[0] + self.padding[1],
        )

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        return jnp.pad(x, ((0, 0), (0, 0), self.padding)), state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class Cropping2DLayer(Layer):
    """Crop NCHW spatially (reference: Cropping2D). crop = (top, bottom, left, right)."""

    crop: Tuple[int, int, int, int] = (0, 0, 0, 0)

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self.crop
        return ConvolutionalType(
            height=input_type.height - t - b,
            width=input_type.width - l - r,
            channels=input_type.channels,
        )

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        t, b, l, r = self.crop
        h, w = x.shape[2], x.shape[3]
        return x[:, :, t : h - b, l : w - r], state


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class SpaceToDepthLayer(Layer):
    """NCHW space-to-depth (reference: SpaceToDepthLayer)."""

    block_size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        bs = self.block_size
        return ConvolutionalType(
            height=input_type.height // bs,
            width=input_type.width // bs,
            channels=input_type.channels * bs * bs,
        )

    def apply(self, params: Params, state: State, x: jax.Array, ctx: LayerContext) -> Tuple[jax.Array, State]:
        n, c, h, w = x.shape
        bs = self.block_size
        y = x.reshape(n, c, h // bs, bs, w // bs, bs)
        y = y.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * bs * bs, h // bs, w // bs)
        return y, state
