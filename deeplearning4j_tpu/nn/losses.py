"""Loss functions.

Parity with the reference's ``org.nd4j.linalg.lossfunctions.LossFunctions``
(canonical: nd4j-api, ILossFunction impls). Semantics preserved:

* per-example score arrays (for masking / weighted losses), mean-reduced score;
* optional per-output ``weights`` vector;
* optional ``mask`` — [batch] or [batch, time] for sequence outputs (callers
  flatten time into batch before calling, as the reference's RnnOutputLayer
  does);
* softmax+MCXENT and sigmoid+XENT compute from pre-activations via log-softmax
  / logits for numerical stability — mathematically identical to the
  reference's activate-then-loss with its fused backward.

Gradients come from jax autodiff; there is no ``computeGradient`` twin to keep
in sync (a classic divergence bug source in the reference, where ILossFunction
implements score and gradient separately).
"""

from __future__ import annotations

import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .activations import Activation

_EPS = 1e-7


def _apply_mask_and_mean(per_example: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
    if mask is not None:
        mask = mask.reshape(per_example.shape[0])
        per_example = per_example * mask
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.sum(per_example) / denom
    return jnp.mean(per_example)


class LossFunction(enum.Enum):
    MSE = "MSE"
    L1 = "L1"
    L2 = "L2"
    XENT = "XENT"
    MCXENT = "MCXENT"
    SPARSE_MCXENT = "SPARSE_MCXENT"
    NEGATIVELOGLIKELIHOOD = "NEGATIVELOGLIKELIHOOD"
    COSINE_PROXIMITY = "COSINE_PROXIMITY"
    HINGE = "HINGE"
    SQUARED_HINGE = "SQUARED_HINGE"
    KL_DIVERGENCE = "KL_DIVERGENCE"
    MEAN_ABSOLUTE_ERROR = "MEAN_ABSOLUTE_ERROR"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "MEAN_ABSOLUTE_PERCENTAGE_ERROR"
    MEAN_SQUARED_LOGARITHMIC_ERROR = "MEAN_SQUARED_LOGARITHMIC_ERROR"
    POISSON = "POISSON"
    WASSERSTEIN = "WASSERSTEIN"

    @classmethod
    def from_any(cls, l) -> "LossFunction":
        if isinstance(l, LossFunction):
            return l
        return cls[str(l).upper()]

    def score_array(
        self,
        labels: jax.Array,
        preoutput: jax.Array,
        activation: Activation,
        weights: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Per-example scores, shape [batch]. ``preoutput`` is pre-activation."""
        return _score_array(self, labels, preoutput, activation, weights)

    def score(
        self,
        labels: jax.Array,
        preoutput: jax.Array,
        activation: Activation,
        mask: Optional[jax.Array] = None,
        weights: Optional[jax.Array] = None,
    ) -> jax.Array:
        per = self.score_array(labels, preoutput, activation, weights)
        return _apply_mask_and_mean(per, mask)


def _weighted(err: jax.Array, weights: Optional[jax.Array]) -> jax.Array:
    if weights is not None:
        err = err * weights
    return err


def _score_array(
    kind: LossFunction,
    labels: jax.Array,
    pre: jax.Array,
    activation: Activation,
    weights: Optional[jax.Array],
) -> jax.Array:
    act = Activation.from_any(activation)
    # loss math in >= f32 regardless of compute dtype: log/exp/div on bf16
    # logits is where mixed precision loses accuracy for no speed win (the
    # FLOPs live in the matmuls, not here)
    if jnp.issubdtype(pre.dtype, jnp.floating):
        f32 = jnp.promote_types(pre.dtype, jnp.float32)
        pre = pre.astype(f32)
        if jnp.issubdtype(jnp.asarray(labels).dtype, jnp.floating):
            labels = jnp.asarray(labels).astype(f32)
    sum_last = lambda a: jnp.sum(a, axis=tuple(range(1, a.ndim)))

    if kind in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
        if act is Activation.SOFTMAX:
            logp = jax.nn.log_softmax(pre, axis=-1)
        else:
            logp = jnp.log(jnp.clip(act(pre), _EPS, 1.0))
        return sum_last(_weighted(-labels * logp, weights))

    if kind is LossFunction.SPARSE_MCXENT:
        if act is Activation.SOFTMAX:
            logp = jax.nn.log_softmax(pre, axis=-1)
        else:
            logp = jnp.log(jnp.clip(act(pre), _EPS, 1.0))
        idx = labels.astype(jnp.int32)
        if idx.ndim == logp.ndim:  # [batch, 1] -> [batch]
            idx = idx.squeeze(-1)
        picked = jnp.take_along_axis(logp, idx[..., None], axis=-1).squeeze(-1)
        return -picked

    if kind is LossFunction.XENT:
        if act is Activation.SIGMOID:
            # stable BCE-with-logits
            per = jnp.maximum(pre, 0) - pre * labels + jnp.log1p(jnp.exp(-jnp.abs(pre)))
        else:
            p = jnp.clip(act(pre), _EPS, 1.0 - _EPS)
            per = -(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p))
        return sum_last(_weighted(per, weights))

    out = act(pre)
    if kind is LossFunction.MSE:
        return sum_last(_weighted((out - labels) ** 2, weights)) / out.shape[-1]
    if kind is LossFunction.L2:
        return sum_last(_weighted((out - labels) ** 2, weights))
    if kind is LossFunction.MEAN_ABSOLUTE_ERROR:
        return sum_last(_weighted(jnp.abs(out - labels), weights)) / out.shape[-1]
    if kind is LossFunction.L1:
        return sum_last(_weighted(jnp.abs(out - labels), weights))
    if kind is LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR:
        pct = jnp.abs((labels - out) / jnp.clip(jnp.abs(labels), _EPS)) * 100.0
        return sum_last(_weighted(pct, weights)) / out.shape[-1]
    if kind is LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR:
        per = (jnp.log1p(jnp.clip(out, -1 + _EPS)) - jnp.log1p(jnp.clip(labels, -1 + _EPS))) ** 2
        return sum_last(_weighted(per, weights)) / out.shape[-1]
    if kind is LossFunction.COSINE_PROXIMITY:
        on = out / jnp.clip(jnp.linalg.norm(out, axis=-1, keepdims=True), _EPS)
        ln = labels / jnp.clip(jnp.linalg.norm(labels, axis=-1, keepdims=True), _EPS)
        return -sum_last(on * ln)
    if kind is LossFunction.HINGE:
        return sum_last(_weighted(jnp.maximum(0.0, 1.0 - labels * out), weights))
    if kind is LossFunction.SQUARED_HINGE:
        return sum_last(_weighted(jnp.maximum(0.0, 1.0 - labels * out) ** 2, weights))
    if kind is LossFunction.KL_DIVERGENCE:
        p = jnp.clip(labels, _EPS, 1.0)
        q = jnp.clip(out, _EPS, 1.0)
        return sum_last(_weighted(p * (jnp.log(p) - jnp.log(q)), weights))
    if kind is LossFunction.POISSON:
        return sum_last(_weighted(out - labels * jnp.log(jnp.clip(out, _EPS)), weights))
    if kind is LossFunction.WASSERSTEIN:
        return sum_last(_weighted(labels * out, weights))
    raise ValueError(f"Unhandled loss {kind}")
