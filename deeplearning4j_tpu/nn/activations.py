"""Activation functions.

Capability parity with the reference's ``org.nd4j.linalg.activations.Activation``
enum (canonical: nd4j-api, ~20 members). Each is a pure jnp function; XLA fuses
them into adjacent matmuls/convs, so there is no per-activation kernel to write
(the reference needs one native kernel per activation per dtype — SURVEY.md
§2.1 "legacy op loops").
"""

from __future__ import annotations

import enum
from typing import Callable, Dict

import jax
import jax.numpy as jnp


def _cube(x):
    return x * x * x


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _rationaltanh(x):
    # DL4J's RationalTanh: 1.7159 * tanh_approx(2x/3) via rational approximation
    a = 0.6666667 * x
    approx = jnp.sign(a) * (1.0 - 1.0 / (1.0 + jnp.abs(a) + a * a + 1.41645 * a * a * a * a))
    return 1.7159 * approx


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def _thresholdedrelu(x, theta: float = 1.0):
    return jnp.where(x > theta, x, 0.0)


ACTIVATIONS: Dict[str, Callable] = {
    "IDENTITY": lambda x: x,
    "RELU": jax.nn.relu,
    "RELU6": jax.nn.relu6,
    "LEAKYRELU": lambda x: jax.nn.leaky_relu(x, 0.01),
    "ELU": jax.nn.elu,
    "SELU": jax.nn.selu,
    "CELU": jax.nn.celu,
    "GELU": jax.nn.gelu,
    "SIGMOID": jax.nn.sigmoid,
    "HARDSIGMOID": _hardsigmoid,
    "TANH": jnp.tanh,
    "HARDTANH": _hardtanh,
    "RATIONALTANH": _rationaltanh,
    "RECTIFIEDTANH": _rectifiedtanh,
    "SOFTMAX": lambda x: jax.nn.softmax(x, axis=-1),
    "LOGSOFTMAX": lambda x: jax.nn.log_softmax(x, axis=-1),
    "SOFTPLUS": jax.nn.softplus,
    "SOFTSIGN": jax.nn.soft_sign,
    "CUBE": _cube,
    "SWISH": jax.nn.swish,
    "MISH": jax.nn.mish,
    "THRESHOLDEDRELU": _thresholdedrelu,
    "GLU": lambda x: jax.nn.glu(x, axis=-1),
}


class Activation(enum.Enum):
    """Named activations matching the reference enum's vocabulary."""

    IDENTITY = "IDENTITY"
    RELU = "RELU"
    RELU6 = "RELU6"
    LEAKYRELU = "LEAKYRELU"
    ELU = "ELU"
    SELU = "SELU"
    CELU = "CELU"
    GELU = "GELU"
    SIGMOID = "SIGMOID"
    HARDSIGMOID = "HARDSIGMOID"
    TANH = "TANH"
    HARDTANH = "HARDTANH"
    RATIONALTANH = "RATIONALTANH"
    RECTIFIEDTANH = "RECTIFIEDTANH"
    SOFTMAX = "SOFTMAX"
    LOGSOFTMAX = "LOGSOFTMAX"
    SOFTPLUS = "SOFTPLUS"
    SOFTSIGN = "SOFTSIGN"
    CUBE = "CUBE"
    SWISH = "SWISH"
    MISH = "MISH"
    THRESHOLDEDRELU = "THRESHOLDEDRELU"
    GLU = "GLU"

    def __call__(self, x):
        return ACTIVATIONS[self.value](x)

    @classmethod
    def from_any(cls, a) -> "Activation":
        if isinstance(a, Activation):
            return a
        if isinstance(a, str):
            return cls[a.upper()]
        raise TypeError(f"Cannot interpret activation: {a!r}")
