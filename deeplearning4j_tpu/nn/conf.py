"""Network configuration DSL.

Reference: org.deeplearning4j.nn.conf.{NeuralNetConfiguration.Builder,
MultiLayerConfiguration} (canonical: deeplearning4j-nn). The builder collects
global defaults (updater, weight init, activation, regularization, dropout),
``.list()`` collects layers, ``.set_input_type()`` runs the shape-inference
walk that resolves every layer's nIn and auto-inserts preprocessors at format
boundaries, and ``.build()`` returns an immutable, JSON-round-trippable
``MultiLayerConfiguration``.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, List, Optional, Tuple

from ..core.config import register_config
from .activations import Activation
from .input_type import (
    ConvolutionalFlatType,
    ConvolutionalType,
    FeedForwardType,
    InputType,
    RecurrentType,
)
from .layers.base import Layer
from .layers import (
    BatchNormalizationLayer,
    Convolution1DLayer,
    Convolution3DLayer,
    ConvolutionLayer,
    Deconvolution2DLayer,
    DepthwiseConvolution2DLayer,
    SeparableConvolution2DLayer,
    SubsamplingLayer,
    Subsampling1DLayer,
    Subsampling3DLayer,
    LocalResponseNormalizationLayer,
    ZeroPaddingLayer,
    ZeroPadding1DLayer,
    Cropping2DLayer,
    SpaceToDepthLayer,
    Upsampling2DLayer,
    Upsampling1DLayer,
    Upsampling3DLayer,
    DenseLayer,
    OutputLayer,
    LossLayer,
    EmbeddingLayer,
    EmbeddingSequenceLayer,
    LSTMLayer,
    GravesLSTMLayer,
    SimpleRnnLayer,
    BidirectionalLayer,
    LastTimeStepLayer,
    MaskZeroLayer,
    TimeDistributedLayer,
    MixtureOfExpertsLayer,
    SelfAttentionLayer,
    LearnedSelfAttentionLayer,
    RecurrentAttentionLayer,
    RnnOutputLayer,
    RnnLossLayer,
    CnnLossLayer,
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    RnnToFeedForwardPreProcessor,
)
from .weights import Distribution, WeightInit


class GradientNormalization(enum.Enum):
    """Reference: org.deeplearning4j.nn.conf.GradientNormalization."""

    NONE = "None"
    RENORMALIZE_L2_PER_LAYER = "RenormalizeL2PerLayer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "RenormalizeL2PerParamType"
    CLIP_ELEMENT_WISE_ABSOLUTE_VALUE = "ClipElementWiseAbsoluteValue"
    CLIP_L2_PER_LAYER = "ClipL2PerLayer"
    CLIP_L2_PER_PARAM_TYPE = "ClipL2PerParamType"


class BackpropType(enum.Enum):
    STANDARD = "Standard"
    TRUNCATED_BPTT = "TruncatedBPTT"


class WorkspaceMode(enum.Enum):
    """Kept for config-surface parity; on TPU XLA owns buffer reuse and
    ``donate_argnums`` plays the workspace role (SURVEY.md §7), so this is a
    no-op knob recorded in the config."""

    ENABLED = "ENABLED"
    NONE = "NONE"


# Layer families for preprocessor insertion (reference: each layer conf's
# getPreProcessorForInputType).
_CNN_LAYERS = (
    ConvolutionLayer, SubsamplingLayer, LocalResponseNormalizationLayer,
    Deconvolution2DLayer, DepthwiseConvolution2DLayer, SeparableConvolution2DLayer,
    ZeroPaddingLayer, Cropping2DLayer, SpaceToDepthLayer, Upsampling2DLayer,
    CnnLossLayer,
)
_CNN3D_LAYERS = (Convolution3DLayer, Subsampling3DLayer, Upsampling3DLayer)
_RNN_LAYERS = (
    Convolution1DLayer, Subsampling1DLayer, ZeroPadding1DLayer, Upsampling1DLayer,
    LSTMLayer, GravesLSTMLayer, SimpleRnnLayer, BidirectionalLayer,
    MaskZeroLayer, TimeDistributedLayer, SelfAttentionLayer,
    LearnedSelfAttentionLayer, RecurrentAttentionLayer,
    RnnOutputLayer, RnnLossLayer, LastTimeStepLayer,
)
_FF_LAYERS = (DenseLayer, OutputLayer, EmbeddingLayer)
# Token layers consume FF ([b, f]) and recurrent ([b, f, t]) input natively
# (MoE treats timesteps as extra tokens), so they only need flattening from
# spatial input — inserting RnnToFeedForward would destroy the per-sequence
# token_mask path.
_TOKEN_LAYERS = (MixtureOfExpertsLayer,)


def _needs(layer: Layer) -> str:
    if isinstance(layer, _CNN3D_LAYERS):
        return "cnn3d"
    if isinstance(layer, _CNN_LAYERS):
        return "cnn"
    if isinstance(layer, _RNN_LAYERS):
        return "rnn"
    if isinstance(layer, _TOKEN_LAYERS):
        return "tokens"
    if isinstance(layer, _FF_LAYERS):
        return "ff"
    return "any"


def _preprocessor_for(current: InputType, need: str) -> Optional[Layer]:
    if need == "cnn":
        if isinstance(current, ConvolutionalFlatType):
            return FeedForwardToCnnPreProcessor(
                height=current.height, width=current.width, channels=current.channels
            )
        if isinstance(current, ConvolutionalType):
            return None
        if isinstance(current, FeedForwardType):
            raise ValueError(
                "Cannot feed feed-forward data into a CNN layer without spatial "
                "dimensions; declare InputType.convolutional_flat(...) instead"
            )
        return None
    if need == "ff":
        if isinstance(current, ConvolutionalType):
            return CnnToFeedForwardPreProcessor(
                height=current.height, width=current.width, channels=current.channels
            )
        if isinstance(current, RecurrentType):
            return RnnToFeedForwardPreProcessor()
        return None
    if need == "rnn":
        if isinstance(current, ConvolutionalType):
            return CnnToRnnPreProcessor(
                height=current.height, width=current.width, channels=current.channels
            )
        return None
    if need == "tokens":
        if isinstance(current, ConvolutionalType):
            return CnnToFeedForwardPreProcessor(
                height=current.height, width=current.width, channels=current.channels
            )
        return None
    return None


@register_config
@dataclasses.dataclass(frozen=True, kw_only=True)
class MultiLayerConfiguration:
    """Immutable network config (reference: MultiLayerConfiguration).
    ``layers`` already include auto-inserted preprocessors and fully resolved
    nIn values when built via the builder with an input type."""

    layers: Tuple[Layer, ...] = ()
    input_type: Optional[InputType] = None
    seed: int = 0
    dtype: str = "float32"
    # Mixed precision: params/updater state stay in ``dtype`` (f32 master
    # weights); forward/backward math runs in ``compute_dtype`` (bf16 on the
    # TPU MXU). None = compute in ``dtype`` (no mixed precision).
    compute_dtype: Optional[str] = None
    updater: Optional[Any] = None
    backprop_type: BackpropType = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    gradient_normalization: GradientNormalization = GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0
    # Per-layer rematerialization (jax.checkpoint around each layer apply):
    # frees intra-layer intermediates (attention probs, FFN hidden) in the
    # backward at the cost of one recompute — the HBM/FLOPs trade
    # (SURVEY §7 "jax.checkpoint / rematerialisation").
    gradient_checkpointing: bool = False
    mini_batch: bool = True
    max_num_line_search_iterations: int = 5
    training_workspace_mode: WorkspaceMode = WorkspaceMode.ENABLED
    inference_workspace_mode: WorkspaceMode = WorkspaceMode.ENABLED

    def layer_name(self, i: int) -> str:
        n = self.layers[i].name
        return n if n else f"layer_{i}"


class ListBuilder:
    def __init__(self, parent: "NeuralNetConfigurationBuilder") -> None:
        self._parent = parent
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None
        self._backprop_type = BackpropType.STANDARD
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def layer(self, layer: Layer, index: Optional[int] = None) -> "ListBuilder":
        if index is not None and index != len(self._layers):
            raise ValueError("layers must be added in order")
        self._layers.append(layer)
        return self

    def set_input_type(self, input_type: InputType) -> "ListBuilder":
        self._input_type = input_type
        return self

    # reference spelling
    def setInputType(self, input_type: InputType) -> "ListBuilder":
        return self.set_input_type(input_type)

    def backprop_type(self, t: BackpropType) -> "ListBuilder":
        self._backprop_type = t
        return self

    def tbptt_fwd_length(self, n: int) -> "ListBuilder":
        self._tbptt_fwd = n
        return self

    def tbptt_back_length(self, n: int) -> "ListBuilder":
        self._tbptt_back = n
        return self

    def build(self) -> MultiLayerConfiguration:
        p = self._parent
        layers = [p._apply_global_defaults(l) for l in self._layers]

        if self._input_type is not None:
            resolved: List[Layer] = []
            current = self._input_type
            timesteps = current.timesteps if isinstance(current, RecurrentType) else None
            for layer in layers:
                need = _needs(layer)
                pre = _preprocessor_for(current, need)
                if pre is not None:
                    resolved.append(pre)
                    current = pre.output_type(current)
                if isinstance(current, ConvolutionalFlatType) and need in ("ff", "any"):
                    current = FeedForwardType(size=current.flat_size())
                if need == "rnn" and isinstance(current, FeedForwardType):
                    if isinstance(layer, (RnnOutputLayer, RnnLossLayer)) and timesteps is not None:
                        pre2 = FeedForwardToRnnPreProcessor(timesteps=timesteps)
                        resolved.append(pre2)
                        current = pre2.output_type(current)
                layer = layer.with_input(current)
                resolved.append(layer)
                current = layer.output_type(current)
                if isinstance(current, RecurrentType) and current.timesteps is not None:
                    timesteps = current.timesteps
            layers = resolved

        return MultiLayerConfiguration(
            layers=tuple(layers),
            input_type=self._input_type,
            seed=p._seed,
            dtype=p._dtype,
            compute_dtype=p._compute_dtype,
            updater=p._updater,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            gradient_normalization=p._grad_norm,
            gradient_normalization_threshold=p._grad_norm_threshold,
            gradient_checkpointing=p._grad_ckpt,
            mini_batch=p._mini_batch,
            training_workspace_mode=p._train_ws,
            inference_workspace_mode=p._infer_ws,
        )


class NeuralNetConfigurationBuilder:
    """Reference: NeuralNetConfiguration.Builder. Fluent global defaults."""

    def __init__(self) -> None:
        self._seed = 0
        self._dtype = "float32"
        self._compute_dtype: Optional[str] = None
        self._grad_ckpt: bool = False
        self._activation: Optional[Activation] = None
        self._weight_init: Optional[WeightInit] = None
        self._dist: Optional[Distribution] = None
        self._updater = None
        self._bias_updater = None
        self._l1: Optional[float] = None
        self._l2: Optional[float] = None
        self._l1_bias: Optional[float] = None
        self._l2_bias: Optional[float] = None
        self._weight_decay: Optional[float] = None
        self._dropout: Optional[float] = None
        self._grad_norm = GradientNormalization.NONE
        self._grad_norm_threshold = 1.0
        self._mini_batch = True
        self._train_ws = WorkspaceMode.ENABLED
        self._infer_ws = WorkspaceMode.ENABLED

    def seed(self, s: int) -> "NeuralNetConfigurationBuilder":
        self._seed = int(s)
        return self

    def data_type(self, dtype: str) -> "NeuralNetConfigurationBuilder":
        self._dtype = dtype
        return self

    def compute_dtype(self, dtype: Optional[str]) -> "NeuralNetConfigurationBuilder":
        """Mixed-precision compute dtype (e.g. "bfloat16"); params stay in
        ``data_type``. See MultiLayerConfiguration.compute_dtype."""
        self._compute_dtype = dtype
        return self

    def gradient_checkpointing(self, enabled: bool = True) -> "NeuralNetConfigurationBuilder":
        """Remat each layer in the backward pass (activation-memory saver)."""
        self._grad_ckpt = bool(enabled)
        return self

    def activation(self, a) -> "NeuralNetConfigurationBuilder":
        self._activation = Activation.from_any(a)
        return self

    def weight_init(self, w, dist: Optional[Distribution] = None) -> "NeuralNetConfigurationBuilder":
        self._weight_init = WeightInit.from_any(w)
        self._dist = dist
        return self

    def dist(self, d: Distribution) -> "NeuralNetConfigurationBuilder":
        self._dist = d
        self._weight_init = WeightInit.DISTRIBUTION
        return self

    def updater(self, u) -> "NeuralNetConfigurationBuilder":
        self._updater = u
        return self

    def l1(self, v: float) -> "NeuralNetConfigurationBuilder":
        self._l1 = v
        return self

    def l2(self, v: float) -> "NeuralNetConfigurationBuilder":
        self._l2 = v
        return self

    def l1_bias(self, v: float) -> "NeuralNetConfigurationBuilder":
        self._l1_bias = v
        return self

    def l2_bias(self, v: float) -> "NeuralNetConfigurationBuilder":
        self._l2_bias = v
        return self

    def weight_decay(self, v: float) -> "NeuralNetConfigurationBuilder":
        self._weight_decay = v
        return self

    def dropout(self, retain_prob: float) -> "NeuralNetConfigurationBuilder":
        self._dropout = retain_prob
        return self

    def gradient_normalization(self, g: GradientNormalization) -> "NeuralNetConfigurationBuilder":
        self._grad_norm = g
        return self

    def gradient_normalization_threshold(self, t: float) -> "NeuralNetConfigurationBuilder":
        self._grad_norm_threshold = t
        return self

    def mini_batch(self, b: bool) -> "NeuralNetConfigurationBuilder":
        self._mini_batch = b
        return self

    def training_workspace_mode(self, m: WorkspaceMode) -> "NeuralNetConfigurationBuilder":
        self._train_ws = m
        return self

    def inference_workspace_mode(self, m: WorkspaceMode) -> "NeuralNetConfigurationBuilder":
        self._infer_ws = m
        return self

    def list(self) -> ListBuilder:
        return ListBuilder(self)

    def graph_builder(self):
        from .graph_conf import GraphBuilder

        return GraphBuilder(self)

    def _apply_global_defaults(self, layer: Layer) -> Layer:
        """Fold builder-level defaults into layers that did not override them
        (reference: layer confs inherit from NeuralNetConfiguration globals).
        Wrapper layers (Bidirectional etc.) get defaults pushed into their
        underlying layer too."""
        updates = {}
        if layer.activation is None and self._activation is not None:
            updates["activation"] = self._activation
        if layer.weight_init is None and self._weight_init is not None:
            updates["weight_init"] = self._weight_init
            if self._dist is not None:
                updates["weight_init_distribution"] = self._dist
        if layer.l1 is None and self._l1 is not None:
            updates["l1"] = self._l1
        if layer.l2 is None and self._l2 is not None:
            updates["l2"] = self._l2
        if layer.l1_bias is None and self._l1_bias is not None:
            updates["l1_bias"] = self._l1_bias
        if layer.l2_bias is None and self._l2_bias is not None:
            updates["l2_bias"] = self._l2_bias
        if layer.weight_decay is None and self._weight_decay is not None:
            updates["weight_decay"] = self._weight_decay
        if layer.dropout is None and self._dropout is not None and not isinstance(layer, BatchNormalizationLayer):
            updates["dropout"] = self._dropout
        if layer.updater is None and self._updater is not None:
            updates["updater"] = self._updater
        for wrapper_field in ("fwd", "underlying"):
            inner = getattr(layer, wrapper_field, None)
            if isinstance(inner, Layer):
                updates[wrapper_field] = self._apply_global_defaults(inner)
        if not updates:
            return layer
        return dataclasses.replace(layer, **updates)


class NeuralNetConfiguration:
    """Entry point matching the reference spelling:
    ``NeuralNetConfiguration.builder()`` (Java: ``new NeuralNetConfiguration.Builder()``)."""

    Builder = NeuralNetConfigurationBuilder

    @staticmethod
    def builder() -> NeuralNetConfigurationBuilder:
        return NeuralNetConfigurationBuilder()
