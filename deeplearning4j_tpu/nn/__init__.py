from . import layers
from .activations import Activation
from .conf import (
    BackpropType,
    GradientNormalization,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    WorkspaceMode,
)
from .input_type import InputType
from .losses import LossFunction
from .sequential import MultiLayerNetwork, Sequential
from .weights import Distribution, WeightInit

__all__ = [
    "Activation",
    "BackpropType",
    "Distribution",
    "GradientNormalization",
    "InputType",
    "LossFunction",
    "MultiLayerConfiguration",
    "MultiLayerNetwork",
    "NeuralNetConfiguration",
    "Sequential",
    "WeightInit",
    "WorkspaceMode",
    "layers",
]
