"""ComputationGraph — the DAG model.

Reference: org.deeplearning4j.nn.graph.ComputationGraph (~5k LoC, SURVEY.md
§2.2/§3.2 — the ResNet-50 path). Topologically-ordered forward over vertices,
multi-input/multi-output, per-output loss weighting. Backward is jax autodiff
over the whole graph; the reference's reverse-topo epsilon accumulation has no
hand-written equivalent here.

The training step is one jitted donated XLA program, same design as the
Sequential solver (SURVEY.md §7).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtypes import as_input
from ..core.listeners import ListenerBus, TrainingListener
from ..core.rng import RngState
from .graph_conf import ComputationGraphConfiguration, VertexSpec
from .layers.base import Layer, LayerContext, apply_layer as _apply_layer
from .layers.output import BaseOutputLayer
from .sequential import _layer_reg_score


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration) -> None:
        self.conf = conf
        self.params: Dict[str, Dict[str, jax.Array]] = {}
        self.state: Dict[str, Dict[str, jax.Array]] = {}
        self._persistent_keys: Dict[str, Tuple[str, ...]] = {}
        self.listeners = ListenerBus()
        self.iteration_count = 0
        self.epoch_count = 0
        self.last_batch_size = 0
        self.score_value = float("nan")
        self._rng = RngState(conf.seed)
        self._solver = None
        self._output_fn_cache: Dict[Any, Any] = {}
        self._initialized = False
        # loss weights per output (reference: setOutputs + loss weighting)
        self.output_weights: Dict[str, float] = {n: 1.0 for n in conf.network_outputs}

    @property
    def dtype(self):
        return jnp.dtype(self.conf.dtype)

    def keeps_int_input(self, input_name: str) -> bool:
        """True when ``input_name`` feeds an index-consuming layer
        (embedding lookup) — its integer dtype is then preserved through
        every cast boundary (see core.dtypes.as_input)."""
        for spec in self.conf.vertices:
            if input_name in spec.inputs and spec.layer is not None \
                    and getattr(spec.layer, "consumes_indices", False):
                return True
        return False

    def _as_inputs(self, xs) -> tuple:
        names = self.conf.network_inputs
        return tuple(
            as_input(x, self.dtype,
                     self.keeps_int_input(names[i]) if i < len(names) else False)
            for i, x in enumerate(xs)
        )

    def _to_compute(self, params, inputs):
        """Mixed-precision boundary (see MultiLayerNetwork._to_compute)."""
        cd = getattr(self.conf, "compute_dtype", None)
        if not cd or jnp.dtype(cd) == self.dtype:
            return params, inputs
        from ..core.dtypes import cast_floats

        return cast_floats(params, cd), [cast_floats(x, cd) for x in inputs]

    # Solver compatibility surface ------------------------------------------
    def named_param_layers(self) -> List[Tuple[str, Layer]]:
        return [
            (s.name, s.layer) for s in self.conf.vertices
            if s.layer is not None and s.layer.has_params()
        ]

    def linear_chain(self) -> List[VertexSpec]:
        """The vertex sequence when this graph is one input→output layer
        chain (each vertex a layer consuming exactly the previous vertex's
        output) — the shape pipeline-stage partitioning requires. Raises
        ``ValueError`` for branching/merging topologies or op vertices."""
        conf = self.conf
        if len(conf.network_inputs) != 1 or len(conf.network_outputs) != 1:
            raise ValueError(
                "pipeline partitioning needs exactly one graph input and "
                f"one output, got {len(conf.network_inputs)}/"
                f"{len(conf.network_outputs)}")
        prev = conf.network_inputs[0]
        chain: List[VertexSpec] = []
        for spec in conf.vertices:
            if spec.layer is None:
                raise ValueError(
                    f"vertex {spec.name!r} is an op vertex — pipeline "
                    "partitioning needs a pure layer chain")
            if tuple(spec.inputs) != (prev,):
                raise ValueError(
                    f"vertex {spec.name!r} consumes {spec.inputs}, not the "
                    f"previous vertex {prev!r} — not a linear chain")
            chain.append(spec)
            prev = spec.name
        if prev != conf.network_outputs[0]:
            raise ValueError(
                f"the chain ends at {prev!r}, not the network output "
                f"{conf.network_outputs[0]!r}")
        return chain

    # ------------------------------------------------------------------ init
    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        rng = RngState(self.conf.seed if seed is None else seed)
        dtype = self.dtype
        self.params, self.state, self._persistent_keys = {}, {}, {}
        for spec in self.conf.vertices:
            if spec.layer is None:
                continue
            name = spec.name
            self.params[name] = (
                spec.layer.init(rng.next_key(), dtype) if spec.layer.has_params() else {}
            )
            st = spec.layer.init_state(dtype)
            self.state[name] = st
            self._persistent_keys[name] = tuple(st.keys())
        self._initialized = True
        self._output_fn_cache.clear()
        self._solver = None
        return self

    def _check_init(self) -> None:
        if not self._initialized:
            self.init()

    def migrate_state(self) -> None:
        """Fill persistent-state keys added by newer framework versions with
        their ``init_state`` defaults, keeping existing values (see
        MultiLayerNetwork.migrate_state — e.g. PR 3's MoE
        ``expert_tokens``/``dropped_tokens`` keys)."""
        if not self._initialized:
            return
        changed = False
        for spec in self.conf.vertices:
            if spec.layer is None:
                continue
            defaults = spec.layer.init_state(self.dtype)
            if not defaults:
                continue
            cur = dict(self.state.get(spec.name, {}))
            missing = [k for k in defaults if k not in cur]
            if missing:
                for k in missing:
                    cur[k] = defaults[k]
                self.state[spec.name] = cur
                self._persistent_keys[spec.name] = tuple(cur.keys())
                changed = True
        if changed:
            self._output_fn_cache.clear()

    # -------------------------------------------------------------- forward
    def forward_pure(
        self,
        params,
        state,
        inputs: Sequence[jax.Array],
        *,
        train: bool,
        rng: Optional[jax.Array],
        masks: Optional[Sequence[Optional[jax.Array]]] = None,
        stop_at_outputs: bool = True,
        dist=None,
    ):
        """Topo-order forward. Returns ({vertex: activation}, new_state)."""
        params, inputs = self._to_compute(params, inputs)
        acts: Dict[str, jax.Array] = dict(zip(self.conf.network_inputs, inputs))
        vmasks: Dict[str, Optional[jax.Array]] = {}
        if masks is not None:
            vmasks.update(zip(self.conf.network_inputs, masks))
        new_state: Dict[str, Dict[str, jax.Array]] = {}
        for vi, spec in enumerate(self.conf.vertices):
            xs = [acts[i] for i in spec.inputs]
            in_mask = vmasks.get(spec.inputs[0]) if spec.inputs else None
            if spec.layer is not None:
                x = xs[0]
                key = jax.random.fold_in(rng, vi) if rng is not None else None
                ctx = LayerContext(train=train, rng=key, mask=in_mask, dist=dist)
                if spec.preprocessor is not None:
                    x, _ = spec.preprocessor.apply({}, {}, x, ctx)
                lstate = dict(state.get(spec.name, {}))
                y, lstate_out = _apply_layer(
                    spec.layer, params.get(spec.name, {}), lstate, x, ctx,
                    remat=self.conf.gradient_checkpointing and train)
                persistent = self._persistent_keys.get(spec.name, ())
                new_state[spec.name] = {k: v for k, v in lstate_out.items() if k in persistent}
                vmasks[spec.name] = spec.layer.feed_forward_mask(in_mask, None) if in_mask is not None else None
            else:
                y = spec.vertex.apply(*xs)
                vmasks[spec.name] = in_mask
            acts[spec.name] = y
        return acts, new_state

    def loss_pure(
        self,
        params,
        state,
        inputs: Sequence[jax.Array],
        labels: Sequence[jax.Array],
        *,
        rng: Optional[jax.Array],
        masks=None,
        label_masks: Optional[Sequence[Optional[jax.Array]]] = None,
        train: bool = True,
        dist=None,
    ):
        """Weighted sum of output-layer losses + regularization."""
        # regularization runs on master (uncast) params; forward math in
        # compute_dtype
        master_params = params
        params, inputs = self._to_compute(params, inputs)
        acts_needed: Dict[str, jax.Array] = {}
        # run the full graph once; output layers need their INPUT activations,
        # so run forward but for output layer vertices compute loss instead.
        acts: Dict[str, jax.Array] = dict(zip(self.conf.network_inputs, inputs))
        vmasks: Dict[str, Optional[jax.Array]] = {}
        if masks is not None:
            vmasks.update(zip(self.conf.network_inputs, masks))
        new_state: Dict[str, Dict[str, jax.Array]] = {}
        losses: Dict[str, jax.Array] = {}
        label_by_output = dict(zip(self.conf.network_outputs, labels))
        lmask_by_output: Dict[str, Optional[jax.Array]] = {}
        if label_masks is not None:
            lmask_by_output.update(zip(self.conf.network_outputs, label_masks))

        for vi, spec in enumerate(self.conf.vertices):
            xs = [acts[i] for i in spec.inputs]
            in_mask = vmasks.get(spec.inputs[0]) if spec.inputs else None
            if spec.layer is not None:
                x = xs[0]
                key = jax.random.fold_in(rng, vi) if rng is not None else None
                ctx = LayerContext(train=train, rng=key, mask=in_mask, dist=dist)
                if spec.preprocessor is not None:
                    x, _ = spec.preprocessor.apply({}, {}, x, ctx)
                lstate = dict(state.get(spec.name, {}))
                is_loss_output = (
                    isinstance(spec.layer, BaseOutputLayer)
                    and spec.name in label_by_output
                )
                if is_loss_output:
                    losses[spec.name] = spec.layer.compute_loss(
                        params.get(spec.name, {}), x, label_by_output[spec.name],
                        ctx, label_mask=lmask_by_output.get(spec.name),
                    )
                y, lstate_out = _apply_layer(
                    spec.layer, params.get(spec.name, {}), lstate, x, ctx,
                    remat=self.conf.gradient_checkpointing and train)
                persistent = self._persistent_keys.get(spec.name, ())
                new_state[spec.name] = {k: v for k, v in lstate_out.items() if k in persistent}
                vmasks[spec.name] = None if in_mask is None else spec.layer.feed_forward_mask(in_mask, None)
            else:
                y = spec.vertex.apply(*xs)
                vmasks[spec.name] = in_mask
            acts[spec.name] = y

        score_dtype = jnp.promote_types(self.dtype, jnp.float32)
        total = jnp.asarray(0.0, score_dtype)
        for name, l in losses.items():
            total = total + self.output_weights.get(name, 1.0) * l.astype(score_dtype)
        for name, layer in self.named_param_layers():
            if master_params.get(name):
                total = total + _layer_reg_score(layer, master_params[name], score_dtype)
            # MoE load-balance aux loss (GShard), same contract as the
            # sequential path: forward stashed this batch's aux in state
            bl_w = getattr(layer, "balance_loss_weight", 0.0)
            if bl_w:
                aux = new_state.get(name, {}).get("aux_load_balance")
                if aux is not None:
                    total = total + bl_w * aux.astype(score_dtype)
        return total, new_state

    # -------------------------------------------------------------- user API
    @staticmethod
    def _as_tuple(x) -> Tuple:
        if isinstance(x, (list, tuple)):
            return tuple(x)
        return (x,)

    def output(self, *inputs, masks=None):
        """Inference; returns one array or a tuple matching network_outputs."""
        self._check_init()
        xs = self._as_inputs(inputs)
        key = ("output", masks is not None)
        if key not in self._output_fn_cache:
            def fn(params, state, xs, masks):
                acts, _ = self.forward_pure(params, state, xs, train=False, rng=None, masks=masks)
                # user-facing outputs in the model dtype even under a bf16
                # compute_dtype (mixed precision is an internal property)
                return tuple(acts[n].astype(self.dtype) for n in self.conf.network_outputs)

            self._output_fn_cache[key] = jax.jit(fn)
        outs = self._output_fn_cache[key](self.params, self.state, xs, masks)
        return outs[0] if len(outs) == 1 else outs

    def score(self, features, labels, masks=None, label_masks=None) -> float:
        self._check_init()
        xs = self._as_inputs(self._as_tuple(features))
        ys = tuple(jnp.asarray(y) for y in self._as_tuple(labels))
        s, _ = self.loss_pure(self.params, self.state, xs, ys, rng=None,
                              masks=masks, label_masks=label_masks, train=False)
        return float(s)

    def calculate_gradients(self, features, labels, mask=None, label_mask=None):
        self._check_init()
        xs = self._as_inputs(self._as_tuple(features))
        ys = tuple(jnp.asarray(y) for y in self._as_tuple(labels))
        masks = None if mask is None else self._as_tuple(mask)
        lmasks = None if label_mask is None else self._as_tuple(label_mask)

        def loss_of(p):
            s, _ = self.loss_pure(p, self.state, xs, ys, rng=None,
                                  masks=masks, label_masks=lmasks, train=True)
            return s

        return jax.grad(loss_of)(self.params)

    # ------------------------------------------------------------------ fit
    def add_listeners(self, *listeners: TrainingListener) -> None:
        for l in listeners:
            self.listeners.add(l)

    def fit(self, data, labels=None, *, epochs: int = 1) -> "ComputationGraph":
        self._check_init()
        from ..train.graph_solver import GraphSolver

        if self._solver is None:
            self._solver = GraphSolver(self)
        self._solver.fit(data, labels, epochs=epochs)
        return self

    # alias used by serializer
    @property
    def _trainer(self):
        return self._solver

    @_trainer.setter
    def _trainer(self, v) -> None:
        self._solver = v

    def evaluate(self, iterator_or_features, labels=None):
        from ..train.evaluation import Evaluation
        from .sequential import _as_batches

        ev = Evaluation()
        for feats, labs, msk, lmsk in _as_batches(iterator_or_features, labels, None):
            out = self.output(*self._as_tuple(feats))
            first = out[0] if isinstance(out, tuple) else out
            first_lab = self._as_tuple(labs)[0]
            ev.eval(np.asarray(first_lab), np.asarray(first))
        return ev

    def num_params(self) -> int:
        return int(sum(l.size for l in jax.tree_util.tree_leaves(self.params)))

    def summary(self) -> str:
        lines = [f"{'name':<28}{'type':<28}{'inputs':<30}{'params':>10}"]
        total = 0
        for spec in self.conf.vertices:
            kind = type(spec.layer or spec.vertex).__name__
            n = sum(int(a.size) for a in self.params.get(spec.name, {}).values())
            total += n
            lines.append(f"{spec.name:<28}{kind:<28}{','.join(spec.inputs):<30}{n:>10}")
        lines.append(f"Total params: {total}")
        return "\n".join(lines)

    def clone(self) -> "ComputationGraph":
        m = ComputationGraph(self.conf)
        if self._initialized:
            m.params = jax.tree_util.tree_map(lambda a: a, self.params)
            m.state = jax.tree_util.tree_map(lambda a: a, self.state)
            m._persistent_keys = dict(self._persistent_keys)
            m._initialized = True
        return m
