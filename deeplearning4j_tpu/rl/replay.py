"""Experience replay buffer.

Reference: org.deeplearning4j.rl4j.learning.sync.ExpReplay — bounded FIFO
of transitions with uniform random minibatch sampling.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np


@dataclasses.dataclass
class Transition:
    observation: np.ndarray
    action: int
    reward: float
    next_observation: np.ndarray
    done: bool


class ExpReplay:
    def __init__(self, max_size: int = 10000, batch_size: int = 32,
                 seed: int = 0) -> None:
        self.max_size = int(max_size)
        self.batch_size = int(batch_size)
        self.rng = np.random.RandomState(seed)
        self._buf: List[Transition] = []
        self._pos = 0

    def store(self, t: Transition) -> None:
        if len(self._buf) < self.max_size:
            self._buf.append(t)
        else:  # ring overwrite
            self._buf[self._pos] = t
            self._pos = (self._pos + 1) % self.max_size

    def __len__(self) -> int:
        return len(self._buf)

    def sample(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                              np.ndarray, np.ndarray]:
        """Uniform minibatch as stacked arrays (obs, action, reward,
        next_obs, done)."""
        n = min(self.batch_size, len(self._buf))
        idx = self.rng.randint(0, len(self._buf), n)
        ts = [self._buf[i] for i in idx]
        return (
            np.stack([t.observation for t in ts]).astype(np.float32),
            np.asarray([t.action for t in ts], np.int32),
            np.asarray([t.reward for t in ts], np.float32),
            np.stack([t.next_observation for t in ts]).astype(np.float32),
            np.asarray([t.done for t in ts], np.float32),
        )
