"""MDP environment interface + local CartPole.

Reference: org.deeplearning4j.rl4j.mdp.MDP (gym-style contract) and the
bundled toy environments (rl4j used gym/malmo bindings; with zero egress
the classic CartPole dynamics are implemented locally — same physics
constants as gym's CartPole-v1).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class StepReply:
    observation: np.ndarray
    reward: float
    done: bool
    info: Any = None


class MDP:
    """Environment SPI (reference: MDP<OBSERVATION, ACTION, ACTION_SPACE>)."""

    observation_size: int
    action_size: int

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> StepReply:
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:  # reference API
        pass


class CartPole(MDP):
    """Classic cart-pole balancing (gym CartPole-v1 physics)."""

    observation_size = 4
    action_size = 2

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LENGTH = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * math.pi / 180
    X_LIMIT = 2.4

    def __init__(self, max_steps: int = 500, seed: int = 0) -> None:
        self.max_steps = max_steps
        self.rng = np.random.RandomState(seed)
        self.state: Optional[np.ndarray] = None
        self.steps = 0
        self._done = True

    def reset(self) -> np.ndarray:
        self.state = self.rng.uniform(-0.05, 0.05, 4)
        self.steps = 0
        self._done = False
        return self.state.astype(np.float32)

    def step(self, action: int) -> StepReply:
        assert self.state is not None and not self._done, "call reset() first"
        x, x_dot, theta, theta_dot = self.state
        force = self.FORCE if action == 1 else -self.FORCE
        total_mass = self.CART_MASS + self.POLE_MASS
        pm_l = self.POLE_MASS * self.POLE_HALF_LENGTH
        cos_t, sin_t = math.cos(theta), math.sin(theta)
        temp = (force + pm_l * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LENGTH
            * (4.0 / 3.0 - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pm_l * theta_acc * cos_t / total_mass
        self.state = np.array([
            x + self.DT * x_dot,
            x_dot + self.DT * x_acc,
            theta + self.DT * theta_dot,
            theta_dot + self.DT * theta_acc,
        ])
        self.steps += 1
        out_of_bounds = (abs(self.state[0]) > self.X_LIMIT
                         or abs(self.state[2]) > self.THETA_LIMIT)
        self._done = out_of_bounds or self.steps >= self.max_steps
        return StepReply(self.state.astype(np.float32), 1.0, self._done)

    def is_done(self) -> bool:
        return self._done
