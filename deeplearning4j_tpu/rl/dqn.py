"""DQN / double-DQN over dense observations.

Reference: org.deeplearning4j.rl4j.learning.sync.qlearning.discrete.
QLearningDiscreteDense + QLearningConfiguration: epsilon-greedy rollout,
experience replay, TD targets from a periodically-synced target network,
double-DQN action selection.

The Q-network is a MultiLayerNetwork (config DSL); the TD update is one
jitted step (network forward x2 + masked MSE on the taken actions),
mirroring how the reference drives a DL4J model from its learning loop.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Callable, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..nn import NeuralNetConfiguration
from ..nn.layers import DenseLayer, OutputLayer
from ..nn.losses import LossFunction
from ..nn.sequential import MultiLayerNetwork
from ..train.updaters import Adam
from .mdp import MDP
from .policy import EpsGreedyPolicy
from .replay import ExpReplay, Transition


@dataclasses.dataclass
class QLearningConfiguration:
    """Reference: QLearningConfiguration (builder fields kept)."""

    seed: int = 123
    max_step: int = 10000
    max_epoch_step: int = 500
    exp_replay_size: int = 10000
    batch_size: int = 32
    target_dqn_update_freq: int = 100
    update_start: int = 100
    gamma: float = 0.99
    eps_start: float = 1.0
    min_epsilon: float = 0.05
    epsilon_nb_step: int = 3000
    double_dqn: bool = True
    learning_rate: float = 1e-3
    hidden: tuple = (64, 64)


class QLearningDiscreteDense:
    def __init__(self, mdp: MDP, conf: Optional[QLearningConfiguration] = None,
                 network: Optional[MultiLayerNetwork] = None) -> None:
        self.mdp = mdp
        self.conf = conf or QLearningConfiguration()
        c = self.conf
        self.network = network or self._default_network()
        self.target_params = copy.deepcopy(self.network.params)
        self.replay = ExpReplay(c.exp_replay_size, c.batch_size, seed=c.seed)
        self.policy = EpsGreedyPolicy(
            self._q_values, mdp.action_size, eps_start=c.eps_start,
            eps_min=c.min_epsilon, decay_steps=c.epsilon_nb_step, seed=c.seed)
        self.episode_rewards: List[float] = []
        self._steps = 0
        self._q_jit = None
        self._td_jit = None

    def _default_network(self) -> MultiLayerNetwork:
        c = self.conf
        b = (NeuralNetConfiguration.builder().seed(c.seed)
             .updater(Adam(learning_rate=c.learning_rate)).list())
        from ..nn.activations import Activation

        n_in = self.mdp.observation_size
        for h in c.hidden:
            b.layer(DenseLayer(n_in=n_in, n_out=h,
                               activation=Activation.RELU))
            n_in = h
        # IDENTITY head: Q-values are unbounded regression targets (the
        # OutputLayer default is the classifier's SOFTMAX)
        b.layer(OutputLayer(n_in=n_in, n_out=self.mdp.action_size,
                            loss=LossFunction.MSE,
                            activation=Activation.IDENTITY))
        return MultiLayerNetwork(b.build()).init()

    # --- device-side pieces -------------------------------------------

    def _q_values(self, obs: np.ndarray) -> np.ndarray:
        if self._q_jit is None:
            model = self.network

            def q(params, state, x):
                out, _, _ = model.forward_pure(params, state, x, train=False,
                                               rng=None)
                return out

            self._q_jit = jax.jit(q)
        return np.asarray(self._q_jit(self.network.params,
                                      self.network.state,
                                      jnp.asarray(obs, jnp.float32)))

    def _td_targets(self, obs, actions, rewards, next_obs, dones
                    ) -> np.ndarray:
        """Q-matrix with the taken actions' entries replaced by TD targets —
        feeding the standard fit(x, y) MSE step (the reference does the
        same through its DQN output layer)."""
        c = self.conf
        if self._td_jit is None:
            model = self.network

            def td(params, target_params, state, obs, actions, rewards,
                   next_obs, dones):
                q_now, _, _ = model.forward_pure(params, state, obs,
                                                 train=False, rng=None)
                q_next_t, _, _ = model.forward_pure(target_params, state,
                                                    next_obs, train=False,
                                                    rng=None)
                if c.double_dqn:
                    q_next_live, _, _ = model.forward_pure(
                        params, state, next_obs, train=False, rng=None)
                    next_a = jnp.argmax(q_next_live, axis=1)
                else:
                    next_a = jnp.argmax(q_next_t, axis=1)
                next_q = jnp.take_along_axis(
                    q_next_t, next_a[:, None], axis=1)[:, 0]
                targets = rewards + c.gamma * next_q * (1.0 - dones)
                return q_now.at[jnp.arange(obs.shape[0]), actions].set(
                    targets)

            self._td_jit = jax.jit(td)
        return np.asarray(self._td_jit(
            self.network.params, self.target_params, self.network.state,
            jnp.asarray(obs), jnp.asarray(actions), jnp.asarray(rewards),
            jnp.asarray(next_obs), jnp.asarray(dones)))

    # --- learning loop ------------------------------------------------

    def train_step(self) -> None:
        obs, actions, rewards, next_obs, dones = self.replay.sample()
        y = self._td_targets(obs, actions, rewards, next_obs, dones)
        self.network.fit(obs, y)

    def train(self, on_episode_end: Optional[Callable[[int, float], None]]
              = None) -> List[float]:
        """Run the full learning loop (reference: QLearning.train())."""
        c = self.conf
        while self._steps < c.max_step:
            obs = self.mdp.reset()
            ep_reward = 0.0
            for _ in range(c.max_epoch_step):
                action = self.policy.next_action(obs)
                reply = self.mdp.step(action)
                self.replay.store(Transition(
                    obs, action, reply.reward, reply.observation,
                    reply.done))
                obs = reply.observation
                ep_reward += reply.reward
                self._steps += 1
                if self._steps >= c.update_start and len(self.replay) >= \
                        self.replay.batch_size:
                    self.train_step()
                if self._steps % c.target_dqn_update_freq == 0:
                    self.target_params = copy.deepcopy(self.network.params)
                if reply.done or self._steps >= c.max_step:
                    break
            self.episode_rewards.append(ep_reward)
            if on_episode_end:
                on_episode_end(len(self.episode_rewards), ep_reward)
        return self.episode_rewards

    def get_policy(self):
        """Greedy policy over the trained network (reference:
        getPolicy())."""
        from .policy import GreedyPolicy

        return GreedyPolicy(self._q_values)
