"""Reinforcement learning tier.

Reference: rl4j (SURVEY.md §2.2 "RL4J"): MDP environment interface,
experience replay, DQN/double-DQN with target network, epsilon-greedy
policies. The jitted Q-update batches TD targets onto the device; the
environment loop stays host-side (tiny, sequential by nature).
"""

from .mdp import MDP, CartPole, StepReply
from .replay import ExpReplay, Transition
from .policy import EpsGreedyPolicy, GreedyPolicy
from .a3c import A3CConfiguration, A3CDiscreteDense
from .dqn import QLearningConfiguration, QLearningDiscreteDense

__all__ = [
    "A3CConfiguration",
    "A3CDiscreteDense",
    "CartPole",
    "EpsGreedyPolicy",
    "ExpReplay",
    "GreedyPolicy",
    "MDP",
    "QLearningConfiguration",
    "QLearningDiscreteDense",
    "StepReply",
    "Transition",
]
