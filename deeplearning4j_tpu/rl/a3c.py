"""A3C — advantage actor-critic (reference: rl4j A3CDiscrete/
A3CDiscreteDense + AsyncNStepQLearning's worker machinery, SURVEY.md §2.2
"RL4J").

TPU design note (same stance as the hogwild Word2Vec and
ThresholdCompressedSync divergence docs): the reference's "async" is N CPU
worker threads with local nets racing updates into a global param store —
a scheme built for many weak cores. On one strong accelerator the
equivalent work batches: N environment "workers" step in lockstep, their
observations stack into one policy/value forward, and the n-step
advantage-actor-critic update is ONE jitted program (policy gradient +
entropy bonus + value MSE). Objective and hyperparameter vocabulary follow
the reference; the execution schedule is synchronous (A2C) by design.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax

from .mdp import MDP


@dataclasses.dataclass
class A3CConfiguration:
    """Reference vocabulary: A3CConfiguration(seed, maxEpochStep, maxStep,
    numThread, nstep, gamma, ...)."""

    seed: int = 123
    max_epoch_step: int = 500
    max_step: int = 20000
    num_threads: int = 8          # reference numThread -> batched workers
    n_step: int = 16
    gamma: float = 0.99
    learning_rate: float = 1e-3
    entropy_coef: float = 0.01
    value_coef: float = 0.5
    hidden: tuple = (64, 64)


class A3CDiscreteDense:
    """Dense-observation discrete-action A3C (reference:
    A3CDiscreteDense). ``train()`` runs batched synchronous workers;
    ``get_policy()`` returns the greedy softmax policy."""

    def __init__(self, mdp_factory: Callable[[], MDP],
                 conf: Optional[A3CConfiguration] = None) -> None:
        self.conf = conf or A3CConfiguration()
        c = self.conf
        self.envs: List[MDP] = [mdp_factory() for _ in range(c.num_threads)]
        probe = self.envs[0]
        self.obs_size = probe.observation_size
        self.n_actions = probe.action_size

        # shared trunk with policy + value heads, as one param pytree
        rng = np.random.RandomState(c.seed)
        key = jax.random.PRNGKey(c.seed)
        sizes = (self.obs_size,) + tuple(c.hidden)
        params = {}
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, k = jax.random.split(key)
            params[f"W{i}"] = (jax.random.normal(k, (a, b), jnp.float32)
                               * np.sqrt(2.0 / a))
            params[f"b{i}"] = jnp.zeros(b, jnp.float32)
        key, k1, k2 = jax.random.split(key, 3)
        h = sizes[-1]
        params["Wp"] = jax.random.normal(k1, (h, self.n_actions)) * 0.01
        params["bp"] = jnp.zeros(self.n_actions, jnp.float32)
        params["Wv"] = jax.random.normal(k2, (h, 1)) * 0.01
        params["bv"] = jnp.zeros(1, jnp.float32)
        self.params = params
        self.opt = optax.adam(c.learning_rate)
        self.opt_state = self.opt.init(params)
        self.episode_rewards: List[float] = []
        self._rng = rng
        self._fwd_jit = jax.jit(self._forward)
        self._update_jit = jax.jit(self._update)

    # --- the jitted pieces --------------------------------------------

    def _forward(self, params, obs):
        h = obs
        i = 0
        while f"W{i}" in params:
            h = jax.nn.relu(h @ params[f"W{i}"] + params[f"b{i}"])
            i += 1
        logits = h @ params["Wp"] + params["bp"]
        value = (h @ params["Wv"] + params["bv"])[:, 0]
        return logits, value

    def _update(self, params, opt_state, obs, actions, returns):
        c = self.conf

        def loss_fn(p):
            logits, value = self._forward(p, obs)
            logp = jax.nn.log_softmax(logits)
            probs = jnp.exp(logp)
            adv = returns - value
            # batch-normalized advantages: the synchronous batch replaces
            # the reference's per-thread updates, whose implicit staggering
            # kept early huge advantages from saturating the policy
            adv_n = jax.lax.stop_gradient(
                (adv - jnp.mean(adv)) / (jnp.std(adv) + 1e-8))
            chosen = jnp.take_along_axis(logp, actions[:, None], axis=1)[:, 0]
            policy_loss = -jnp.mean(chosen * adv_n)
            entropy = -jnp.mean(jnp.sum(probs * logp, axis=1))
            value_loss = jnp.mean(adv ** 2)
            return (policy_loss + c.value_coef * value_loss
                    - c.entropy_coef * entropy)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # --- environment interaction --------------------------------------

    def _act(self, obs_batch: np.ndarray) -> np.ndarray:
        logits, _ = self._fwd_jit(self.params, jnp.asarray(obs_batch))
        probs = np.asarray(jax.nn.softmax(logits))
        return np.asarray([
            self._rng.choice(self.n_actions, p=probs[i] / probs[i].sum())
            for i in range(len(probs))
        ])

    def train(self, on_episode_end: Optional[Callable[[int, float], None]]
              = None) -> "A3CDiscreteDense":
        c = self.conf
        obs = np.stack([e.reset() for e in self.envs]).astype(np.float32)
        ep_reward = np.zeros(len(self.envs))
        steps = 0
        episode = 0
        while steps < c.max_step:
            # n-step rollout across all workers, in lockstep
            roll_obs, roll_act, roll_rew, roll_done = [], [], [], []
            for _ in range(c.n_step):
                actions = self._act(obs)
                next_obs = np.empty_like(obs)
                rewards = np.zeros(len(self.envs), np.float32)
                dones = np.zeros(len(self.envs), np.float32)
                for i, env in enumerate(self.envs):
                    reply = env.step(int(actions[i]))
                    rewards[i] = reply.reward
                    ep_reward[i] += reply.reward
                    if reply.done:
                        dones[i] = 1.0
                        self.episode_rewards.append(float(ep_reward[i]))
                        if on_episode_end:
                            on_episode_end(episode, float(ep_reward[i]))
                        episode += 1
                        ep_reward[i] = 0.0
                        next_obs[i] = env.reset()
                    else:
                        next_obs[i] = reply.observation
                roll_obs.append(obs.copy())
                roll_act.append(actions)
                roll_rew.append(rewards)
                roll_done.append(dones)
                obs = next_obs.astype(np.float32)
                steps += len(self.envs)

            # n-step discounted returns bootstrapped from V(s_{t+n})
            _, boot = self._fwd_jit(self.params, jnp.asarray(obs))
            ret = np.asarray(boot, np.float32)
            returns = []
            for t in reversed(range(len(roll_rew))):
                ret = roll_rew[t] + c.gamma * ret * (1.0 - roll_done[t])
                returns.append(ret.copy())
            returns.reverse()

            flat_obs = np.concatenate(roll_obs)
            flat_act = np.concatenate(roll_act).astype(np.int32)
            flat_ret = np.concatenate(returns).astype(np.float32)
            self.params, self.opt_state, _ = self._update_jit(
                self.params, self.opt_state, jnp.asarray(flat_obs),
                jnp.asarray(flat_act), jnp.asarray(flat_ret))
        return self

    # --- reference API surface ----------------------------------------

    def get_policy(self):
        fwd = self._fwd_jit
        params = self.params

        class _Policy:
            def next_action(self, observation: np.ndarray) -> int:
                logits, _ = fwd(params, jnp.asarray(
                    observation[None], jnp.float32))
                return int(np.argmax(np.asarray(logits)[0]))

        return _Policy()
