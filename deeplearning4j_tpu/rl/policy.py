"""Action policies.

Reference: org.deeplearning4j.rl4j.policy.{Policy, EpsGreedy, DQNPolicy}.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


class GreedyPolicy:
    """argmax-Q policy (reference: DQNPolicy)."""

    def __init__(self, q_fn: Callable[[np.ndarray], np.ndarray]) -> None:
        self.q_fn = q_fn

    def next_action(self, observation: np.ndarray) -> int:
        q = np.asarray(self.q_fn(observation[None, :]))[0]
        return int(q.argmax())


class EpsGreedyPolicy(GreedyPolicy):
    """Annealed epsilon-greedy wrapper (reference: EpsGreedy): linear decay
    from ``eps_start`` to ``eps_min`` over ``decay_steps`` calls."""

    def __init__(self, q_fn, n_actions: int, *, eps_start: float = 1.0,
                 eps_min: float = 0.05, decay_steps: int = 1000,
                 seed: int = 0) -> None:
        super().__init__(q_fn)
        self.n_actions = int(n_actions)
        self.eps_start = float(eps_start)
        self.eps_min = float(eps_min)
        self.decay_steps = int(decay_steps)
        self.rng = np.random.RandomState(seed)
        self.steps = 0

    @property
    def epsilon(self) -> float:
        frac = min(1.0, self.steps / max(1, self.decay_steps))
        return self.eps_start + (self.eps_min - self.eps_start) * frac

    def next_action(self, observation: np.ndarray) -> int:
        eps = self.epsilon
        self.steps += 1
        if self.rng.rand() < eps:
            return int(self.rng.randint(self.n_actions))
        return super().next_action(observation)
