"""StatsListener + StatsStorage.

Reference: org.deeplearning4j.ui.model.stats.StatsListener streaming typed
payloads (score, param/gradient/update histograms and norms, update:param
ratios, runtime info) into a StatsStorage (in-memory or MapDB file) that
the dashboard reads (SURVEY.md §5.5). The update:param ratio is DL4J's
signature learning-rate debugging aid — kept intact.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.listeners import TrainingListener


def _tensor_stats(arr: np.ndarray, bins: int) -> Dict[str, Any]:
    flat = np.asarray(arr, np.float32).reshape(-1)
    # Divergence (NaN/Inf params or grads) is exactly what this dashboard
    # exists to diagnose — record it instead of letting np.histogram raise
    # from inside the listener and kill the run.
    finite = flat[np.isfinite(flat)]
    nonfinite = int(flat.size - finite.size)
    if finite.size == 0:
        finite = np.zeros(1, np.float32)
    counts, edges = np.histogram(finite, bins=bins)
    out = {
        "mean": float(finite.mean()),
        "std": float(finite.std()),
        "norm": float(np.linalg.norm(finite)),
        "mean_magnitude": float(np.abs(finite).mean()),
        "histogram": {"min": float(edges[0]), "max": float(edges[-1]),
                      "counts": counts.tolist()},
    }
    if nonfinite:
        out["nonfinite_count"] = nonfinite
    return out


class StatsStorage:
    """SPI: ordered stream of JSON-able stat records per session."""

    def put(self, record: Dict[str, Any]) -> None:
        raise NotImplementedError

    def records(self, session_id: Optional[str] = None) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def session_ids(self) -> List[str]:
        return sorted({r.get("session", "") for r in self.records()})

    def scores(self, session_id: Optional[str] = None) -> List[float]:
        return [r["score"] for r in self.records(session_id)
                if "score" in r]

    def update_ratios(self, param_name: str,
                      session_id: Optional[str] = None) -> List[float]:
        """The update:param-ratio trajectory for one parameter — the
        dashboard's headline chart."""
        out = []
        for r in self.records(session_id):
            ratio = r.get("update_ratios", {}).get(param_name)
            if ratio is not None:
                out.append(ratio)
        return out


class InMemoryStatsStorage(StatsStorage):
    def __init__(self) -> None:
        self._records: List[Dict[str, Any]] = []

    def put(self, record: Dict[str, Any]) -> None:
        self._records.append(record)

    def records(self, session_id=None):
        if session_id is None:
            return list(self._records)
        return [r for r in self._records if r.get("session") == session_id]


class FileStatsStorage(StatsStorage):
    """JSONL file storage (reference: FileStatsStorage over MapDB). One
    record per line; readable with pandas/jq while training runs."""

    def __init__(self, path: str) -> None:
        self.path = path
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        self._fh = None

    def put(self, record: Dict[str, Any]) -> None:
        # persistent handle + per-line flush: records flow every iteration;
        # an open/close syscall pair per step would stall the dispatch
        # pipeline the listeners docstring warns about
        if self._fh is None:
            self._fh = open(self.path, "a")
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def records(self, session_id=None):
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                r = json.loads(line)
                if session_id is None or r.get("session") == session_id:
                    out.append(r)
        return out


class StatsListener(TrainingListener):
    """Collects per-iteration stats into a StatsStorage.

    ``update_frequency`` controls how often the expensive pytree stats
    (histograms over params/grads/updates) materialize; score-only records
    flow every iteration.
    """

    requires_arrays = True

    def __init__(self, storage: StatsStorage, *, session_id: str = "default",
                 update_frequency: int = 10, histogram_bins: int = 20) -> None:
        self.storage = storage
        self.session_id = session_id
        self.update_frequency = max(1, update_frequency)
        self.histogram_bins = histogram_bins
        self._prev_params: Optional[Dict[str, np.ndarray]] = None
        self._last_grads: Optional[Dict[str, Any]] = None
        self._start = time.time()

    # flatten {layer: {param: arr}} → {"layer/param": arr}
    @staticmethod
    def _flatten(tree: Dict[str, Any]) -> Dict[str, np.ndarray]:
        flat: Dict[str, np.ndarray] = {}
        for lname, lparams in (tree or {}).items():
            if isinstance(lparams, dict):
                for pname, arr in lparams.items():
                    flat[f"{lname}/{pname}"] = np.asarray(arr)
            else:
                flat[str(lname)] = np.asarray(lparams)
        return flat

    def on_gradient_calculation(self, model: Any, gradients: Any) -> None:
        self._last_grads = gradients

    def iteration_done(self, model: Any, iteration: int, epoch: int,
                       score: float) -> None:
        record: Dict[str, Any] = {
            "session": self.session_id,
            "iteration": iteration,
            "epoch": epoch,
            "score": float(score),
            "wallclock_s": time.time() - self._start,
        }
        if iteration % self.update_frequency == 0:
            params = self._flatten(getattr(model, "params", {}))
            record["params"] = {k: _tensor_stats(v, self.histogram_bins)
                                for k, v in params.items()}
            if self._last_grads is not None:
                grads = self._flatten(self._last_grads)
                record["gradients"] = {
                    k: _tensor_stats(v, self.histogram_bins)
                    for k, v in grads.items()}
            if self._prev_params is not None:
                updates = {k: params[k] - self._prev_params[k]
                           for k in params if k in self._prev_params
                           and params[k].shape == self._prev_params[k].shape}
                record["updates"] = {k: _tensor_stats(v, self.histogram_bins)
                                     for k, v in updates.items()}
                record["update_ratios"] = {
                    k: float(np.abs(u).mean()
                             / max(np.abs(params[k]).mean(), 1e-12))
                    for k, u in updates.items()}
            self._prev_params = params
        self.storage.put(record)
