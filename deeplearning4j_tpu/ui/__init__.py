"""Training observability — stats collection, storage, profiling.

Reference: deeplearning4j-ui (SURVEY.md §2.2 "Training UI", §5.5):
``StatsListener`` → ``StatsStorage`` → Vert.x dashboard. Here the listener
bus stays, storage is in-memory or JSONL on disk (tensorboard/pandas-
friendly), and the Vert.x web server is replaced by storage query helpers —
the signature debugging aid (update:param-ratio histograms) is preserved.

Profiling (SURVEY.md §5.1): ``ProfilingListener`` emits Chrome trace-event
JSON (chrome://tracing / perfetto), like SameDiff's ProfilingListener;
``device_trace`` wraps ``jax.profiler`` for XLA-level traces; ``NanPanicListener``
is the "NaN panic" tripwire (reference: OpExecutionerUtil checkForNAN).
"""

from .server import UIServer
from .stats import FileStatsStorage, InMemoryStatsStorage, StatsListener, StatsStorage
from .profiling import (
    NanPanicListener,
    ProfilingListener,
    device_trace,
    enable_debug_nans,
)

__all__ = [
    "UIServer",
    "FileStatsStorage",
    "InMemoryStatsStorage",
    "NanPanicListener",
    "ProfilingListener",
    "StatsListener",
    "StatsStorage",
    "device_trace",
    "enable_debug_nans",
]
