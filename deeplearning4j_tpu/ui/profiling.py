"""Profiling + numerics tripwires.

Reference (SURVEY.md §5.1): SameDiff ProfilingListener writes Chrome
trace-event JSON; OpProfiler/PerformanceTracker time per-op work;
ProfilerConfig checkForNAN/INF ("NaN panic") throws on the first bad
value. TPU equivalents: iteration-phase trace events (host view),
``jax.profiler`` traces (device view, perfetto), ``jax_debug_nans``
plus a listener-level score tripwire.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any, List, Optional

import jax

from ..core.listeners import TrainingListener


class ProfilingListener(TrainingListener):
    """Emits Chrome trace-event JSON (load in chrome://tracing or
    ui.perfetto.dev). Each iteration is a complete event on the training
    track; epochs are nested spans."""

    def __init__(self, path: str, flush_every: int = 50) -> None:
        self.path = path
        self.flush_every = max(1, flush_every)
        self._events: List[dict] = []
        self._iter_start: Optional[float] = None
        self._epoch_start: Optional[float] = None
        self._epoch = 0
        self._t0 = time.perf_counter()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def on_epoch_start(self, model: Any) -> None:
        now = self._now_us()
        self._epoch_start = now
        # iteration 1's span starts here (it includes jit compile — usually
        # the dominant cost; a fabricated 1us duration would hide it) and
        # inter-epoch time is not charged to the next iteration
        self._iter_start = now

    def on_epoch_end(self, model: Any) -> None:
        if self._epoch_start is not None:
            self._events.append({
                "name": f"epoch {self._epoch}", "ph": "X", "pid": 0,
                "tid": 0, "ts": self._epoch_start,
                "dur": self._now_us() - self._epoch_start,
                "cat": "epoch",
            })
        self._epoch += 1
        self.flush()

    def iteration_done(self, model: Any, iteration: int, epoch: int,
                       score: float) -> None:
        now = self._now_us()
        start = self._iter_start if self._iter_start is not None else now
        self._events.append({
            "name": "iteration", "ph": "X", "pid": 0, "tid": 1,
            "ts": start, "dur": max(now - start, 1.0), "cat": "train",
            "args": {"iteration": iteration, "epoch": epoch,
                     "score": float(score)},
        })
        self._iter_start = now
        # periodic flush: a run that dies mid-epoch still leaves a trace
        if len(self._events) % self.flush_every == 0:
            self.flush()

    def flush(self) -> None:
        with open(self.path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms"}, f)


@contextlib.contextmanager
def device_trace(log_dir: str):
    """XLA/device-level profiling via jax.profiler (perfetto/tensorboard
    readable) — the deep view the host-side listener can't see."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def enable_debug_nans(enable: bool = True) -> None:
    """Global NaN panic (reference: ProfilerConfig.checkForNAN): XLA raises
    at the op that produced the first NaN. Costly — debugging only."""
    jax.config.update("jax_debug_nans", enable)


class NanPanicListener(TrainingListener):
    """Listener-level tripwire: raises the moment the training score goes
    non-finite, with context (reference: the executioner's checkForNAN at
    the op level; this is the cheap always-on variant)."""

    def iteration_done(self, model: Any, iteration: int, epoch: int,
                       score: float) -> None:
        import math

        if not math.isfinite(score):
            raise FloatingPointError(
                f"NaN panic: non-finite score {score} at iteration "
                f"{iteration} (epoch {epoch}). Enable "
                f"ui.enable_debug_nans() to locate the producing op.")
