"""Training UI web server.

Reference: deeplearning4j-ui's Vert.x dashboard (`UIServer.getInstance();
uiServer.attach(statsStorage)` — SURVEY.md §2.2 "Training UI"). Same
contract here on the stdlib http.server: attach a
:class:`~..ui.stats.StatsStorage`, browse http://localhost:9000 for live
loss curves, update:param ratios, and per-layer histograms; the JSON
endpoints (`/train/sessions`, `/train/stats?sessionId=`) serve machine
readers. No external web framework — the dashboard is one self-contained
HTML page with inline canvas charts, polling the JSON.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..obs.metrics import MetricsRegistry, get_registry
from ..obs.prom import CONTENT_TYPE as _PROM_CONTENT_TYPE
from ..obs.prom import render_prometheus
from ..obs.tracing import Tracer, get_tracer
from .stats import StatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><title>dl4j-tpu training UI</title>
<style>
 body { font-family: sans-serif; margin: 1.5em; background: #fafafa; }
 h1 { font-size: 1.2em; } h2 { font-size: 1.0em; color: #444; }
 canvas { background: #fff; border: 1px solid #ccc; margin: 4px 12px 12px 0; }
 .row { display: flex; flex-wrap: wrap; }
</style></head>
<body>
<h1>dl4j-tpu training UI</h1>
<div>session: <select id="session"></select></div>
<div class="row">
 <div><h2>score (loss)</h2><canvas id="score" width="460" height="220"></canvas></div>
 <div><h2>log10 update:param ratios</h2><canvas id="ratios" width="460" height="220"></canvas></div>
</div>
<script>
function drawSeries(id, series, logY) {
  const c = document.getElementById(id), g = c.getContext('2d');
  g.clearRect(0, 0, c.width, c.height);
  const names = Object.keys(series);
  if (!names.length) return;
  let lo = Infinity, hi = -Infinity, n = 0;
  for (const k of names) for (const v of series[k]) {
    if (isFinite(v)) { lo = Math.min(lo, v); hi = Math.max(hi, v); }
    n = Math.max(n, series[k].length);
  }
  if (!isFinite(lo)) return;
  if (hi === lo) { hi = lo + 1; }
  const colors = ['#06c', '#c33', '#090', '#960', '#909', '#099'];
  names.forEach((k, ci) => {
    g.strokeStyle = colors[ci % colors.length];
    g.beginPath();
    series[k].forEach((v, i) => {
      const x = 30 + (c.width - 40) * i / Math.max(n - 1, 1);
      const y = c.height - 20 - (c.height - 40) * (v - lo) / (hi - lo);
      i ? g.lineTo(x, y) : g.moveTo(x, y);
    });
    g.stroke();
    g.fillStyle = g.strokeStyle;
    g.fillText(k, 34, 14 + 12 * ci);
  });
  g.fillStyle = '#000';
  g.fillText(hi.toPrecision(4), 2, 12);
  g.fillText(lo.toPrecision(4), 2, c.height - 8);
}
async function refresh() {
  const sess = document.getElementById('session').value || '';
  const r = await fetch('/train/stats?sessionId=' + sess);
  const d = await r.json();
  drawSeries('score', {score: d.scores});
  drawSeries('ratios', d.update_ratios);
}
async function init() {
  const r = await fetch('/train/sessions');
  const sessions = await r.json();
  const sel = document.getElementById('session');
  sel.textContent = '';
  for (const s of sessions) {
    const o = document.createElement('option');
    o.textContent = s;
    sel.appendChild(o);
  }
  sel.onchange = refresh;
  await refresh();
  setInterval(refresh, 2000);
}
init();
</script></body></html>
"""


class UIServer:
    """``UIServer.get_instance().attach(storage)`` + ``start()`` — the
    reference's spelling, minus the JVM."""

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 9000, host: str = "127.0.0.1",
                 registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None) -> None:
        # loopback by default: the dashboard has no auth; pass
        # host="0.0.0.0" explicitly to expose it beyond the machine
        self.port = port
        self.host = host
        self.storage: Optional[StatsStorage] = None
        # /metrics source; None = the process-global registry at scrape
        # time, so the training dashboard process is scrapeable alongside
        # any serving endpoints it hosts
        self.registry = registry
        # /v1/traces source; None = the process-global tracer's store
        self.tracer = tracer
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def get_instance(cls, port: Optional[int] = None) -> "UIServer":
        """``port=None`` means "no preference" — it never overrides a port
        an earlier caller configured explicitly."""
        if cls._instance is None:
            cls._instance = cls(port if port is not None else 9000)
        elif port is not None and port != cls._instance.port:
            if cls._instance._httpd is not None:
                raise ValueError(
                    f"UIServer already running on port {cls._instance.port}; "
                    "stop() it before requesting a different port")
            # not yet started: honour the newly requested explicit port
            cls._instance.port = port
        return cls._instance

    getInstance = get_instance

    def attach(self, storage: StatsStorage) -> "UIServer":
        self.storage = storage
        return self

    # ---- payload builders (shared by HTTP + tests) ------------------------
    def sessions_payload(self):
        return self.storage.session_ids() if self.storage else []

    def stats_payload(self, session_id: Optional[str]) -> Dict[str, Any]:
        if self.storage is None:
            return {"scores": [], "update_ratios": {}, "iterations": []}
        sid = session_id or None
        records = self.storage.records(sid)
        scores = [float(r["score"]) for r in records if "score" in r]
        ratios: Dict[str, list] = {}
        for r in records:
            for pname, ratio in (r.get("update_ratios") or {}).items():
                val = float(ratio)
                ratios.setdefault(pname, []).append(
                    float(np.log10(max(val, 1e-12))))
        return {
            "scores": scores,
            "update_ratios": ratios,
            "iterations": [int(r.get("iteration", i))
                           for i, r in enumerate(records)],
        }

    def traces_payload(self, query: str = "") -> Dict[str, Any]:
        """``GET /v1/traces`` — same query surface as ``JsonModelServer``
        (``min_ms``, ``route``, ``limit``), so the training process's
        deploy/step traces are browsable next to its metrics."""
        q = parse_qs(query or "")

        def first(key, cast, default=None):
            vals = q.get(key)
            if not vals:
                return default
            try:
                return cast(vals[0])
            except (TypeError, ValueError):
                return default

        tracer = self.tracer if self.tracer is not None else get_tracer()
        return {
            "enabled": tracer.enabled,
            "trace_count": len(tracer.store),
            "traces": tracer.store.traces(
                min_duration_ms=first("min_ms", float),
                route=first("route", str),
                limit=first("limit", int, 50)),
        }

    # ---- server lifecycle -------------------------------------------------
    def start(self, block: bool = False) -> "UIServer":
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, body: bytes, ctype: str) -> None:
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                url = urlparse(self.path)
                if url.path in ("/", "/train", "/train/overview"):
                    self._send(_PAGE.encode(), "text/html")
                elif url.path == "/train/sessions":
                    self._send(json.dumps(ui.sessions_payload()).encode(),
                               "application/json")
                elif url.path == "/train/stats":
                    q = parse_qs(url.query)
                    sid = (q.get("sessionId") or [None])[0]
                    self._send(json.dumps(ui.stats_payload(sid)).encode(),
                               "application/json")
                elif url.path == "/metrics":
                    reg = ui.registry if ui.registry is not None \
                        else get_registry()
                    self._send(render_prometheus(reg).encode(),
                               _PROM_CONTENT_TYPE)
                elif url.path == "/v1/traces":
                    self._send(json.dumps(
                        ui.traces_payload(url.query)).encode(),
                        "application/json")
                else:
                    self.send_response(404)
                    self.end_headers()

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        if block:
            self._httpd.serve_forever()
            return self
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if UIServer._instance is self:
            UIServer._instance = None
