"""Versioned model registry, zero-downtime hot-swap, and canary serving.

The production-serving subsystem between training and the HTTP edge
(README "Model registry & hot-swap serving"):

* :class:`~.store.ModelStore` — versioned on-disk artifact store over
  ``model/serializer.py``: monotonic version ids, atomic publish,
  SHA-256 manifests verified on load, ``resolve("latest")``/pinned
  lookup, retention GC.
* :class:`~.manager.ModelManager` — load → warm → atomic swap →
  probation → automatic rollback (warmup failure or circuit-breaker
  open), plus canary/shadow rollout on a second engine.
* :class:`~.router.ModelRouter` — deterministic hash-split canary
  routing and fail-open shadow mirroring.
* :class:`~.multiplex.ModelMultiplexer` — multi-tenant weight paging:
  N registered models behind one submit surface on a fixed byte budget
  (LRU + request-rate-EWMA eviction via ``ModelManager.park()``,
  per-tenant SLO admission, bounded cold-start page-in queueing) plus
  :class:`~.multiplex.PoolAutoscaler` for load-driven replica counts.

``remote/JsonModelServer`` exposes managed models over HTTP
(``GET /v1/models``, ``POST /v1/models/<name>``, ``X-Model-Version``
pinning); ``tools/check_registry_contract.py`` enforces the
publish → resolve → swap → rollback contract every test run.
"""

from .disagg import (
    DisaggCoordinator,
    PartialHandoffError,
    PrefillEngine,
    deserialize_handoff,
    serialize_handoff,
)
from .manager import (
    LOAD_SITE,
    WARMUP_SITE,
    ModelManager,
    ModelParkedError,
    SwapError,
)
from .multiplex import ModelMultiplexer, PoolAutoscaler, model_bytes
from .router import ModelRouter
from .store import (
    LATEST,
    ChecksumMismatchError,
    ModelStore,
    ModelStoreError,
    ModelVersion,
    VersionNotFoundError,
)

__all__ = [
    "LATEST",
    "LOAD_SITE",
    "WARMUP_SITE",
    "ChecksumMismatchError",
    "DisaggCoordinator",
    "ModelManager",
    "ModelMultiplexer",
    "ModelParkedError",
    "ModelRouter",
    "ModelStore",
    "ModelStoreError",
    "ModelVersion",
    "PartialHandoffError",
    "PoolAutoscaler",
    "PrefillEngine",
    "SwapError",
    "VersionNotFoundError",
    "deserialize_handoff",
    "model_bytes",
    "serialize_handoff",
]
