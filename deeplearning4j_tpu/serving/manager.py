"""Hot-swap engine: load → warm → swap → probation → rollback.

A retrained model must replace the live one **without stopping the
server** (ROADMAP north star; the servable lifecycle of "TensorFlow: A
system for large-scale machine learning", PAPERS.md). The sequence a
:class:`ModelManager` runs for :meth:`deploy`:

1. **Load off the serving path.** The candidate version is resolved and
   checksum-verified out of the :class:`~.store.ModelStore` in the
   caller's thread; serving workers keep draining traffic untouched.
2. **Warm before swap.** The candidate's jitted forward is compiled and
   executed on the bucketed batch shapes the live
   :class:`~deeplearning4j_tpu.parallel.inference.ParallelInference`
   actually serves (:meth:`~deeplearning4j_tpu.parallel.inference.
   ParallelInference.bucket_sizes` × the last-served feature shape), so
   the first post-swap request never pays an XLA compile. A warmup
   failure aborts the deploy — the prior version stays live
   (``dl4j_tpu_serving_swap_total{outcome="warmup_failed"}``).
3. **Atomic swap.** One reference assignment installs the candidate; the
   retired servable is kept resident as the rollback target. The
   candidate gets a **fresh** :class:`~deeplearning4j_tpu.core.
   resilience.CircuitBreaker` so the old version's failure window cannot
   bias it.
4. **Probation.** If the candidate's breaker opens within
   ``probation_seconds`` of the swap, the manager rolls back to the
   prior servable automatically
   (``dl4j_tpu_serving_swap_total{outcome="rolled_back"}``).

The same sequence drives a replica pool unchanged: pass
``engine=EnginePool(...)`` and deploy warms the candidate on every
replica (one warmup pass executes each replica's jitted forward), then
swaps all replicas atomically-per-replica with rollback on partial
failure (:meth:`~deeplearning4j_tpu.parallel.pool.EnginePool.swap`).
The probation breaker is shared across replicas — probation judges the
*version*, not a replica.

Canary rollout runs the candidate on a *second* engine behind a
:class:`~.router.ModelRouter` (deterministic hash split or shadow
mirroring) before it ever owns 100% of traffic; a canary breaker-open
inside probation tears the canary down instead of rolling back the live
engine. Every path is exercisable on CPU via the seeded
:class:`~deeplearning4j_tpu.core.resilience.FaultInjector` sites
``model_manager.load`` and ``model_manager.warmup``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Union

import numpy as np

import jax.numpy as jnp

from ..core.resilience import CircuitBreaker, CircuitState, get_fault_injector
from ..obs.metrics import MetricsRegistry, Span, get_registry
from ..obs.tracing import Tracer, get_tracer
from ..parallel.inference import ParallelInference, Servable
from .router import ModelRouter
from .store import LATEST, ModelStore, ModelVersion, VersionNotFoundError

LOAD_SITE = "model_manager.load"      # FaultInjector: artifact load
WARMUP_SITE = "model_manager.warmup"  # FaultInjector: per-bucket warmup fwd

_SWAP_OUTCOMES = ("completed", "warmup_failed", "rolled_back",
                  "canary_started", "canary_promoted", "canary_stopped")

#: sentinel: "use the manager's default optimize pipeline" — distinct from
#: None, which explicitly disables rewrites for one deploy/canary
_DEFAULT_OPTIMIZE = object()


class SwapError(RuntimeError):
    """A deploy/rollback could not complete; the prior version is live."""


class ModelParkedError(RuntimeError):
    """The model's device weights are paged out (``park()``); a request
    must page the model back in (``unpark()``) before it can serve. The
    multiplexing layer treats this as a cold-start miss and queues the
    page-in instead of failing the request."""


class _Deployment:
    """A resident version: servable + the breaker that judged it + the
    rewrite pipeline it was loaded under (so a canary promotion replays
    the canary's optimize spec, and re-deploying the same version under a
    DIFFERENT pipeline — quantize/de-quantize — is a real swap)."""

    __slots__ = ("entry", "servable", "breaker", "optimize")

    def __init__(self, entry: Optional[ModelVersion], servable: Servable,
                 breaker: CircuitBreaker, optimize=None) -> None:
        self.entry = entry
        self.servable = servable
        self.breaker = breaker
        self.optimize = optimize

    @property
    def version(self) -> str:
        return self.servable.version


class ModelManager:
    def __init__(
        self,
        store: ModelStore,
        model_name: str,
        *,
        version: Union[int, str] = LATEST,
        model=None,
        engine: Optional[ParallelInference] = None,
        batch_limit: int = 32,
        workers: int = 2,
        queue_limit: int = 256,
        default_timeout: Optional[float] = None,
        warmup_example=None,
        probation_seconds: float = 300.0,
        breaker_factory: Optional[Callable[[], CircuitBreaker]] = None,
        clock: Callable[[], float] = time.monotonic,
        fault_injector=None,
        registry: Optional[MetricsRegistry] = None,
        optimize: Union[str, list, None] = "inference",
        tracer: Optional[Tracer] = None,
    ) -> None:
        self.store = store
        self.model_name = model_name
        self._clock = clock
        self._fault_injector = fault_injector
        self._tracer = tracer  # None -> process-global at call time
        # graph rewrite pipeline applied to every store-loaded model
        # BEFORE warmup (nn/rewrite): the default "inference" set folds
        # conv+BN, rewrites the conv stem and fuses remaining BNs, so the
        # swapped-in version serves — and probation measures — the
        # rewritten graph. In-memory only: store artifacts stay
        # un-rewritten. None disables.
        self._optimize = optimize
        self.probation_seconds = float(probation_seconds)
        self._breaker_factory = breaker_factory or (
            lambda: CircuitBreaker(clock=clock))
        self._warmup_example = warmup_example
        self._engine_opts = dict(
            batch_limit=batch_limit, workers=workers, queue_limit=queue_limit,
            default_timeout=default_timeout, clock=clock,
            fault_injector=fault_injector, tracer=tracer)
        self.registry = registry if registry is not None else get_registry()
        swap = self.registry.counter(
            "dl4j_tpu_serving_swap_total",
            "Model hot-swap lifecycle events by outcome",
            ("model", "outcome"))
        self._c_swap = {o: swap.labels(model_name, o) for o in _SWAP_OUTCOMES}
        self._h_warmup = self.registry.histogram(
            "dl4j_tpu_serving_warmup_latency_seconds",
            "Per-bucket warmup forward latency (compile + execute)",
            ("model",)).labels(model_name)
        self._g_live = self.registry.gauge(
            "dl4j_tpu_serving_live_version",
            "Version id currently serving 100% (or primary) traffic",
            ("model",)).labels(model_name)
        self._g_quant_live = self.registry.gauge(
            "dl4j_tpu_serving_quantized_live",
            "Quantized layers in the graph serving primary traffic (0 = "
            "full-precision serving)", ("model",)).labels(model_name)
        self._c_quant_family = self.registry.counter(
            "dl4j_tpu_serving_quantized_deploys_total",
            "Loads (deploy or canary) whose rewrite pipeline applied a "
            "weight-quantization pass", ("model", "dtype"))

        self._lock = threading.RLock()
        self._probation_until = 0.0
        self._rolling_back = False
        self._canary: Optional[_Deployment] = None
        self._canary_engine: Optional[ParallelInference] = None
        self._router: Optional[ModelRouter] = None
        # park()/unpark(): non-None while the device weights are paged
        # out — holds exactly the pipeline state a page-in must replay
        self._parked: Optional[Dict] = None
        self._owns_engine = engine is None

        if engine is not None:
            self.engine = engine
            entry = None
            try:
                entry = store.resolve(model_name, engine.model_version)
            except VersionNotFoundError:
                pass
            self._live = _Deployment(entry, engine._servable,
                                     engine._breaker,
                                     optimize=self._optimize)
        else:
            entry = None
            if model is None:
                model, entry = self._load(version)
            elif version != LATEST:
                entry = store.resolve(model_name, version)
            initial_version = str(entry.version) if entry is not None else "0"
            breaker = self._breaker_factory()
            self.engine = ParallelInference(
                model, circuit_breaker=breaker, registry=self.registry,
                name=f"{model_name}-live", model_version=initial_version,
                **self._engine_opts)
            self._live = _Deployment(entry, self.engine._servable, breaker,
                                     optimize=self._optimize)
        self._previous: Optional[_Deployment] = None
        self._set_live_gauge()
        self._set_quantized_gauge()

    # ----- helpers ----------------------------------------------------
    def _inj(self):
        return self._fault_injector or get_fault_injector()

    @property
    def tracer(self) -> Tracer:
        return self._tracer if self._tracer is not None else get_tracer()

    def _resolve_optimize(self, optimize):
        return self._optimize if optimize is _DEFAULT_OPTIMIZE else optimize

    def _load(self, version: Union[int, str], *,
              optimize=_DEFAULT_OPTIMIZE):
        """Load + checksum-verify from the store, then apply the inference
        rewrite pipeline to the in-memory copy (the artifact on disk stays
        un-rewritten). Warmup — and therefore probation — always measures
        the graph that will actually serve. ``optimize`` overrides the
        manager default for this load (the per-deploy knob: e.g. canary a
        quantized ``"inference:int8"`` build of a version against the
        full-precision incumbent)."""
        opt = self._resolve_optimize(optimize)
        with self.tracer.span("manager.load",
                              attrs={"model": self.model_name,
                                     "version": str(version)}):
            self._inj().fire(LOAD_SITE)
            model, entry = self.store.load(self.model_name, version)
            if opt:
                from ..nn.rewrite import rewrite_model

                model, applied = rewrite_model(model, opt,
                                               context="inference")
                if applied:
                    self.registry.log_event(
                        "model_rewrite", model=self.model_name,
                        version=str(entry.version), passes=applied)
                    for pname in applied:
                        if pname.startswith("quantize_weights_"):
                            self._c_quant_family.labels(
                                self.model_name,
                                pname.rsplit("_", 1)[-1]).inc()
        return model, entry

    def _set_quantized_gauge(self) -> None:
        from ..nn.rewrite import count_quantized_layers

        model = getattr(self._live.servable, "model", None)
        self._g_quant_live.set(
            float(count_quantized_layers(model)) if model is not None
            else 0.0)

    def _set_live_gauge(self) -> None:
        try:
            self._g_live.set(float(self._live.version))
        except ValueError:
            self._g_live.set(0.0)

    def _warmup_shapes(self):
        """Feature shape to warm on: explicit example wins, else the last
        shape the live engine served, else skip warmup (nothing is known
        about the traffic yet — the first request compiles, exactly like
        a cold engine)."""
        if self._warmup_example is not None:
            ex = np.asarray(self._warmup_example)
            return tuple(ex.shape[1:] if ex.ndim > 1 else ex.shape)
        return self.engine.last_input_shape

    def _warm(self, servable: Servable, engine: ParallelInference) -> None:
        feat = self._warmup_shapes()
        if feat is None:
            return
        if getattr(servable, "model", None) is None:
            # remote-backed servable (cross-host fabric): there is no
            # local jitted forward to warm — each host warms during its
            # own deploy, driven by the swap fan-out
            return
        dtype = servable.model.dtype
        with self.tracer.span("manager.warmup",
                              attrs={"model": self.model_name,
                                     "version": servable.version,
                                     "buckets": len(engine.bucket_sizes())}):
            for b in engine.bucket_sizes():
                x = jnp.zeros((b,) + tuple(feat), dtype)
                with Span(self._h_warmup):
                    self._inj().fire(WARMUP_SITE)
                    np.asarray(servable.fwd(x))  # block until executed

    # ----- deploy / rollback ------------------------------------------
    @property
    def live_version(self) -> str:
        parked = self._parked
        if parked is not None:
            return parked["version"]
        return self._live.version

    @property
    def previous_version(self) -> Optional[str]:
        parked = self._parked
        if parked is not None:
            return parked["previous_version"]
        return self._previous.version if self._previous else None

    @property
    def canary_version(self) -> Optional[str]:
        return self._canary.version if self._canary else None

    def deploy(self, version: Union[int, str] = LATEST, *,
               optimize=_DEFAULT_OPTIMIZE) -> ModelVersion:
        """Zero-downtime hot swap to ``version``: load + verify + warm off
        the serving path, then atomically install. On warmup failure the
        prior version stays live and :class:`SwapError` is raised. The
        new version serves under a fresh circuit breaker and is on
        probation for ``probation_seconds`` — a breaker-open inside that
        window rolls back automatically. ``optimize`` overrides the
        manager's rewrite pipeline for this deploy (e.g.
        ``"inference:int8"`` serves the quantized build of the version;
        the store artifact stays full-precision either way) — redeploying
        the LIVE version under a different pipeline is a real swap."""
        with self._lock:
            opt = self._resolve_optimize(optimize)
            entry = self.store.resolve(self.model_name, version)
            if self._parked is not None:
                # deploy-while-parked retargets the page-in: the next
                # unpark loads this version under this pipeline. No
                # load/warm happens now — a cold model costs nothing
                # until traffic actually pages it in (fleet-wide deploy
                # fan-outs stay cheap across mostly-cold hosts).
                self._parked["version"] = str(entry.version)
                self._parked["optimize"] = opt
                self._parked["previous_version"] = None
                self._parked["canary"] = None
                self.registry.log_event(
                    "model_parked_deploy", model=self.model_name,
                    version=str(entry.version))
                return entry
            if (str(entry.version) == self._live.version
                    and opt == self._live.optimize):
                return entry
            # a slow deploy must be diagnosable after the fact: the whole
            # load→warm→swap sequence is one trace, children per stage
            with self.tracer.span(
                    "manager.deploy",
                    attrs={"model": self.model_name,
                           "version": str(entry.version),
                           "previous": self._live.version}) as dspan:
                model, entry = self._load(entry.version, optimize=opt)
                servable = self.engine.make_servable(
                    model, version=str(entry.version))
                try:
                    self._warm(servable, self.engine)
                except Exception as e:
                    self._c_swap["warmup_failed"].inc()
                    dspan.set_attribute("outcome", "warmup_failed")
                    raise SwapError(
                        f"{self.model_name} v{entry.version}: warmup failed, "
                        f"keeping v{self._live.version} live: {e}") from e
                breaker = self._breaker_factory()
                breaker.add_observer(self._on_candidate_transition)
                old_breaker = self._live.breaker
                with self.tracer.span("manager.swap",
                                      attrs={"model": self.model_name,
                                             "version": str(entry.version)}):
                    self.engine.swap(servable, circuit_breaker=breaker)
                old_breaker.remove_observer(self._on_candidate_transition)
                self._previous = self._live
                self._live = _Deployment(entry, servable, breaker,
                                         optimize=opt)
                self._probation_until = self._clock() + self.probation_seconds
                self._rolling_back = False
                self._c_swap["completed"].inc()
                self._set_live_gauge()
                self._set_quantized_gauge()
                dspan.set_attribute("outcome", "completed")
                self.registry.log_event(
                    "model_swap", model=self.model_name,
                    version=str(entry.version),
                    previous=self._previous.version)
            return entry

    def _on_candidate_transition(self, old: CircuitState,
                                 new: CircuitState) -> None:
        """Breaker observer for the probationary live version: an OPEN
        inside the probation window triggers automatic rollback.

        Deliberately lock-free: this can fire from any thread that reads
        ``breaker.state`` — including one already holding the engine's
        lock (``output_async``) while ``deploy`` holds the manager lock
        and wants the engine's (ABBA). The screen below is a benign
        race; the reaper thread re-verifies under the lock."""
        if new is not CircuitState.OPEN:
            return
        live = self._live
        if (self._rolling_back or self._previous is None
                or self._clock() > self._probation_until):
            return
        threading.Thread(target=self._auto_rollback, args=(live,),
                         name=f"{self.model_name}-rollback",
                         daemon=True).start()

    def _auto_rollback(self, dep: _Deployment) -> None:
        with self._lock:
            # identity check: if a newer deploy landed between the trip
            # and this reaper, the open breaker belonged to a version
            # that is no longer live — do not roll back the newcomer
            if (dep is not self._live or self._rolling_back
                    or self._previous is None
                    or self._clock() > self._probation_until):
                return
            self._rolling_back = True
            self._rollback_locked()

    def rollback(self) -> ModelVersion:
        """Manually swap back to the previously live version."""
        with self._lock:
            if self._parked is not None:
                raise ModelParkedError(
                    f"{self.model_name} is parked; unpark before rollback")
            if self._previous is None:
                raise SwapError(f"{self.model_name}: no previous version "
                                f"resident to roll back to")
            return self._rollback_locked().entry

    def _rollback_locked(self) -> _Deployment:
        bad = self._live
        good = self._previous
        with self.tracer.span("manager.rollback",
                              attrs={"model": self.model_name,
                                     "version": good.version,
                                     "rolled_back_from": bad.version}):
            bad.breaker.remove_observer(self._on_candidate_transition)
            # counter first: anyone who observes the version flip must also
            # see the rollback already counted
            self._c_swap["rolled_back"].inc()
            self.engine.swap(good.servable, circuit_breaker=good.breaker)
            self._live = good
            self._previous = None  # the bad version is not a rollback target
            self._probation_until = 0.0
            self._set_live_gauge()
            self._set_quantized_gauge()
            self.registry.log_event(
                "model_rollback", model=self.model_name,
                version=good.version, rolled_back_from=bad.version)
        return good

    def confirm(self) -> None:
        """End probation early: the live version is declared good."""
        with self._lock:
            self._probation_until = 0.0
            self._live.breaker.remove_observer(self._on_candidate_transition)

    # ----- canary / shadow --------------------------------------------
    def start_canary(self, version: Union[int, str], *,
                     weight: float = 0.05, shadow: bool = False,
                     workers: int = 1,
                     optimize=_DEFAULT_OPTIMIZE) -> ModelVersion:
        """Load + warm ``version`` on a second engine and route ``weight``
        of traffic (deterministic per request key) to it — or, with
        ``shadow=True``, mirror every request to it while responses keep
        coming from the live version. A canary breaker-open inside the
        probation window stops the canary automatically. ``optimize``
        overrides the rewrite pipeline for the canary only — the
        quantization rollout path: ``start_canary(v,
        optimize="inference:int8")`` serves the int8 build next to the
        full-precision incumbent under the hash split, and
        :meth:`promote_canary` replays the same pipeline on the live
        engine (rollback stays free: the incumbent servable is resident)."""
        with self._lock:
            if self._parked is not None:
                raise ModelParkedError(
                    f"{self.model_name} is parked; unpark before canary")
            if self._canary is not None:
                raise SwapError(f"{self.model_name}: canary v"
                                f"{self._canary.version} already running")
            opt = self._resolve_optimize(optimize)
            with self.tracer.span(
                    "manager.canary_start",
                    attrs={"model": self.model_name,
                           "version": str(version), "weight": weight,
                           "shadow": bool(shadow)}):
                model, entry = self._load(version, optimize=opt)
                breaker = self._breaker_factory()
                opts = dict(self._engine_opts)
                opts["workers"] = workers
                engine = ParallelInference(
                    model, circuit_breaker=breaker, registry=self.registry,
                    name=f"{self.model_name}-canary",
                    model_version=str(entry.version), **opts)
                try:
                    self._warm(engine._servable, engine)
                except Exception as e:
                    engine.shutdown(drain=False)
                    self._c_swap["warmup_failed"].inc()
                    raise SwapError(
                        f"{self.model_name} v{entry.version}: canary warmup "
                        f"failed: {e}") from e
            breaker.add_observer(self._on_canary_transition)
            self._canary = _Deployment(entry, engine._servable, breaker,
                                       optimize=opt)
            self._canary_engine = engine
            self._router = ModelRouter(
                self.engine,
                canary=None if shadow else engine,
                canary_weight=0.0 if shadow else weight,
                shadow=engine if shadow else None,
                name=self.model_name, registry=self.registry)
            self._probation_until = self._clock() + self.probation_seconds
            self._c_swap["canary_started"].inc()
            self.registry.log_event(
                "canary_start", model=self.model_name,
                version=str(entry.version), weight=weight, shadow=shadow)
            return entry

    def _on_canary_transition(self, old: CircuitState,
                              new: CircuitState) -> None:
        if new is not CircuitState.OPEN:
            return
        # Lock-free screen, like _on_candidate_transition. Beyond the
        # lock-order hazard, this observer fires on the canary engine's
        # own worker thread (whichever recorded the tripping failure) and
        # tearing the engine down would join that thread — so the reaper
        # is mandatory here, not just defensive. Until it runs,
        # canary-routed requests fail fast with CircuitOpenError, which
        # is the correct interim behavior.
        canary = self._canary
        if canary is None or self._clock() > self._probation_until:
            return
        threading.Thread(target=self._abort_canary, args=(canary,),
                         name=f"{self.model_name}-canary-reaper",
                         daemon=True).start()

    def _abort_canary(self, dep: _Deployment) -> None:
        with self._lock:
            if self._canary is not dep:  # stopped/replaced in the interim
                return
            self._stop_canary_locked()
            self._c_swap["rolled_back"].inc()
            self.registry.log_event(
                "canary_rollback", model=self.model_name,
                version=dep.version)

    def promote_canary(self) -> ModelVersion:
        """The canary won: hot-swap its version onto the live engine
        (full deploy path: warmed, fresh breaker, probation — under the
        SAME rewrite pipeline the canary was judged on, so a quantized
        canary promotes to quantized serving), then tear the canary
        engine down."""
        with self._lock:
            if self._canary is None:
                raise SwapError(f"{self.model_name}: no canary to promote")
            version = self._canary.entry.version
            optimize = self._canary.optimize
            self._stop_canary_locked()
            entry = self.deploy(version, optimize=optimize)
            self._c_swap["canary_promoted"].inc()
            return entry

    def stop_canary(self) -> None:
        with self._lock:
            if self._canary is None:
                return
            self._stop_canary_locked()

    def _stop_canary_locked(self) -> None:
        engine, dep = self._canary_engine, self._canary
        self._canary = None
        self._canary_engine = None
        self._router = None
        dep.breaker.remove_observer(self._on_canary_transition)
        engine.shutdown(drain=True, drain_timeout=10.0)
        self._c_swap["canary_stopped"].inc()

    # ----- weight paging (park / unpark) ------------------------------
    @property
    def parked(self) -> bool:
        return self._parked is not None

    @property
    def residency(self) -> str:
        """``"warm"`` or ``"parked"`` — the multiplexing layer overlays
        the transient ``"paging"`` state while a page-in is running."""
        return "parked" if self._parked is not None else "warm"

    def resident_bytes(self) -> int:
        """Device-weight bytes this manager keeps resident: every param/
        state leaf of the live, rollback and canary servables (deduped —
        rollback and live can share nothing, but a servable without a
        local model, e.g. remote-backed, contributes 0). Parked → 0."""
        import jax

        with self._lock:
            if self._parked is not None:
                return 0
            total = 0
            for dep in (self._live, self._previous, self._canary):
                model = getattr(dep.servable, "model", None) \
                    if dep is not None else None
                if model is None:
                    continue
                leaves = jax.tree_util.tree_leaves(
                    (getattr(model, "params", None),
                     getattr(model, "state", None)))
                total += sum(int(leaf.size) * leaf.dtype.itemsize
                             for leaf in leaves if hasattr(leaf, "dtype"))
            return total

    def park(self, *, drain_timeout: Optional[float] = 30.0) -> bool:
        """Page the model out: drain + shut down the engine and drop
        every resident servable (the device weights), keeping only the
        pipeline state a page-in needs — live version id + its rewrite
        pipeline, the rollback target's id, and a running canary's spec
        (version/weight/shadow/pipeline) so :meth:`unpark` replays the
        exact deployment, quantization included. Store artifacts are
        untouched and :meth:`resident_versions` keeps counting the
        parked versions, so GC can never collect what a page-in needs.
        Idempotent: returns False when already parked."""
        with self._lock:
            if self._parked is not None:
                return False
            if not self._owns_engine:
                raise SwapError(
                    f"{self.model_name}: cannot park a caller-owned "
                    f"engine (pass model=/version= so the manager owns "
                    f"the engine lifecycle)")
            if self._live.entry is None:
                raise SwapError(
                    f"{self.model_name}: live version is not backed by a "
                    f"store artifact; a page-in could not replay it")
            canary_spec = None
            if self._canary is not None:
                canary_spec = {
                    "version": self._canary.entry.version
                    if self._canary.entry is not None
                    else self._canary.version,
                    "weight": self._router.canary_weight
                    if self._router is not None else 0.0,
                    "shadow": bool(self._router is not None
                                   and self._router.shadow is not None),
                    "optimize": self._canary.optimize,
                }
                self._stop_canary_locked()
            state = {
                "version": self._live.version,
                "optimize": self._live.optimize,
                "previous_version": self.previous_version,
                "canary": canary_spec,
                "warm_shape": self.engine.last_input_shape,
            }
            engine = self.engine
            # flip first: submits refuse (ModelParkedError) while the
            # engine drains, so no request can race the teardown
            self._parked = state
        engine.shutdown(drain=True, drain_timeout=drain_timeout)
        with self._lock:
            self.engine = None
            self._live = None
            self._previous = None
            self._probation_until = 0.0
        self.registry.log_event("model_park", model=self.model_name,
                                version=state["version"])
        return True

    def unpark(self) -> ModelVersion:
        """Page the model back in by replaying the recorded deployment:
        load + checksum-verify the parked version from the store, apply
        the same rewrite pipeline (a quantized deploy pages back in
        quantized — byte-identical weights, since the rewrite is a
        deterministic function of the immutable artifact), rebuild the
        engine, warm on the shapes served before the park, and restart a
        recorded canary. On failure the manager STAYS parked (the next
        request retries the page-in). Idempotent when already warm."""
        with self._lock:
            if self._parked is None:
                return self.store.resolve(self.model_name,
                                          self._live.version)
            state = self._parked
            model, entry = self._load(state["version"],
                                      optimize=state["optimize"])
            breaker = self._breaker_factory()
            engine = ParallelInference(
                model, circuit_breaker=breaker, registry=self.registry,
                name=f"{self.model_name}-live",
                model_version=str(entry.version), **self._engine_opts)
            if state["warm_shape"] is not None:
                engine.last_input_shape = tuple(state["warm_shape"])
            old_engine, self.engine = self.engine, engine
            try:
                self._warm(engine._servable, engine)
            except Exception as e:
                self.engine = old_engine
                engine.shutdown(drain=False)
                self._c_swap["warmup_failed"].inc()
                raise SwapError(
                    f"{self.model_name} v{entry.version}: page-in warmup "
                    f"failed; staying parked: {e}") from e
            self._live = _Deployment(entry, engine._servable, breaker,
                                     optimize=state["optimize"])
            self._previous = None
            self._parked = None
            self._set_live_gauge()
            self._set_quantized_gauge()
            self.registry.log_event("model_unpark", model=self.model_name,
                                    version=str(entry.version))
            canary = state.get("canary")
            if canary is not None:
                try:
                    self.start_canary(canary["version"],
                                      weight=canary["weight"],
                                      shadow=canary["shadow"],
                                      optimize=canary["optimize"])
                except Exception as e:  # canary restore is best-effort
                    self.registry.log_event(
                        "canary_restore_failed", model=self.model_name,
                        version=str(canary["version"]), error=str(e))
            return entry

    # ----- request path -----------------------------------------------
    def submit(self, x, *, key: Optional[str] = None,
               version: Optional[Union[int, str]] = None,
               timeout: Optional[float] = None, deadline=None,
               priority: Optional[str] = None):
        """Route one request; returns ``(future, version_str)``. A pinned
        ``version`` must be resident and serving (the live version, or
        the canary) — pinning is how a client deterministically hits the
        canary or asserts which version answered. ``priority`` names an
        admission priority class (HTTP ``X-Priority``)."""
        if self._parked is not None:
            raise ModelParkedError(
                f"{self.model_name} is parked (weights paged out)")
        if version is not None:
            want = str(version).lstrip("v")
            if want == self._live.version:
                fut = self.engine.output_async(
                    x, timeout=timeout, deadline=deadline,
                    priority=priority)
                return fut, self._live.version
            canary, engine = self._canary, self._canary_engine
            if canary is not None and want == canary.version:
                fut = engine.output_async(
                    x, timeout=timeout, deadline=deadline,
                    priority=priority)
                return fut, canary.version
            raise VersionNotFoundError(
                f"{self.model_name} v{want} is not currently serving "
                f"(live=v{self._live.version}, canary="
                f"{'v' + canary.version if canary else 'none'})")
        router = self._router
        if router is not None:
            fut, _target, served = router.submit(
                x, key=key, timeout=timeout, deadline=deadline,
                priority=priority)
            return fut, served
        fut = self.engine.output_async(x, timeout=timeout, deadline=deadline,
                                       priority=priority)
        return fut, self._live.version

    def output(self, x, *, key: Optional[str] = None,
               version: Optional[Union[int, str]] = None,
               timeout: Optional[float] = None) -> np.ndarray:
        fut, _ = self.submit(x, key=key, version=version, timeout=timeout)
        return fut.result()

    # ----- introspection / lifecycle ----------------------------------
    def describe(self) -> Dict:
        from ..nn.rewrite import count_quantized_layers

        with self._lock:
            if self._parked is not None:
                return {
                    "name": self.model_name,
                    "residency": "parked",
                    "live_version": self._parked["version"],
                    "previous_version": self._parked["previous_version"],
                    "parked_canary": self._parked["canary"],
                    "optimize": self._parked["optimize"],
                }
            canary = None
            if self._canary is not None:
                canary = {
                    "version": self._canary.version,
                    "weight": self._router.canary_weight if self._router else 0.0,
                    "shadow": bool(self._router and self._router.shadow is not None),
                    "circuit": self._canary.breaker.state.value,
                    "quantized_layers": count_quantized_layers(
                        getattr(self._canary.servable, "model", None)),
                }
            live_model = getattr(self._live.servable, "model", None)
            return {
                "quantized_layers": count_quantized_layers(live_model),
                "name": self.model_name,
                "residency": "warm",
                "live_version": self._live.version,
                "previous_version": self.previous_version,
                "canary": canary,
                "probation_remaining": max(
                    0.0, self._probation_until - self._clock()),
                "circuit": self._live.breaker.state.value,
            }

    def resident_versions(self):
        """Version ids that must survive GC (live, rollback target,
        canary) — INCLUDING while parked: a paged-out model's versions
        are exactly the artifacts the next page-in loads, so GC deleting
        them would turn every future cold-start into a 404."""
        out = set()
        with self._lock:
            if self._parked is not None:
                canary = self._parked["canary"]
                for v in (self._parked["version"],
                          self._parked["previous_version"],
                          str(canary["version"]) if canary else None):
                    if v is not None and str(v).isdigit():
                        out.add(int(v))
                return out
            for dep in (self._live, self._previous, self._canary):
                if dep is not None and dep.version.isdigit():
                    out.add(int(dep.version))
        return out

    def gc(self, *, keep_last: Optional[int] = None) -> Dict:
        """Store GC for this model, protecting every resident version."""
        return self.store.gc(self.model_name, keep_last=keep_last,
                             in_use=self.resident_versions())

    def stats(self) -> Dict:
        if self._parked is not None:
            return {"residency": "parked",
                    "live_version": self.live_version}
        s = self.engine.stats()
        s["residency"] = "warm"
        if self._canary_engine is not None:
            s["canary"] = self._canary_engine.stats()
        return s

    def shutdown(self, *, drain: bool = True,
                 drain_timeout: Optional[float] = 30.0) -> None:
        with self._lock:
            if self._canary is not None:
                self._stop_canary_locked()
            engine = self.engine
        if engine is not None:  # parked: nothing resident to tear down
            engine.shutdown(drain=drain, drain_timeout=drain_timeout)
