"""Deterministic traffic splitting for canary rollout + shadow mirroring.

A canary must receive a *stable* slice of traffic: the same request key
always lands on the same side (users don't flap between model versions,
and an incident is attributable to the version that served it). The
router therefore hashes the request key — not a random draw — into
``granularity`` buckets and sends the lowest ``weight``-fraction to the
canary; keyless requests hash their own payload bytes, which keeps the
split deterministic for replayed traffic too.

Shadow mode mirrors every request to the shadow backend and ignores the
result (errors included): the candidate sees production traffic and
fills its metrics/latency histograms, while responses keep coming from
the primary. Mirroring is fail-open — a shed/open/broken shadow never
affects a live response.

Backends are anything with ``output_async(x, timeout=, deadline=)`` and
``model_version`` — i.e. :class:`~deeplearning4j_tpu.parallel.inference.
ParallelInference` engines.
"""

from __future__ import annotations

import hashlib
from concurrent.futures import Future
from typing import Optional, Tuple

import numpy as np

from ..obs.metrics import MetricsRegistry, get_registry

PRIMARY = "primary"
CANARY = "canary"
SHADOW = "shadow"


def _hash_bucket(key: bytes, salt: str, granularity: int) -> int:
    h = hashlib.sha256(salt.encode() + key).digest()
    return int.from_bytes(h[:8], "big") % granularity


class ModelRouter:
    def __init__(self, primary, *, canary=None, canary_weight: float = 0.0,
                 shadow=None, salt: str = "", granularity: int = 10_000,
                 name: str = "router",
                 registry: Optional[MetricsRegistry] = None) -> None:
        if not 0.0 <= canary_weight <= 1.0:
            raise ValueError(f"canary_weight must be in [0, 1], "
                             f"got {canary_weight}")
        if canary is None and canary_weight > 0.0:
            raise ValueError("canary_weight > 0 without a canary backend")
        self.primary = primary
        self.canary = canary
        self.canary_weight = float(canary_weight)
        self.shadow = shadow
        self.salt = salt
        self.granularity = int(granularity)
        self.name = name
        reg = registry if registry is not None else get_registry()
        routes = reg.counter(
            "dl4j_tpu_serving_routes_total",
            "Routing decisions (shadow counts mirrored submissions)",
            ("router", "target"))
        self._c = {t: routes.labels(name, t)
                   for t in (PRIMARY, CANARY, SHADOW)}

    # ----- decision ----------------------------------------------------
    def _key_bytes(self, x, key: Optional[str]) -> bytes:
        if key is not None:
            return str(key).encode()
        return np.ascontiguousarray(x).tobytes()

    def assign(self, x, *, key: Optional[str] = None) -> str:
        """``"primary"`` or ``"canary"`` for this request — pure function
        of (key|payload, salt, weight)."""
        if self.canary is None or self.canary_weight <= 0.0:
            return PRIMARY
        bucket = _hash_bucket(self._key_bytes(x, key), self.salt,
                              self.granularity)
        if bucket < self.canary_weight * self.granularity:
            return CANARY
        return PRIMARY

    # ----- request path -------------------------------------------------
    def _mirror(self, x, timeout) -> None:
        """Fire-and-forget shadow submission; never raises."""
        try:
            fut = self.shadow.output_async(np.array(x, copy=True),
                                           timeout=timeout)
        except Exception:
            return
        self._c[SHADOW].inc()
        fut.add_done_callback(lambda f: f.exception())  # swallow

    def submit(self, x, *, key: Optional[str] = None,
               timeout: Optional[float] = None,
               deadline=None,
               priority: Optional[str] = None) -> Tuple[Future, str, str]:
        """Route one request. Returns ``(future, target, version)`` where
        ``target`` is ``"primary"``/``"canary"`` and ``version`` the
        model version of the backend that owns the response.
        ``priority`` is forwarded to the backend's admission controller."""
        x = np.asarray(x)
        if self.shadow is not None:
            self._mirror(x, timeout)
        target = self.assign(x, key=key)
        backend = self.canary if target == CANARY else self.primary
        # only forward priority when set: the documented backend duck
        # type is output_async(x, timeout=, deadline=)
        kw = {} if priority is None else {"priority": priority}
        fut = backend.output_async(x, timeout=timeout, deadline=deadline,
                                   **kw)
        self._c[target].inc()
        return fut, target, backend.model_version

    def output(self, x, *, key: Optional[str] = None,
               timeout: Optional[float] = None) -> np.ndarray:
        fut, _, _ = self.submit(x, key=key, timeout=timeout)
        return fut.result()
