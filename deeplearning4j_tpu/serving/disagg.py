"""Disaggregated LLM serving: prefill/decode split over the fabric.

Prefill and decode have opposite hardware appetites — prefill is one
big compute-bound batched forward, decode is a long memory-bound stream
of tiny steps — so co-hosting them makes prefill bursts spike decode
tail latency. This module splits them across hosts (ISSUE 17):

* :class:`PrefillEngine` — the prefill-tier engine. Runs the SAME
  bucketed prefill computation as
  :class:`~deeplearning4j_tpu.parallel.decode.DecodeEngine` (identical
  jit, identical seeded sampling of the first token, so the decode tier
  continues the stream token-identically) and returns a **handoff**: the
  prompt, the sampled first token, the sampling law, and the per-layer
  KV cache trimmed to the used positions.
* :func:`serialize_handoff` / :func:`deserialize_handoff` — the wire
  format: one JSON header line (prompt/sampling/tensor manifest) then
  the raw C-order tensor buffers concatenated. int8 caches ship their
  quantized planes + scale planes verbatim — the wire cost is the
  quantized cost.
* :class:`DisaggCoordinator` — the front-tier router. Implements the
  generator protocol (``submit() -> GenerationHandle``), so a
  :class:`~deeplearning4j_tpu.remote.server.JsonModelServer` takes it as
  ``generator=`` unchanged: each request POSTs
  ``/v1/disagg/prefill`` on a prefill host (least-inflight among
  breaker-closed targets, failover on error), ships the handoff bytes to
  a decode host's ``/v1/disagg/resume`` and re-emits the NDJSON token
  stream into the local handle. When every prefill target is down the
  request FALLS BACK to the decode host's own ``/v1/generate`` (unified
  prefill+decode there) — degraded latency, identical tokens, zero
  loss.

Failure semantics: per-target circuit breakers (open targets are
skipped, half-open targets probe with live traffic), prefill failover
walks every closed target before falling back, and a decode stream that
drops after the first token fails cleanly (partial tokens kept — the
same no-transparent-reopen law as
:class:`~deeplearning4j_tpu.remote.server.JsonRemoteInference`).
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.client import HTTPException
from typing import Callable, Dict, List, Optional, Sequence
from urllib import request as urllib_request
from urllib.error import HTTPError, URLError
from urllib.parse import urlparse, urlunparse

import numpy as np

import jax
import jax.numpy as jnp

from ..core.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    CircuitState,
    Deadline,
)
from ..generate.sampling import sample_tokens
from ..generate.session import GenerationSession
from ..obs.metrics import MetricsRegistry, get_registry

_engine_seq = itertools.count()
_coord_seq = itertools.count()

HANDOFF_VERSION = 1

_SAMPLING_KEYS = ("seed", "greedy", "temperature", "top_k", "top_p",
                  "max_tokens", "eos_id", "speculative_k")


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def serialize_handoff(handoff: dict) -> bytes:
    """Handoff dict -> bytes: one JSON header line (everything except the
    tensor data, plus an ordered tensor manifest), then the raw C-order
    buffers concatenated in manifest order."""
    tensors = []
    buffers = []
    for layer in sorted(handoff["layers"]):
        planes = handoff["layers"][layer]
        for key in sorted(planes):
            arr = np.ascontiguousarray(np.asarray(planes[key]))
            tensors.append({"layer": layer, "key": key,
                            "dtype": arr.dtype.name,
                            "shape": list(arr.shape)})
            buffers.append(arr.tobytes())
    header = {
        "version": HANDOFF_VERSION,
        "prompt": [int(t) for t in handoff["prompt"]],
        "first_token": int(handoff["first_token"]),
        "pos": int(handoff["pos"]),
        "cache_dtype": handoff.get("cache_dtype"),
        "sampling": handoff.get("sampling", {}),
        "tensors": tensors,
    }
    return json.dumps(header).encode() + b"\n" + b"".join(buffers)


def deserialize_handoff(data: bytes) -> dict:
    """Inverse of :func:`serialize_handoff` (zero-copy per tensor via
    ``np.frombuffer`` views over the payload)."""
    nl = data.index(b"\n")
    header = json.loads(data[:nl])
    if header.get("version") != HANDOFF_VERSION:
        raise ValueError(
            f"unsupported handoff version {header.get('version')!r}")
    layers: Dict[str, dict] = {}
    off = nl + 1
    for t in header["tensors"]:
        dt = np.dtype(t["dtype"])
        shape = tuple(int(s) for s in t["shape"])
        n = dt.itemsize * int(np.prod(shape, dtype=np.int64)) if shape \
            else dt.itemsize
        arr = np.frombuffer(data, dt, count=max(1, n // dt.itemsize),
                            offset=off).reshape(shape)
        off += n
        layers.setdefault(t["layer"], {})[t["key"]] = arr
    if off != len(data):
        raise ValueError(
            f"handoff payload size mismatch: consumed {off} of {len(data)}")
    return {
        "version": header["version"],
        "prompt": header["prompt"],
        "first_token": header["first_token"],
        "pos": header["pos"],
        "cache_dtype": header.get("cache_dtype"),
        "sampling": header.get("sampling", {}),
        "layers": layers,
    }


# ---------------------------------------------------------------------------
# prefill tier
# ---------------------------------------------------------------------------


class PrefillEngine:
    """Prefill-tier engine: the bucketed-prefill half of a
    :class:`~deeplearning4j_tpu.parallel.decode.DecodeEngine`, producing
    handoffs instead of decoding. The jitted prefill function and the
    seeded first-token sample are bit-for-bit the computation the decode
    engine runs locally, which is what makes the restored decode stream
    token-identical to an unbroken one."""

    role = "prefill"

    def __init__(self, model, *, max_len: int = 256,
                 cache_dtype: Optional[str] = None,
                 circuit_breaker: Optional[CircuitBreaker] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None,
                 name: Optional[str] = None) -> None:
        self.session = GenerationSession(model, max_len=max_len,
                                         cache_dtype=cache_dtype)
        self.cache_dtype = cache_dtype
        self.max_len = int(max_len)
        self.name = name or f"prefill-{next(_engine_seq)}"
        self._breaker = circuit_breaker or CircuitBreaker(clock=clock)
        self._row_template = self.session.decode_state(1)
        self._fns: dict = {}
        self._lock = threading.Lock()
        self._inflight = 0
        reg = registry if registry is not None else get_registry()
        pre = reg.counter(
            "dl4j_tpu_disagg_prefills_total",
            "Prefill-tier handoffs produced, by outcome",
            ("instance", "outcome"))
        self._c_pre = {o: pre.labels(self.name, o)
                       for o in ("completed", "failed")}
        self._h_prefill = reg.histogram(
            "dl4j_tpu_disagg_prefill_latency_seconds",
            "Prefill-tier bucketed prefill latency (admit to handoff)",
            ("instance",)).labels(self.name)

    def _prefill_fn(self, tb: int):
        # IDENTICAL computation to DecodeEngine._prefill_fn — any drift
        # here breaks cross-tier token identity
        key = ("prefill", tb)
        if key not in self._fns:
            sess = self.session
            model = sess.model

            def fn(params, state, row_carry, ids, lengths, seed, gflag,
                   temp, k, p):
                mask = (jnp.arange(tb, dtype=jnp.int32)[None, :]
                        < lengths[:, None]).astype(model.dtype)
                out, _, new_rnn = model.forward_pure(
                    params, state, sess._prep(ids), train=False, rng=None,
                    mask=mask, rnn_state=row_carry)
                logits = sess._logits(out)
                last = jnp.take_along_axis(
                    logits, (lengths - 1)[:, None, None].astype(jnp.int32),
                    axis=2)[:, :, 0]
                tok = sample_tokens(last, seed, jnp.zeros((1,), jnp.int32),
                                    gflag, temp, k, p)
                return new_rnn, tok[0]

            self._fns[key] = jax.jit(fn)
        return self._fns[key]

    def prefill(self, prompt: Sequence[int], *,
                max_tokens: Optional[int] = None, greedy: bool = True,
                temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                seed: int = 0, eos_id: Optional[int] = None,
                speculative_k: Optional[int] = None) -> dict:
        """Run the bucketed prefill + first-token sample and return the
        handoff dict (cache planes trimmed to the ``len(prompt)`` used
        positions — the only part of the row worth shipping)."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) >= self.max_len:
            raise ValueError(
                f"prompt length {len(prompt)} >= max_len {self.max_len} — "
                "no room to generate")
        if self._breaker.state is CircuitState.OPEN:
            raise CircuitOpenError(retry_after=self._breaker.retry_after())
        with self._lock:
            self._inflight += 1
        t0 = time.perf_counter()
        try:
            sess = self.session
            tb = min(next(s for s in sess.bucket_sizes()
                          if s >= len(prompt)), self.max_len)
            ids = np.zeros((1, tb), np.int32)
            ids[0, : len(prompt)] = prompt
            row, tok = self._prefill_fn(tb)(
                sess.model.params, sess.model.state, self._row_template,
                jnp.asarray(ids), jnp.asarray([len(prompt)], jnp.int32),
                jnp.asarray([int(seed) & 0xFFFFFFFF], jnp.uint32),
                jnp.asarray([bool(greedy)], bool),
                jnp.asarray([float(temperature)], jnp.float32),
                jnp.asarray([int(top_k)], jnp.int32),
                jnp.asarray([float(top_p)], jnp.float32))
            pos = len(prompt)
            layers: Dict[str, dict] = {}
            for lname, st in row.items():
                planes = {}
                for key, v in st.items():
                    if key == "pos":
                        continue
                    planes[key] = np.asarray(v)[:, :, :pos]
                if planes:
                    layers[lname] = planes
            handoff = {
                "version": HANDOFF_VERSION,
                "prompt": prompt,
                "first_token": int(tok),
                "pos": pos,
                "cache_dtype": self.cache_dtype,
                "sampling": {
                    "seed": int(seed) & 0xFFFFFFFF, "greedy": bool(greedy),
                    "temperature": float(temperature), "top_k": int(top_k),
                    "top_p": float(top_p), "max_tokens": max_tokens,
                    "eos_id": eos_id, "speculative_k": speculative_k,
                },
                "layers": layers,
            }
            self._breaker.record_success()
            self._c_pre["completed"].inc()
            self._h_prefill.observe(time.perf_counter() - t0)
            return handoff
        except ValueError:
            raise  # malformed input is the caller's fault, not a fault
        except Exception:
            self._breaker.record_failure()
            self._c_pre["failed"].inc()
            raise
        finally:
            with self._lock:
                self._inflight -= 1

    # ----- server protocol surface ------------------------------------
    @property
    def circuit_state(self) -> CircuitState:
        return self._breaker.state

    def load_score(self) -> float:
        with self._lock:
            return float(self._inflight)

    def stats(self) -> dict:
        with self._lock:
            inflight = self._inflight
        return {
            "role": self.role,
            "queue_depth": inflight,
            "in_flight": inflight,
            "max_len": self.max_len,
            "cache_dtype": (self.cache_dtype
                            or str(self.session.model.dtype)),
            "prefills": {o: int(c.value) for o, c in self._c_pre.items()},
            "circuit_state": self._breaker.state.value,
        }


# ---------------------------------------------------------------------------
# front tier
# ---------------------------------------------------------------------------


class _Target:
    """One remote host in a role group: base URL + breaker + inflight."""

    __slots__ = ("name", "base", "breaker", "inflight")

    def __init__(self, endpoint: str, breaker: CircuitBreaker) -> None:
        u = urlparse(endpoint)
        if not u.scheme or not u.netloc:
            raise ValueError(
                f"endpoint must be an absolute URL, got {endpoint!r}")
        self.base = f"{u.scheme}://{u.netloc}"
        self.name = u.netloc
        self.breaker = breaker
        self.inflight = 0

    def url(self, path: str) -> str:
        u = urlparse(self.base)
        return urlunparse((u.scheme, u.netloc, path, "", "", ""))


def _generation_handle(request_id, deadline):
    # lazy: parallel.decode must stay importable without serving
    from ..parallel.decode import GenerationHandle

    return GenerationHandle(request_id, deadline)


class DisaggCoordinator:
    """Front-tier router for a disaggregated prefill/decode pipeline.

    Generator-protocol compatible (``submit``/``stats``/``load_score``/
    ``circuit_state``/``drain``/``shutdown``), so a
    :class:`~deeplearning4j_tpu.remote.server.JsonModelServer` serves it
    as ``generator=`` and ``POST /v1/generate`` transparently becomes a
    two-hop pipeline. Target selection is least-inflight among
    breaker-closed hosts; every closed prefill host is tried before the
    unified fallback on the decode host."""

    role = "coordinator"

    def __init__(self, prefill_endpoints: Sequence[str],
                 decode_endpoints: Sequence[str], *,
                 timeout: float = 30.0,
                 connect_timeout: float = 2.0,
                 workers: int = 8,
                 clock: Callable[[], float] = time.monotonic,
                 breaker_factory: Optional[Callable[[], CircuitBreaker]]
                 = None,
                 registry: Optional[MetricsRegistry] = None,
                 name: Optional[str] = None) -> None:
        if not decode_endpoints:
            raise ValueError("need at least one decode endpoint")
        mk = breaker_factory or (lambda: CircuitBreaker(clock=clock))
        self.prefill_targets = [_Target(e, mk()) for e in prefill_endpoints]
        self.decode_targets = [_Target(e, mk()) for e in decode_endpoints]
        self.default_timeout = float(timeout)
        self.connect_timeout = float(connect_timeout)
        self._clock = clock
        self.name = name or f"disagg-{next(_coord_seq)}"
        self._lock = threading.Lock()
        self._shutdown = False
        self._draining = False
        self._inflight = 0
        self._executor = ThreadPoolExecutor(
            max_workers=max(1, int(workers)),
            thread_name_prefix=f"{self.name}-hop")
        reg = registry if registry is not None else get_registry()
        ho = reg.counter(
            "dl4j_tpu_disagg_handoffs_total",
            "Disaggregated requests by outcome: completed = two-hop "
            "pipeline, fallback = unified decode-host generate, failed = "
            "no path produced a stream",
            ("instance", "outcome"))
        self._c_handoff = {o: ho.labels(self.name, o)
                           for o in ("completed", "fallback", "failed")}
        self._c_fallback = reg.counter(
            "dl4j_tpu_disagg_fallback_total",
            "Requests that fell back to the decode host's unified "
            "/v1/generate because no prefill target could serve",
            ("instance",)).labels(self.name)
        self._h_bytes = reg.histogram(
            "dl4j_tpu_disagg_handoff_bytes",
            "Serialized handoff size shipped prefill -> decode",
            ("instance",),
            buckets=(1e3, 1e4, 1e5, 1e6, 1e7, 1e8)).labels(self.name)
        self._h_first = reg.histogram(
            "dl4j_tpu_disagg_first_token_seconds",
            "Submit to first token through the two-hop pipeline",
            ("instance",)).labels(self.name)

    # ----- target selection -------------------------------------------
    def _candidates(self, targets: List[_Target]) -> List[_Target]:
        """Breaker-closed (or probing half-open) targets, least-inflight
        first; open targets excluded entirely."""
        with self._lock:
            avail = [t for t in targets
                     if t.breaker.state is not CircuitState.OPEN]
            return sorted(avail, key=lambda t: t.inflight)

    def _track(self, t: _Target, delta: int) -> None:
        with self._lock:
            t.inflight += delta

    # ----- HTTP hops ---------------------------------------------------
    def _post(self, url: str, body: bytes, content_type: str,
              deadline: Deadline, priority: Optional[str],
              request_id: Optional[str]):
        rem = deadline.remaining()
        if rem is not None and rem <= 0:
            raise TimeoutError("deadline exceeded before dispatch")
        headers = {"Content-Type": content_type}
        if rem is not None:
            headers["X-Deadline-Ms"] = str(int(rem * 1000))
        if priority:
            headers["X-Priority"] = priority
        if request_id:
            headers["X-Request-Id"] = request_id
        req = urllib_request.Request(url, data=body, headers=headers)
        return urllib_request.urlopen(
            req, timeout=rem if rem is not None else self.default_timeout)

    def _run_prefill(self, payload: dict, deadline: Deadline,
                     priority: Optional[str],
                     request_id: Optional[str]) -> Optional[bytes]:
        """POST the prefill hop on the best closed target, failing over
        across all of them. None = no prefill target could serve (the
        caller falls back); malformed-input 400s raise instead."""
        body = json.dumps(payload).encode()
        for t in self._candidates(self.prefill_targets):
            self._track(t, 1)
            try:
                with self._post(t.url("/v1/disagg/prefill"), body,
                                "application/json", deadline, priority,
                                request_id) as resp:
                    data = resp.read()
                t.breaker.record_success()
                self._h_bytes.observe(len(data))
                return data
            except HTTPError as e:
                detail = ""
                try:
                    detail = json.loads(e.read()).get("error", "")
                except Exception:
                    pass
                if e.code == 400:
                    raise ValueError(detail or "bad request") from e
                t.breaker.record_failure()
            except (URLError, ConnectionError, HTTPException, OSError,
                    TimeoutError):
                t.breaker.record_failure()
            finally:
                self._track(t, -1)
        return None

    def _stream_into(self, resp, handle, t: _Target) -> str:
        """Re-emit a host's NDJSON token stream into the local handle.
        Returns the terminal reason; raises on a drop mid-stream."""
        emitted = 0
        for line in resp:
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            if "token" in ev:
                handle._emit(int(ev["index"]), int(ev["token"]))
                emitted += 1
            if ev.get("done"):
                reason = str(ev.get("reason", "completed"))
                handle._finish(reason, error=ev.get("error"))
                t.breaker.record_success()
                return reason
            if handle.cancelled:
                raise _ClientCancelled()
        raise PartialHandoffError(
            f"decode stream ended without a done event after {emitted} "
            f"tokens")

    def _run_decode(self, data: bytes, handle, deadline: Deadline,
                    priority: Optional[str],
                    request_id: Optional[str]) -> bool:
        """Ship handoff bytes to a decode host and stream tokens back.
        Failover only before the first byte; a drop mid-stream fails the
        handle (never transparently re-opens — that would re-emit)."""
        for t in self._candidates(self.decode_targets):
            self._track(t, 1)
            started = False
            try:
                with self._post(t.url("/v1/disagg/resume"), data,
                                "application/octet-stream", deadline,
                                priority, request_id) as resp:
                    started = True
                    self._stream_into(resp, handle, t)
                return True
            except HTTPError as e:
                detail = ""
                try:
                    detail = json.loads(e.read()).get("error", "")
                except Exception:
                    pass
                if e.code == 400:
                    raise ValueError(detail or "bad request") from e
                t.breaker.record_failure()
            except _ClientCancelled:
                handle._finish("cancelled")
                return True
            except (URLError, ConnectionError, HTTPException, OSError,
                    TimeoutError, PartialHandoffError, ValueError) as e:
                t.breaker.record_failure()
                if started and handle.tokens:
                    # tokens already escaped to the consumer: terminal
                    handle._finish("failed",
                                   error=f"decode stream dropped: {e}")
                    return True
            finally:
                self._track(t, -1)
        return False

    def _run_fallback(self, payload: dict, handle, deadline: Deadline,
                      priority: Optional[str],
                      request_id: Optional[str]) -> bool:
        """Unified fallback: the decode host prefills AND decodes via its
        own /v1/generate. Slower first token, identical stream."""
        body = json.dumps(dict(payload, stream=True)).encode()
        for t in self._candidates(self.decode_targets):
            self._track(t, 1)
            started = False
            try:
                with self._post(t.url("/v1/generate"), body,
                                "application/json", deadline, priority,
                                request_id) as resp:
                    started = True
                    self._stream_into(resp, handle, t)
                self._c_fallback.inc()
                return True
            except HTTPError as e:
                detail = ""
                try:
                    detail = json.loads(e.read()).get("error", "")
                except Exception:
                    pass
                if e.code == 400:
                    raise ValueError(detail or "bad request") from e
                t.breaker.record_failure()
            except _ClientCancelled:
                handle._finish("cancelled")
                return True
            except (URLError, ConnectionError, HTTPException, OSError,
                    TimeoutError, PartialHandoffError, ValueError) as e:
                t.breaker.record_failure()
                if started and handle.tokens:
                    handle._finish("failed",
                                   error=f"fallback stream dropped: {e}")
                    return True
            finally:
                self._track(t, -1)
        return False

    # ----- generator protocol -----------------------------------------
    def submit(self, prompt: Sequence[int], *,
               max_tokens: Optional[int] = None, greedy: bool = True,
               temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
               seed: int = 0, eos_id: Optional[int] = None,
               timeout: Optional[float] = None,
               deadline: Optional[Deadline] = None,
               request_id: Optional[str] = None,
               priority: Optional[str] = None,
               speculative_k: Optional[int] = None):
        """Admit one request into the two-hop pipeline; returns a
        streaming :class:`~deeplearning4j_tpu.parallel.decode.
        GenerationHandle` immediately."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        with self._lock:
            if self._shutdown or self._draining:
                raise RuntimeError(
                    "DisaggCoordinator is shut down" if self._shutdown
                    else "DisaggCoordinator is draining")
            self._inflight += 1
        if deadline is None:
            deadline = Deadline.after(
                timeout if timeout is not None else self.default_timeout,
                clock=self._clock)
        handle = _generation_handle(request_id or f"{self.name}-req",
                                    deadline)
        payload = {"prompt": prompt, "greedy": bool(greedy),
                   "temperature": float(temperature), "top_k": int(top_k),
                   "top_p": float(top_p), "seed": int(seed)}
        if max_tokens is not None:
            payload["max_tokens"] = int(max_tokens)
        if eos_id is not None:
            payload["eos_id"] = int(eos_id)
        if speculative_k is not None:
            payload["speculative_k"] = int(speculative_k)
        t_submit = time.perf_counter()

        def run():
            try:
                data = None
                if not handle.cancelled:
                    data = self._run_prefill(payload, deadline, priority,
                                             request_id)
                if handle.cancelled:
                    handle._finish("cancelled")
                    return
                if data is not None:
                    if self._run_decode(data, handle, deadline, priority,
                                        request_id):
                        if handle.tokens:
                            self._h_first.observe(
                                time.perf_counter() - t_submit)
                        self._c_handoff["completed"].inc()
                        return
                # no prefill target, or every decode resume failed before
                # a byte: unified fallback on the decode hosts
                if self._run_fallback(payload, handle, deadline, priority,
                                      request_id):
                    self._c_handoff["fallback"].inc()
                    return
                self._c_handoff["failed"].inc()
                handle._finish(
                    "failed",
                    error="no prefill or decode target could serve")
            except Exception as e:  # noqa: BLE001 — terminal per-request
                self._c_handoff["failed"].inc()
                if not handle.done:
                    handle._finish("failed", error=str(e))
            finally:
                with self._lock:
                    self._inflight -= 1

        self._executor.submit(run)
        return handle

    def generate(self, prompt: Sequence[int], **kw) -> List[int]:
        return self.submit(prompt, **kw).result()

    # ----- protocol surface -------------------------------------------
    @property
    def circuit_state(self) -> CircuitState:
        """Aggregate over DECODE targets (the tier that must be up for
        any request to finish): closed while any is closed."""
        rank = {CircuitState.CLOSED: 0, CircuitState.HALF_OPEN: 1,
                CircuitState.OPEN: 2}
        return min((t.breaker.state for t in self.decode_targets),
                   key=rank.__getitem__)

    def load_score(self) -> float:
        with self._lock:
            return float(self._inflight)

    def stats(self) -> dict:
        with self._lock:
            inflight = self._inflight
        return {
            "queue_depth": inflight,
            "in_flight": inflight,
            "handoffs": {o: int(c.value)
                         for o, c in self._c_handoff.items()},
            "fallbacks": int(self._c_fallback.value),
            "roles": {
                **{f"prefill:{t.name}": t.breaker.state.value
                   for t in self.prefill_targets},
                **{f"decode:{t.name}": t.breaker.state.value
                   for t in self.decode_targets},
            },
            "circuit_state": self.circuit_state.value,
            "draining": self._draining,
        }

    def drain(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            self._draining = True
        deadline = None if timeout is None else self._clock() + timeout
        while True:
            with self._lock:
                if self._inflight == 0:
                    return True
            if deadline is not None and self._clock() >= deadline:
                return False
            time.sleep(0.01)

    def shutdown(self, *, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        if drain:
            self.drain(timeout)
        with self._lock:
            self._shutdown = True
        self._executor.shutdown(wait=False)


class _ClientCancelled(Exception):
    """Internal: the local consumer cancelled mid-stream."""


class PartialHandoffError(RuntimeError):
    """A decode-host stream ended without its terminal event."""
