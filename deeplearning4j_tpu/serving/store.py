"""Versioned on-disk model artifact store.

The servable lifecycle — versioned artifacts, integrity checks, retention
— is a first-class subsystem in production serving stacks (PAPERS.md:
the TF-Serving style servable/session management in "TensorFlow: A
system for large-scale machine learning"). :class:`ModelStore` is that
subsystem over the single-file format of
:mod:`~deeplearning4j_tpu.model.serializer`:

* **Monotonic versions per model name.** ``publish`` assigns ``v1, v2,
  ...``; a version directory is immutable once committed.
* **Atomic publish.** The artifact and its manifest are staged in a temp
  directory inside the model directory, fsynced, then ``os.replace``d to
  the final ``v<N>`` path — a crash mid-publish leaves no half-written
  version visible to ``resolve`` (stale staging dirs are swept by
  :meth:`gc`).
* **Integrity.** Each version's ``manifest.json`` records the SHA-256
  and size of ``model.zip``; :meth:`load` verifies it before
  deserializing, so bit-rot or a torn copy surfaces as
  :class:`ChecksumMismatchError` instead of a corrupt model.
* **Retention.** :meth:`gc` keeps the newest ``keep_last`` versions,
  never deletes the latest or any version in ``in_use`` (the versions a
  :class:`~deeplearning4j_tpu.serving.manager.ModelManager` still has
  resident for rollback/pinning).

Store layout::

    <root>/<model_name>/v<N>/model.zip
    <root>/<model_name>/v<N>/manifest.json

Concurrency: safe for many threads in one process (a per-store lock
serializes version assignment). Multi-writer publishes from *separate
processes* to one store are not coordinated — front them with a single
publisher, as a production registry would.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence, Union

from .. import __version__
from ..model.serializer import restore_model, write_model

_ARTIFACT = "model.zip"
_MANIFEST = "manifest.json"
_VDIR_RE = re.compile(r"^v(\d+)$")
_STAGING_PREFIX = ".staging-"

LATEST = "latest"


class ModelStoreError(RuntimeError):
    """Base class for registry failures."""


class VersionNotFoundError(ModelStoreError, KeyError):
    """The requested model name / version is not in the store."""

    # KeyError.__str__ repr-quotes the message; keep plain text
    __str__ = BaseException.__str__


class ChecksumMismatchError(ModelStoreError):
    """The artifact's bytes do not match the manifest's SHA-256."""


def _sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class ModelVersion:
    """One committed (name, version) entry: paths + manifest view."""

    __slots__ = ("name", "version", "path", "manifest")

    def __init__(self, name: str, version: int, path: str,
                 manifest: Dict) -> None:
        self.name = name
        self.version = int(version)
        self.path = path
        self.manifest = manifest

    @property
    def artifact_path(self) -> str:
        return os.path.join(self.path, _ARTIFACT)

    @property
    def sha256(self) -> str:
        return self.manifest["sha256"]

    @property
    def metadata(self) -> Dict:
        return self.manifest.get("metadata") or {}

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"ModelVersion({self.name!r}, v{self.version})"


def _coerce_version(version: Union[int, str]) -> Optional[int]:
    """``"latest"`` -> None; ``3`` / ``"3"`` / ``"v3"`` -> 3."""
    if isinstance(version, str):
        v = version.strip().lower()
        if v == LATEST:
            return None
        if v.startswith("v"):
            v = v[1:]
        if not v.isdigit():
            raise VersionNotFoundError(f"unparseable version {version!r}")
        return int(v)
    return int(version)


class ModelStore:
    def __init__(self, root: str, *, keep_last: Optional[int] = None) -> None:
        self.root = os.path.abspath(root)
        self.keep_last = keep_last
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()

    # ---- enumeration --------------------------------------------------
    def models(self) -> List[str]:
        out = []
        for entry in sorted(os.listdir(self.root)):
            if os.path.isdir(os.path.join(self.root, entry)) and \
                    not entry.startswith("."):
                out.append(entry)
        return out

    def _model_dir(self, name: str) -> str:
        if not name or "/" in name or os.sep in name or name.startswith("."):
            raise ModelStoreError(f"invalid model name {name!r}")
        return os.path.join(self.root, name)

    def _version_ids(self, name: str) -> List[int]:
        mdir = self._model_dir(name)
        if not os.path.isdir(mdir):
            return []
        ids = []
        for entry in os.listdir(mdir):
            m = _VDIR_RE.match(entry)
            # only committed versions count: a staged dir has no manifest
            if m and os.path.exists(os.path.join(mdir, entry, _MANIFEST)):
                ids.append(int(m.group(1)))
        return sorted(ids)

    def versions(self, name: str) -> List[ModelVersion]:
        return [self._entry(name, v) for v in self._version_ids(name)]

    def _entry(self, name: str, version: int) -> ModelVersion:
        vdir = os.path.join(self._model_dir(name), f"v{version}")
        mpath = os.path.join(vdir, _MANIFEST)
        if not os.path.exists(mpath):
            raise VersionNotFoundError(f"{name} v{version} not in store")
        with open(mpath) as f:
            manifest = json.load(f)
        return ModelVersion(name, version, vdir, manifest)

    def resolve(self, name: str,
                version: Union[int, str] = LATEST) -> ModelVersion:
        """Pinned or ``"latest"`` lookup of a committed version."""
        want = _coerce_version(version)
        if want is None:
            ids = self._version_ids(name)
            if not ids:
                raise VersionNotFoundError(f"no versions of {name!r} in store")
            want = ids[-1]
        return self._entry(name, want)

    # ---- publish ------------------------------------------------------
    def publish(self, name: str, model, *, save_updater: bool = False,
                normalizer=None,
                metadata: Optional[Dict] = None) -> ModelVersion:
        """Serialize ``model`` as the next version of ``name``. Atomic:
        the version appears in the store fully-formed or not at all."""
        mdir = self._model_dir(name)
        os.makedirs(mdir, exist_ok=True)
        with self._lock:
            ids = self._version_ids(name)
            version = (ids[-1] + 1) if ids else 1
            final = os.path.join(mdir, f"v{version}")
            staging = tempfile.mkdtemp(prefix=_STAGING_PREFIX, dir=mdir)
            try:
                artifact = os.path.join(staging, _ARTIFACT)
                write_model(model, artifact, save_updater=save_updater,
                            normalizer=normalizer)
                manifest = {
                    "model_name": name,
                    "version": version,
                    "sha256": _sha256_file(artifact),
                    "size_bytes": os.path.getsize(artifact),
                    "created_unix": time.time(),
                    "model_class": type(model).__name__,
                    "framework": "deeplearning4j_tpu",
                    "framework_version": __version__,
                    "metadata": metadata or {},
                }
                mpath = os.path.join(staging, _MANIFEST)
                with open(mpath, "w") as f:
                    json.dump(manifest, f, indent=2, sort_keys=True)
                    f.flush()
                    os.fsync(f.fileno())
                _fsync_dir(staging)
                os.replace(staging, final)
                _fsync_dir(mdir)
            except BaseException:
                shutil.rmtree(staging, ignore_errors=True)
                raise
        return ModelVersion(name, version, final, manifest)

    # ---- load ---------------------------------------------------------
    def verify(self, entry: ModelVersion) -> None:
        """Raise :class:`ChecksumMismatchError` unless the artifact bytes
        match the manifest recorded at publish time."""
        actual = _sha256_file(entry.artifact_path)
        if actual != entry.sha256:
            raise ChecksumMismatchError(
                f"{entry.name} v{entry.version}: artifact sha256 {actual} "
                f"!= manifest {entry.sha256} — artifact corrupt or "
                f"tampered; refusing to load")

    def load(self, name: str, version: Union[int, str] = LATEST, *,
             load_updater: bool = False, verify: bool = True):
        """Resolve + integrity-check + deserialize. Returns
        ``(model, ModelVersion)``."""
        entry = self.resolve(name, version)
        if verify:
            self.verify(entry)
        model = restore_model(entry.artifact_path, load_updater=load_updater)
        return model, entry

    # ---- retention / GC ----------------------------------------------
    def delete(self, name: str, version: Union[int, str]) -> None:
        entry = self.resolve(name, version)
        shutil.rmtree(entry.path)

    def gc(self, name: Optional[str] = None, *,
           keep_last: Optional[int] = None,
           in_use: Sequence[int] = ()) -> Dict[str, List[int]]:
        """Apply the retention policy: per model, keep the newest
        ``keep_last`` committed versions (default: the store's policy;
        ``None`` keeps everything). The latest version and any version in
        ``in_use`` are never collected. Stale staging directories from
        crashed publishes are always swept. Returns
        ``{model_name: [removed version ids]}``."""
        keep = keep_last if keep_last is not None else self.keep_last
        protected = {int(v) for v in in_use}
        removed: Dict[str, List[int]] = {}
        names = [name] if name is not None else self.models()
        with self._lock:
            for n in names:
                mdir = self._model_dir(n)
                if not os.path.isdir(mdir):
                    continue
                for entry in os.listdir(mdir):
                    if entry.startswith(_STAGING_PREFIX):
                        shutil.rmtree(os.path.join(mdir, entry),
                                      ignore_errors=True)
                ids = self._version_ids(n)
                if keep is None or len(ids) <= keep:
                    continue
                doomed = [v for v in ids[:-max(keep, 1)]
                          if v not in protected]
                for v in doomed:
                    shutil.rmtree(os.path.join(mdir, f"v{v}"))
                    removed.setdefault(n, []).append(v)
        return removed
