"""Multi-tenant model multiplexing: weight paging under a byte budget.

The ROADMAP's multi-tenancy item: one serving host should front **more
models than its HBM can hold warm** — residency becomes a managed,
observable resource instead of a hard cap (the serving-side recast of
DL4J's ``ParallelWrapper`` fleet idea, PAPER.md survey; placement-as-
policy from "TensorFlow: A system for large-scale machine learning" and
the resilience framing of the TPU-generations survey, PAPERS.md).

Three cooperating pieces:

* :class:`ModelMultiplexer` — an LRU/cost-aware **residency manager**
  over :class:`~.store.ModelStore` + :class:`~.manager.ModelManager`.
  Registered models start cold (a registration records only the deploy
  spec — version, rewrite pipeline, engine knobs); the first request
  pages a model in (build + warm through the ordinary deploy path) and
  accounts its actual resident bytes (params + state leaves, so an
  ``optimize="inference:int8"`` deploy pages in at its quantized size —
  roughly 4× smaller than f32). When a page-in would exceed
  ``budget_bytes``, warm victims are parked in **LRU order with the
  request-rate EWMA as tie-break** (:meth:`ModelManager.park` drains
  first, so eviction never corrupts an in-flight request). Misses on a
  cold model are **queued with a bounded page-in deadline** — concurrent
  requests for the same cold model wait on one page-in — instead of
  being 503'd; the deadline exhausting sheds with Retry-After.
  :meth:`tick` recomputes per-model request-rate EWMAs from the metrics
  registry's request counters, and :meth:`prefetch` pages the
  hottest-by-EWMA cold models back in while they fit WITHOUT evicting
  anyone (prediction must never displace observed traffic).
* **Per-tenant SLO scheduling** — ``tenants=`` maps an ``X-Tenant``
  header value to an admission priority class (the PR-10 weighted
  window/bucket classes on this multiplexer's
  :class:`~deeplearning4j_tpu.core.resilience.AdmissionController`) and
  a per-tenant page-in deadline: paying tenants shed last AND wait
  longest for a cold model; unknown tenants get the default policy.
* :class:`PoolAutoscaler` — grows/shrinks an
  :class:`~deeplearning4j_tpu.parallel.pool.EnginePool`'s replica count
  from the load-score-per-replica EWMA trend
  (:meth:`~deeplearning4j_tpu.parallel.pool.EnginePool.add_replica` /
  :meth:`~deeplearning4j_tpu.parallel.pool.EnginePool.remove_replica`,
  drain-before-remove). ``spawn=`` builds the new replica — return a
  :class:`~deeplearning4j_tpu.remote.replica.RemoteReplica` to grow
  across fabric hosts (PR 12's deploy fan-out keeps their versions in
  step); default is a local replica cloned from the pool's template.

Observability (README "Observability" table)::

  dl4j_tpu_serving_resident_models        gauge      warm models
  dl4j_tpu_serving_residency_bytes        gauge      resident weight bytes
  dl4j_tpu_serving_residency_budget_bytes gauge      configured budget
  dl4j_tpu_serving_pagein_seconds         histogram  cold-start page-in
  dl4j_tpu_serving_evictions_total        counter    park-for-budget, by model
  dl4j_tpu_serving_coldstart_misses_total counter    misses on cold models

Chaos contract: ``tools/check_multiplex_contract.py`` (tier-1) — more
registered models than the budget admits, hot tenants in-SLO during
cold-tenant page-in churn, zero requests lost to eviction,
kill-during-page-in recovery, byte-identical quantized unpark.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Union

import numpy as np

from ..core.resilience import (
    AdmissionController,
    AdmissionRejectedError,
    Deadline,
)
from ..obs.metrics import MetricsRegistry, get_registry
from .manager import ModelManager, ModelParkedError
from .store import LATEST, ModelStore, VersionNotFoundError


def model_bytes(model) -> int:
    """Resident device bytes of one model: ``size × itemsize`` summed
    over every params/state leaf — the same leaf-bytes arithmetic as
    :meth:`~deeplearning4j_tpu.generate.session.GenerationSession.
    cache_bytes` applies to decode state, applied to weights. A
    quantized (int8) graph reports its post-rewrite size, which is what
    actually occupies the device."""
    import jax

    leaves = jax.tree_util.tree_leaves(
        (getattr(model, "params", None), getattr(model, "state", None)))
    return sum(int(leaf.size) * leaf.dtype.itemsize
               for leaf in leaves if hasattr(leaf, "dtype"))


class _Slot:
    """Per-registered-model residency record. ``state`` transitions:
    parked -> paging -> warm -> parking -> parked. The transitional
    states are held by exactly one thread (the pager / the evictor);
    everyone else waits on the multiplexer's condition variable."""

    __slots__ = ("name", "spec", "manager", "state", "last_used", "ewma",
                 "bytes", "last_count")

    def __init__(self, name: str, spec: Dict) -> None:
        self.name = name
        self.spec = spec
        self.manager: Optional[ModelManager] = None
        self.state = "parked"
        self.last_used = 0.0
        self.ewma = 0.0        # request-rate EWMA (req/s), tick()-updated
        self.bytes = 0         # measured resident bytes while warm
        self.last_count = 0    # request-counter value at the last tick


class ModelMultiplexer:
    """N registered models behind one submit surface on a fixed byte
    budget (see module docstring). Thread-safe; page-ins and evictions
    run outside the accounting lock, so hot-model traffic never blocks
    behind a cold model's compile."""

    def __init__(
        self,
        store: ModelStore,
        *,
        budget_bytes: int,
        priorities: Optional[Dict[str, float]] = None,
        tenants: Optional[Dict[str, Dict]] = None,
        default_pagein_deadline_s: float = 30.0,
        max_pending: int = 256,
        ewma_halflife_s: float = 60.0,
        drain_timeout: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        registry: Optional[MetricsRegistry] = None,
        fault_injector=None,
        name: Optional[str] = None,
        manager_defaults: Optional[Dict] = None,
    ) -> None:
        if budget_bytes <= 0:
            raise ValueError("budget_bytes must be > 0")
        self.store = store
        self.budget_bytes = int(budget_bytes)
        self.name = name or "mux"
        self._clock = clock
        self._fault_injector = fault_injector
        self._halflife = float(ewma_halflife_s)
        self._drain_timeout = float(drain_timeout)
        self._default_pagein_deadline_s = float(default_pagein_deadline_s)
        self._manager_defaults = dict(manager_defaults or {})
        # tenant -> {"priority": class-name, "pagein_deadline_s": float}
        self._tenants = {t: dict(pol) for t, pol in (tenants or {}).items()}
        self._admission = AdmissionController(
            max_pending=max_pending, priorities=priorities, clock=clock)
        self.registry = registry if registry is not None else get_registry()

        self._slots: Dict[str, _Slot] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._last_tick = clock()
        self._shutdown = False

        reg = self.registry
        self._g_resident = reg.gauge(
            "dl4j_tpu_serving_resident_models",
            "Registered models currently warm (weights resident)",
            ("instance",)).labels(self.name)
        self._g_bytes = reg.gauge(
            "dl4j_tpu_serving_residency_bytes",
            "Resident model-weight bytes across warm models",
            ("instance",)).labels(self.name)
        self._g_budget = reg.gauge(
            "dl4j_tpu_serving_residency_budget_bytes",
            "Configured residency byte budget",
            ("instance",)).labels(self.name)
        self._g_budget.set(float(self.budget_bytes))
        self._h_pagein = reg.histogram(
            "dl4j_tpu_serving_pagein_seconds",
            "Cold-start page-in latency (store load + rewrite + engine "
            "build + warmup)", ("instance",)).labels(self.name)
        self._c_evict_family = reg.counter(
            "dl4j_tpu_serving_evictions_total",
            "Warm models parked to fit the byte budget", ("instance",
                                                          "model"))
        self._c_miss_family = reg.counter(
            "dl4j_tpu_serving_coldstart_misses_total",
            "Requests that arrived while their model was not warm",
            ("instance", "model"))
        self._c_req_family = reg.counter(
            "dl4j_tpu_serving_mux_requests_total",
            "Multiplexed requests by model (the EWMA/prefetch signal)",
            ("instance", "model"))
        self._c_tenant_family = reg.counter(
            "dl4j_tpu_serving_tenant_requests_total",
            "Multiplexed requests by tenant", ("instance", "tenant"))
        self._c_evict: Dict[str, object] = {}
        self._c_miss: Dict[str, object] = {}
        self._c_req: Dict[str, object] = {}
        self._c_tenant: Dict[str, object] = {}

    # ----- registration -----------------------------------------------
    def register(self, name: str, *, version: Union[int, str] = LATEST,
                 optimize: Union[str, list, None] = "inference",
                 warmup_example=None, **manager_kwargs) -> None:
        """Register a store-published model. Nothing is loaded now — the
        model is born parked and costs zero bytes until traffic (or
        :meth:`prefetch`) pages it in. ``optimize`` is the rewrite
        pipeline every page-in replays (``"inference:int8"`` keeps the
        model resident at its quantized size); extra kwargs go to the
        :class:`~.manager.ModelManager` built at first page-in."""
        self.store.resolve(name, version)  # fail registration, not traffic
        spec = dict(self._manager_defaults)
        spec.update(manager_kwargs)
        spec.update(version=version, optimize=optimize,
                    warmup_example=warmup_example)
        with self._lock:
            if name in self._slots:
                raise ValueError(f"model {name!r} is already registered")
            self._slots[name] = _Slot(name, spec)
            self._c_evict[name] = self._c_evict_family.labels(self.name,
                                                              name)
            self._c_miss[name] = self._c_miss_family.labels(self.name, name)
            self._c_req[name] = self._c_req_family.labels(self.name, name)

    def unregister(self, name: str, *,
                   drain_timeout: Optional[float] = 10.0) -> None:
        with self._lock:
            slot = self._slots.pop(name, None)
            if slot is None:
                return
            while slot.state in ("paging", "parking"):
                self._cond.wait(timeout=1.0)
            self._update_gauges_locked()
        if slot.manager is not None:
            slot.manager.shutdown(drain=True, drain_timeout=drain_timeout)

    def __contains__(self, name: str) -> bool:
        return name in self._slots

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    def manager(self, name: str) -> Optional[ModelManager]:
        """The model's manager while warm, else None (no side effects)."""
        slot = self._slots.get(name)
        with self._lock:
            return slot.manager if slot is not None \
                and slot.state == "warm" else None

    def state(self, name: str) -> str:
        """``warm`` | ``parked`` | ``paging`` (both transition
        directions report ``paging``)."""
        slot = self._slots[name]
        s = slot.state
        return "paging" if s in ("paging", "parking") else s

    # ----- residency accounting ---------------------------------------
    def _resident_bytes_locked(self) -> int:
        return sum(s.bytes for s in self._slots.values()
                   if s.state == "warm")

    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes_locked()

    def _update_gauges_locked(self) -> None:
        self._g_resident.set(float(sum(
            1 for s in self._slots.values() if s.state == "warm")))
        self._g_bytes.set(float(self._resident_bytes_locked()))

    def _estimate_bytes(self, slot: _Slot) -> int:
        """Page-in size estimate BEFORE loading: the last measured
        resident size when the model was warm before, else the store
        artifact size (≈ full-precision weight bytes — an overestimate
        for quantized pipelines, so eviction errs toward freeing more)."""
        if slot.bytes > 0:
            return slot.bytes
        try:
            entry = self.store.resolve(slot.name, slot.spec["version"])
            return int(entry.manifest.get("size_bytes") or 0)
        except VersionNotFoundError:
            return 0

    def _evict_for(self, need_bytes: int, exclude: _Slot) -> None:
        """Park warm models until ``need_bytes`` more fit under the
        budget. Victim order: least-recently-used first, lower
        request-rate EWMA breaking ties — recency is ground truth,
        prediction only arbitrates between equally-stale models. Runs
        the drains outside the lock; when every other model is already
        cold the budget is overcommitted rather than refusing to serve
        (one model must always be able to run)."""
        while True:
            with self._lock:
                if (self._resident_bytes_locked() + need_bytes
                        <= self.budget_bytes):
                    return
                victims = [s for s in self._slots.values()
                           if s.state == "warm" and s is not exclude]
                if not victims:
                    self.registry.log_event(
                        "residency_overcommit", mux=self.name,
                        model=exclude.name, need_bytes=need_bytes,
                        budget_bytes=self.budget_bytes)
                    return
                victim = min(victims, key=lambda s: (s.last_used, s.ewma))
                victim.state = "parking"
            victim.manager.park(drain_timeout=self._drain_timeout)
            with self._lock:
                victim.state = "parked"
                self._c_evict[victim.name].inc()
                self._update_gauges_locked()
                self._cond.notify_all()
            self.registry.log_event("model_evict", mux=self.name,
                                    model=victim.name,
                                    freed_bytes=victim.bytes)

    def park(self, name: str) -> bool:
        """Administratively page a model out (drain-first)."""
        slot = self._slots[name]
        with self._lock:
            while slot.state in ("paging", "parking"):
                self._cond.wait(timeout=1.0)
            if slot.state != "warm":
                return False
            slot.state = "parking"
        slot.manager.park(drain_timeout=self._drain_timeout)
        with self._lock:
            slot.state = "parked"
            self._update_gauges_locked()
            self._cond.notify_all()
        return True

    # ----- page-in -----------------------------------------------------
    def ensure_resident(self, name: str,
                        deadline: Optional[Deadline] = None,
                        allow_evict: bool = True) -> ModelManager:
        """Block until ``name`` is warm, paging it in if needed (evicting
        under the budget unless ``allow_evict=False`` — the prefetch
        mode). Concurrent callers coalesce onto one page-in. Deadline
        exhaustion while queued sheds with
        :class:`~deeplearning4j_tpu.core.resilience.
        AdmissionRejectedError` (→ 503 + Retry-After at the HTTP edge)."""
        slot = self._slots.get(name)
        if slot is None:
            raise VersionNotFoundError(f"model {name!r} is not registered")
        with self._lock:
            while True:
                if self._shutdown:
                    raise RuntimeError(f"{self.name} is shut down")
                if slot.state == "warm":
                    return slot.manager
                if slot.state in ("paging", "parking"):
                    rem = deadline.remaining() if deadline is not None \
                        else None
                    if deadline is not None and deadline.expired():
                        raise AdmissionRejectedError(
                            f"{name}: page-in queue deadline exhausted",
                            retry_after=1.0)
                    self._cond.wait(timeout=min(rem, 0.5)
                                    if rem is not None else 0.5)
                    continue
                slot.state = "paging"  # this thread is the pager
                break
        try:
            if allow_evict:
                self._evict_for(self._estimate_bytes(slot), exclude=slot)
            elif (self.resident_bytes() + self._estimate_bytes(slot)
                  > self.budget_bytes):
                raise AdmissionRejectedError(
                    f"{name}: no headroom to prefetch", retry_after=1.0)
            t0 = time.perf_counter()
            if slot.manager is None:
                spec = dict(slot.spec)
                slot.manager = ModelManager(
                    self.store, name, registry=self.registry,
                    clock=self._clock,
                    fault_injector=self._fault_injector, **spec)
            else:
                slot.manager.unpark()
            self._h_pagein.observe(time.perf_counter() - t0)
        except BaseException:
            with self._lock:
                slot.state = "parked"  # next request retries the page-in
                self._cond.notify_all()
            raise
        with self._lock:
            slot.bytes = slot.manager.resident_bytes()
            slot.state = "warm"
            slot.last_used = self._clock()
            self._update_gauges_locked()
            self._cond.notify_all()
        # the estimate can undershoot (first page-in of a model whose
        # artifact is smaller than its resident form): re-settle now
        if allow_evict:
            self._evict_for(0, exclude=slot)
        return slot.manager

    # ----- request path -------------------------------------------------
    def _policy(self, tenant: Optional[str]) -> Dict:
        if tenant is not None and tenant in self._tenants:
            return self._tenants[tenant]
        return {"priority": None,
                "pagein_deadline_s": self._default_pagein_deadline_s}

    def _touch(self, slot: _Slot, tenant: Optional[str]) -> None:
        with self._lock:
            slot.last_used = self._clock()
        self._c_req[slot.name].inc()
        if tenant:
            child = self._c_tenant.get(tenant)
            if child is None:
                child = self._c_tenant_family.labels(self.name, tenant)
                self._c_tenant[tenant] = child
            child.inc()

    def submit(self, name: str, x, *, tenant: Optional[str] = None,
               priority: Optional[str] = None, deadline=None,
               version=None, key: Optional[str] = None,
               timeout: Optional[float] = None):
        """Route one request to a registered model; returns ``(future,
        version_str)``. Cold model → counted miss, then queued behind
        the page-in up to the tenant's ``pagein_deadline_s`` (bounded
        further by the request deadline). A model evicted between the
        residency check and the engine submit retries transparently —
        eviction drains first, so the race costs a retry, never a lost
        request."""
        slot = self._slots.get(name)
        if slot is None:
            raise VersionNotFoundError(f"model {name!r} is not registered")
        pol = self._policy(tenant)
        prio = priority if priority is not None else pol.get("priority")
        self._touch(slot, tenant)
        self._admission.admit(prio)
        try:
            for _attempt in range(4):
                with self._lock:
                    mgr = slot.manager if slot.state == "warm" else None
                if mgr is None:
                    self._c_miss[name].inc()
                    mgr = self.ensure_resident(
                        name, deadline=self._pagein_deadline(pol, deadline))
                try:
                    fut, served = mgr.submit(
                        x, key=key, version=version, deadline=deadline,
                        timeout=timeout, priority=prio)
                except ModelParkedError:
                    continue  # evicted in the gap: page back in
                except RuntimeError as e:
                    if "drain" in str(e) or "shut down" in str(e):
                        continue  # drain raced the submit: retry
                    raise
                fut.add_done_callback(
                    lambda _f: self._admission.release())
                return fut, served
            raise AdmissionRejectedError(
                f"{name}: evicted repeatedly mid-submit (budget thrash)",
                retry_after=1.0)
        except BaseException:
            self._admission.release()
            raise

    def _pagein_deadline(self, pol: Dict, deadline) -> Deadline:
        budget_s = pol.get("pagein_deadline_s",
                           self._default_pagein_deadline_s)
        pagein = Deadline.after(budget_s, clock=self._clock)
        if deadline is not None:
            rem = deadline.remaining()
            if rem is not None and (pagein.remaining() is None
                                    or rem < pagein.remaining()):
                return deadline
        return pagein

    def output(self, name: str, x, **kw) -> np.ndarray:
        fut, _ = self.submit(name, x, **kw)
        return fut.result()

    # ----- EWMA / prefetch ----------------------------------------------
    def tick(self) -> Dict[str, float]:
        """Recompute per-model request-rate EWMAs from the registry's
        request counters (delta since the last tick / elapsed time,
        folded in with a half-life of ``ewma_halflife_s``). Call
        periodically (the bench/contract drive it manually; a serving
        loop can run it from any housekeeping thread)."""
        now = self._clock()
        with self._lock:
            dt = max(1e-9, now - self._last_tick)
            self._last_tick = now
            alpha = 1.0 - 0.5 ** (dt / self._halflife)
            out = {}
            for slot in self._slots.values():
                count = int(self._c_req[slot.name].value)
                rate = (count - slot.last_count) / dt
                slot.last_count = count
                slot.ewma = alpha * rate + (1.0 - alpha) * slot.ewma
                out[slot.name] = slot.ewma
            return out

    def prefetch(self, limit: int = 1) -> List[str]:
        """Page in up to ``limit`` cold models, hottest request-rate
        EWMA first, while they fit WITHOUT evicting anything. Failures
        (no headroom, load fault) skip the candidate — prefetch is a
        hint, never load-bearing."""
        with self._lock:
            candidates = sorted(
                (s for s in self._slots.values()
                 if s.state == "parked" and s.ewma > 0.0),
                key=lambda s: -s.ewma)[:max(0, int(limit))]
            names = [s.name for s in candidates]
        fetched = []
        for name in names:
            try:
                self.ensure_resident(name, allow_evict=False)
                fetched.append(name)
            except Exception:
                continue
        return fetched

    # ----- introspection / lifecycle ------------------------------------
    def load_score(self) -> float:
        score = float(self._admission.pending)
        with self._lock:
            warm = [s.manager for s in self._slots.values()
                    if s.state == "warm"]
        for mgr in warm:
            engine = mgr.engine
            if engine is not None and hasattr(engine, "load_score"):
                score += float(engine.load_score())
        return score

    def describe(self) -> Dict:
        with self._lock:
            models = {}
            for s in sorted(self._slots.values(), key=lambda s: s.name):
                st = "paging" if s.state in ("paging", "parking") \
                    else s.state
                models[s.name] = {
                    "residency": st,
                    "bytes": s.bytes if s.state == "warm" else 0,
                    "ewma_rps": round(s.ewma, 6),
                    "requests": int(self._c_req[s.name].value),
                    "evictions": int(self._c_evict[s.name].value),
                    "coldstart_misses": int(self._c_miss[s.name].value),
                }
                if s.manager is not None:
                    models[s.name]["live_version"] = \
                        s.manager.live_version
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self._resident_bytes_locked(),
                "resident_models": sum(
                    1 for s in self._slots.values() if s.state == "warm"),
                "registered_models": len(self._slots),
                "models": models,
            }

    def stats(self) -> Dict:
        out = self.describe()
        out["admission"] = self._admission.stats()
        return out

    def drain(self, timeout: Optional[float] = None) -> bool:
        ok = True
        with self._lock:
            warm = [s.manager for s in self._slots.values()
                    if s.state == "warm"]
        for mgr in warm:
            engine = mgr.engine
            if engine is not None and hasattr(engine, "drain"):
                ok = engine.drain(timeout=timeout) and ok
        return ok

    def shutdown(self, *, drain: bool = True,
                 drain_timeout: Optional[float] = 10.0) -> None:
        with self._lock:
            self._shutdown = True
            managers = [s.manager for s in self._slots.values()
                        if s.manager is not None]
            self._cond.notify_all()
        for mgr in managers:
            mgr.shutdown(drain=drain, drain_timeout=drain_timeout)


# --------------------------------------------------------------------------
# PoolAutoscaler
# --------------------------------------------------------------------------
class PoolAutoscaler:
    """Replica-count controller for one
    :class:`~deeplearning4j_tpu.parallel.pool.EnginePool`: each
    :meth:`tick` folds the pool's mean load score per replica into an
    EWMA and, outside a cooldown window, grows the pool when the trend
    is above ``high_load`` or shrinks it (drain-before-remove, the
    least-loaded replica) below ``low_load``. ``spawn=`` builds the new
    replica — return a ``RemoteReplica`` to scale across fabric hosts
    (the pool's deploy fan-out keeps versions in step); default clones
    the pool's local template via ``add_replica()``."""

    def __init__(self, pool, *, spawn: Optional[Callable[[], object]] = None,
                 min_replicas: int = 1, max_replicas: int = 8,
                 high_load: float = 2.0, low_load: float = 0.25,
                 halflife_s: float = 10.0, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[MetricsRegistry] = None) -> None:
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if low_load >= high_load:
            raise ValueError("low_load must be < high_load")
        self.pool = pool
        self._spawn = spawn
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.high_load = float(high_load)
        self.low_load = float(low_load)
        self._halflife = float(halflife_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._ewma: Optional[float] = None
        self._last_tick = clock()
        self._last_action = -float("inf")
        reg = registry if registry is not None else get_registry()
        fam = reg.counter(
            "dl4j_tpu_pool_autoscale_total",
            "Autoscaler replica-count changes by direction",
            ("pool", "action"))
        self._c_actions = {a: fam.labels(pool.name, a)
                           for a in ("grow", "shrink")}

    def tick(self) -> Dict:
        """One control step; returns the observation + action taken
        (``hold`` / ``grow`` / ``shrink`` / ``cooldown``)."""
        now = self._clock()
        replicas = list(self.pool.replicas)
        score = (sum(max(0.0, e.load_score()) for e in replicas)
                 / max(1, len(replicas)))
        dt = max(1e-9, now - self._last_tick)
        self._last_tick = now
        if self._ewma is None:
            self._ewma = score
        else:
            alpha = 1.0 - 0.5 ** (dt / self._halflife)
            self._ewma = alpha * score + (1.0 - alpha) * self._ewma
        obs = {"load_per_replica": score, "ewma": self._ewma,
               "replicas": len(replicas), "action": "hold"}
        if now - self._last_action < self.cooldown_s:
            obs["action"] = "cooldown"
            return obs
        if self._ewma > self.high_load and len(replicas) < self.max_replicas:
            engine = self._spawn() if self._spawn is not None else None
            self.pool.add_replica(engine)
            self._c_actions["grow"].inc()
            self._last_action = now
            obs["action"] = "grow"
            obs["replicas"] = len(self.pool.replicas)
        elif self._ewma < self.low_load and len(replicas) > self.min_replicas:
            victim = min(replicas, key=lambda e: e.load_score())
            self.pool.remove_replica(victim.name)
            self._c_actions["shrink"].inc()
            self._last_action = now
            obs["action"] = "shrink"
            obs["replicas"] = len(self.pool.replicas)
        return obs
