"""Arbiter — hyperparameter search.

Reference: arbiter (SURVEY.md §2.2): parameter spaces over configs,
random/grid candidate generation, local execution scoring candidates by
training + evaluating, result tracking.
"""

from .spaces import (
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    FixedValue,
    IntegerParameterSpace,
    ParameterSpace,
)
from .search import (
    CandidateResult,
    GridSearchGenerator,
    LocalOptimizationRunner,
    OptimizationConfiguration,
    RandomSearchGenerator,
)

__all__ = [
    "CandidateResult",
    "ContinuousParameterSpace",
    "DiscreteParameterSpace",
    "FixedValue",
    "GridSearchGenerator",
    "IntegerParameterSpace",
    "LocalOptimizationRunner",
    "OptimizationConfiguration",
    "ParameterSpace",
    "RandomSearchGenerator",
]
