"""Parameter spaces.

Reference: org.deeplearning4j.arbiter.optimize.api.ParameterSpace and the
concrete spaces (ContinuousParameterSpace, DiscreteParameterSpace,
IntegerParameterSpace, FixedValue).
"""

from __future__ import annotations

import math
from typing import Any, List, Sequence

import numpy as np


class ParameterSpace:
    """SPI: sample a value from [0,1)^n coordinates, or enumerate a grid."""

    def sample(self, rng: np.random.RandomState) -> Any:
        raise NotImplementedError

    def grid(self, resolution: int) -> List[Any]:
        """Discretization used by grid search."""
        raise NotImplementedError


class FixedValue(ParameterSpace):
    def __init__(self, value: Any) -> None:
        self.value = value

    def sample(self, rng) -> Any:
        return self.value

    def grid(self, resolution: int) -> List[Any]:
        return [self.value]


class ContinuousParameterSpace(ParameterSpace):
    """Uniform (or log-uniform) float range — log scale is the right prior
    for learning rates / regularization strengths."""

    def __init__(self, min_value: float, max_value: float,
                 log_scale: bool = False) -> None:
        if min_value >= max_value:
            raise ValueError("min must be < max")
        if log_scale and min_value <= 0:
            raise ValueError("log scale needs positive bounds")
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        self.log_scale = log_scale

    def sample(self, rng) -> float:
        if self.log_scale:
            lo, hi = math.log(self.min_value), math.log(self.max_value)
            return float(math.exp(rng.uniform(lo, hi)))
        return float(rng.uniform(self.min_value, self.max_value))

    def grid(self, resolution: int) -> List[float]:
        if self.log_scale:
            return list(np.exp(np.linspace(math.log(self.min_value),
                                           math.log(self.max_value),
                                           resolution)))
        return list(np.linspace(self.min_value, self.max_value, resolution))


class IntegerParameterSpace(ParameterSpace):
    def __init__(self, min_value: int, max_value: int) -> None:
        if min_value > max_value:
            raise ValueError("min must be <= max")
        self.min_value = int(min_value)
        self.max_value = int(max_value)

    def sample(self, rng) -> int:
        return int(rng.randint(self.min_value, self.max_value + 1))

    def grid(self, resolution: int) -> List[int]:
        span = self.max_value - self.min_value + 1
        if span <= resolution:
            return list(range(self.min_value, self.max_value + 1))
        return sorted({int(v) for v in np.linspace(
            self.min_value, self.max_value, resolution)})


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, values: Sequence[Any]) -> None:
        if not values:
            raise ValueError("empty value set")
        self.values = list(values)

    def sample(self, rng) -> Any:
        return self.values[rng.randint(len(self.values))]

    def grid(self, resolution: int) -> List[Any]:
        return list(self.values)
