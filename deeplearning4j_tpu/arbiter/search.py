"""Candidate generation + local optimization runner.

Reference: org.deeplearning4j.arbiter.optimize.{generator.
RandomSearchGenerator/GridSearchCandidateGenerator, config.
OptimizationConfiguration, runner.LocalOptimizationRunner}. A candidate is
a sampled {name: value} dict; the user's ``model_factory(hp)`` builds a
model from it (the Pythonic stand-in for MultiLayerSpace), a score
function rates it, and the runner tracks every result plus the best.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from .spaces import ParameterSpace


class CandidateGenerator:
    def __iter__(self) -> Iterator[Dict[str, Any]]:
        raise NotImplementedError


class RandomSearchGenerator(CandidateGenerator):
    def __init__(self, spaces: Dict[str, ParameterSpace],
                 num_candidates: int = 10, seed: int = 12345) -> None:
        self.spaces = dict(spaces)
        self.num_candidates = int(num_candidates)
        self.seed = seed

    def __iter__(self):
        rng = np.random.RandomState(self.seed)
        for _ in range(self.num_candidates):
            yield {k: s.sample(rng) for k, s in self.spaces.items()}


class GridSearchGenerator(CandidateGenerator):
    def __init__(self, spaces: Dict[str, ParameterSpace],
                 discretization: int = 3) -> None:
        self.spaces = dict(spaces)
        self.discretization = int(discretization)

    def __iter__(self):
        names = list(self.spaces)
        axes = [self.spaces[n].grid(self.discretization) for n in names]
        for combo in itertools.product(*axes):
            yield dict(zip(names, combo))


@dataclasses.dataclass
class CandidateResult:
    index: int
    hyperparameters: Dict[str, Any]
    score: float
    duration_s: float
    model: Any = None
    error: Optional[str] = None


@dataclasses.dataclass
class OptimizationConfiguration:
    """Reference: OptimizationConfiguration.Builder fields."""

    candidate_generator: CandidateGenerator
    model_factory: Callable[[Dict[str, Any]], Any]
    score_function: Callable[[Any, Dict[str, Any]], float]
    minimize: bool = True
    keep_models: bool = False


class LocalOptimizationRunner:
    """Sequential local executor (reference: LocalOptimizationRunner —
    its thread pool parallelised CPU training; on one TPU chip candidates
    serialize through the device anyway)."""

    def __init__(self, config: OptimizationConfiguration) -> None:
        self.config = config
        self.results: List[CandidateResult] = []

    def execute(self, log_fn=None) -> CandidateResult:
        cfg = self.config
        for i, hp in enumerate(cfg.candidate_generator):
            t0 = time.perf_counter()
            try:
                model = cfg.model_factory(hp)
                score = float(cfg.score_function(model, hp))
                res = CandidateResult(
                    index=i, hyperparameters=hp, score=score,
                    duration_s=time.perf_counter() - t0,
                    model=model if cfg.keep_models else None)
            except Exception as e:  # a failed candidate shouldn't end search
                res = CandidateResult(
                    index=i, hyperparameters=hp,
                    score=float("inf") if cfg.minimize else float("-inf"),
                    duration_s=time.perf_counter() - t0, error=str(e))
            self.results.append(res)
            if log_fn:
                log_fn(f"candidate {i}: score={res.score:.5f} hp={hp}"
                       + (f" ERROR={res.error}" if res.error else ""))
        if not self.results:
            raise ValueError("candidate generator produced no candidates")
        return self.best_result()

    def best_result(self) -> CandidateResult:
        key = (min if self.config.minimize else max)
        return key(self.results, key=lambda r: r.score)

    def num_candidates_completed(self) -> int:
        return len(self.results)
