"""TensorFlow GraphDef import.

Reference: org.nd4j.imports.graphmapper.tf.TFGraphMapper and the Kotlin
ImportGraph/OpMappingRegistry framework (SURVEY.md §2.2 "TF import" — the
BERT path, BASELINE.json:10). Same job: frozen GraphDef protobuf -> SameDiff
graph, op-by-op mapping rules with attr/dtype translation.

Design notes (TPU-first):
* Frozen inference graphs only (weights as Const) — the reference's primary
  path too (its golden tests are all frozen graphs).
* TF feeds shape-like operands (Reshape's shape, Transpose's perm, reduction
  indices) as tensor inputs; XLA wants static shapes. Const-backed operands
  are folded into op attrs at import time; truly dynamic shape operands are
  rejected with a clear error instead of tracing data-dependent shapes.
* Control flow: functional While/StatelessWhile/If/StatelessIf map to the
  SameDiff structured while_loop/cond nodes (one lax.while_loop / lax.cond
  HLO each); legacy V1 Switch/Merge/Enter/Exit/NextIteration/LoopCond
  frames are rewritten to functional While first, and frameless V1
  Switch/Merge conditionals become where-selects (tf_control_flow.py).

The mapping registry is ``TF_OP_RULES``: tf_op_name -> rule(ctx) returning
(sd_op_name, input_ids, attrs) or a direct SDVariable.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .samediff import SDVariable, SameDiff


def _tf():
    import tensorflow as tf

    return tf


def _iterative_topo(names, deps, cycle_msg: str):
    """Dependency-first ordering via an explicit stack (graphs can be
    thousands of nodes deep — Python recursion would overflow).
    ``deps`` maps name -> prerequisite names; unknown names are ignored."""
    order: List[str] = []
    state: Dict[str, int] = {}  # 1 = on stack, 2 = emitted
    for root in names:
        if state.get(root) == 2:
            continue
        stack = [(root, False)]
        while stack:
            name, expanded = stack.pop()
            if state.get(name) == 2:
                continue
            if expanded:
                state[name] = 2
                order.append(name)
                continue
            if state.get(name) == 1:
                raise ValueError(cycle_msg.format(name))
            state[name] = 1
            stack.append((name, True))
            for dep in deps.get(name, ()):
                if state.get(dep) != 2 and dep in deps:
                    if state.get(dep) == 1:
                        raise ValueError(cycle_msg.format(dep))
                    stack.append((dep, False))
    return order


@dataclasses.dataclass
class _NodeCtx:
    name: str
    op: str
    inputs: List[str]  # canonical "name" or "name:i"
    attr: Dict[str, Any]
    importer: "TFGraphMapper"

    def const_value(self, i: int) -> np.ndarray:
        """Value of input i, which must be Const-backed."""
        src = self.inputs[i].split(":")[0]
        if src not in self.importer.const_values:
            raise ValueError(
                f"{self.op} node {self.name!r}: input {i} ({src!r}) must be a "
                "constant for static-shape import"
            )
        return self.importer.const_values[src]

    def var(self, i: int) -> SDVariable:
        return self.importer.resolve(self.inputs[i])

    def np_dtype(self, key: str, default=None):
        tf = _tf()
        if key not in self.attr:
            return default
        return tf.dtypes.as_dtype(self.attr[key].type).as_numpy_dtype


Rule = Callable[[_NodeCtx], SDVariable]
TF_OP_RULES: Dict[str, Rule] = {}


def tf_rule(*names: str):
    def deco(fn: Rule):
        for n in names:
            TF_OP_RULES[n] = fn
        return fn

    return deco


# ---- simple 1:1 elementwise/nn maps ---------------------------------------
_SIMPLE = {
    "Add": "add", "AddV2": "add", "Sub": "sub", "Mul": "mul", "RealDiv": "div",
    "Div": "div", "Pow": "pow", "Maximum": "maximum", "Minimum": "minimum",
    "SquaredDifference": "squareddifference", "FloorDiv": "floordiv",
    "FloorMod": "mod", "Neg": "neg", "Abs": "abs", "Sign": "sign",
    "Exp": "exp", "Expm1": "expm1", "Log": "log", "Log1p": "log1p",
    "Sqrt": "sqrt", "Rsqrt": "rsqrt", "Square": "square",
    "Reciprocal": "reciprocal", "Sin": "sin", "Cos": "cos", "Tan": "tan",
    "Asin": "asin", "Acos": "acos", "Atan": "atan", "Sinh": "sinh",
    "Cosh": "cosh", "Tanh": "tanh", "Asinh": "asinh", "Acosh": "acosh",
    "Atanh": "atanh", "Erf": "erf", "Erfc": "erfc", "Floor": "floor",
    "Ceil": "ceil", "Round": "round", "IsNan": "isnan", "IsInf": "isinf",
    "Relu": "relu", "Relu6": "relu6", "Elu": "elu", "Selu": "selu",
    "Sigmoid": "sigmoid", "Softplus": "softplus", "Softsign": "softsign",
    "Greater": "gt", "GreaterEqual": "gte", "Less": "lt", "LessEqual": "lte",
    "Equal": "eq", "NotEqual": "neq", "LogicalAnd": "logical_and",
    "LogicalOr": "logical_or", "LogicalNot": "logical_not",
    "ZerosLike": "zeros_like", "OnesLike": "ones_like",
    "Identity": "identity", "StopGradient": "stop_gradient",
    "PreventGradient": "stop_gradient", "Snapshot": "identity",
    "CheckNumerics": "identity", "BitwiseAnd": "bitwise_and",
    "BitwiseOr": "bitwise_or", "BitwiseXor": "bitwise_xor",
    "Invert": "bitwise_not",
}

def _mk(sd_name):
    """Rule factory for 1:1 maps: every TF input becomes a positional var."""
    def rule(ctx: _NodeCtx) -> SDVariable:
        return ctx.importer.sd._op(
            sd_name, *(ctx.var(i) for i in range(len(ctx.inputs))),
            name=ctx.name)

    return rule


for _tf_name, _sd_name in _SIMPLE.items():
    TF_OP_RULES[_tf_name] = _mk(_sd_name)


@tf_rule("AddN")
def _addn(ctx):
    out = ctx.var(0)
    sd = ctx.importer.sd
    for i in range(1, len(ctx.inputs) - 1):
        out = sd._op("add", out, ctx.var(i))
    last = ctx.var(len(ctx.inputs) - 1)
    return sd._op("add", out, last, name=ctx.name)


@tf_rule("MatMul")
def _matmul(ctx):
    return ctx.importer.sd._op(
        "matmul", ctx.var(0), ctx.var(1), name=ctx.name,
        transpose_a=bool(ctx.attr["transpose_a"].b) if "transpose_a" in ctx.attr else False,
        transpose_b=bool(ctx.attr["transpose_b"].b) if "transpose_b" in ctx.attr else False,
    )


@tf_rule("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _batch_matmul(ctx):
    return ctx.importer.sd._op(
        "matmul", ctx.var(0), ctx.var(1), name=ctx.name,
        transpose_a=bool(ctx.attr["adj_x"].b) if "adj_x" in ctx.attr else False,
        transpose_b=bool(ctx.attr["adj_y"].b) if "adj_y" in ctx.attr else False,
    )


@tf_rule("BiasAdd")
def _bias_add(ctx):
    df = ctx.attr["data_format"].s.decode() if "data_format" in ctx.attr else "NHWC"
    return ctx.importer.sd._op("bias_add", ctx.var(0), ctx.var(1), name=ctx.name,
                               data_format=df)


@tf_rule("Softmax")
def _softmax(ctx):
    return ctx.importer.sd._op("softmax", ctx.var(0), name=ctx.name, axis=-1)


@tf_rule("LogSoftmax")
def _log_softmax(ctx):
    return ctx.importer.sd._op("log_softmax", ctx.var(0), name=ctx.name, axis=-1)


@tf_rule("LeakyRelu")
def _leaky(ctx):
    alpha = float(ctx.attr["alpha"].f) if "alpha" in ctx.attr else 0.2
    return ctx.importer.sd._op("leaky_relu", ctx.var(0), name=ctx.name, alpha=alpha)


@tf_rule("Reshape")
def _reshape(ctx):
    shape = [int(s) for s in ctx.const_value(1).reshape(-1)]
    return ctx.importer.sd._op("reshape", ctx.var(0), name=ctx.name, shape=shape)


@tf_rule("Transpose")
def _transpose(ctx):
    perm = [int(p) for p in ctx.const_value(1).reshape(-1)]
    return ctx.importer.sd._op("transpose", ctx.var(0), name=ctx.name, perm=perm)


@tf_rule("ExpandDims")
def _expand_dims(ctx):
    return ctx.importer.sd._op("expand_dims", ctx.var(0), name=ctx.name,
                               axis=int(ctx.const_value(1)))


@tf_rule("Squeeze")
def _squeeze(ctx):
    dims = list(ctx.attr["squeeze_dims"].list.i) if "squeeze_dims" in ctx.attr else None
    return ctx.importer.sd._op("squeeze", ctx.var(0), name=ctx.name, axis=dims)


@tf_rule("ConcatV2")
def _concat(ctx):
    n = len(ctx.inputs) - 1
    axis = int(ctx.const_value(n))
    return ctx.importer.sd._op("concat", *(ctx.var(i) for i in range(n)),
                               name=ctx.name, axis=axis)


@tf_rule("Pack")
def _pack(ctx):
    axis = int(ctx.attr["axis"].i) if "axis" in ctx.attr else 0
    return ctx.importer.sd._op("stack", *(ctx.var(i) for i in range(len(ctx.inputs))),
                               name=ctx.name, axis=axis)


@tf_rule("Unpack")
def _unpack(ctx):
    axis = int(ctx.attr["axis"].i) if "axis" in ctx.attr else 0
    num = int(ctx.attr["num"].i)
    return ctx.importer.sd._op("unstack", ctx.var(0), name=ctx.name, axis=axis, num=num)


@tf_rule("Split")
def _split(ctx):
    axis = int(ctx.const_value(0))
    num = int(ctx.attr["num_split"].i)
    return ctx.importer.sd._op("split", ctx.var(1), name=ctx.name,
                               num_splits=num, axis=axis)


@tf_rule("SplitV")
def _splitv(ctx):
    sizes = [int(s) for s in ctx.const_value(1).reshape(-1)]
    axis = int(ctx.const_value(2))
    return ctx.importer.sd._op("split_v", ctx.var(0), name=ctx.name,
                               size_splits=sizes, axis=axis)


@tf_rule("StridedSlice")
def _strided_slice(ctx):
    return ctx.importer.sd._op(
        "strided_slice", ctx.var(0), name=ctx.name,
        begin=[int(v) for v in ctx.const_value(1).reshape(-1)],
        end=[int(v) for v in ctx.const_value(2).reshape(-1)],
        strides=[int(v) for v in ctx.const_value(3).reshape(-1)],
        begin_mask=int(ctx.attr["begin_mask"].i) if "begin_mask" in ctx.attr else 0,
        end_mask=int(ctx.attr["end_mask"].i) if "end_mask" in ctx.attr else 0,
        shrink_axis_mask=int(ctx.attr["shrink_axis_mask"].i) if "shrink_axis_mask" in ctx.attr else 0,
        new_axis_mask=int(ctx.attr["new_axis_mask"].i) if "new_axis_mask" in ctx.attr else 0,
        ellipsis_mask=int(ctx.attr["ellipsis_mask"].i) if "ellipsis_mask" in ctx.attr else 0,
    )


@tf_rule("Slice")
def _slice(ctx):
    return ctx.importer.sd._op(
        "slice", ctx.var(0), name=ctx.name,
        begin=[int(v) for v in ctx.const_value(1).reshape(-1)],
        size=[int(v) for v in ctx.const_value(2).reshape(-1)],
    )


@tf_rule("Gather", "GatherV2")
def _gather(ctx):
    axis = 0
    if ctx.op == "GatherV2" and len(ctx.inputs) > 2:
        axis = int(ctx.const_value(2))
    return ctx.importer.sd._op("gather", ctx.var(0), ctx.var(1), name=ctx.name, axis=axis)


@tf_rule("GatherNd")
def _gather_nd(ctx):
    return ctx.importer.sd._op("gather_nd", ctx.var(0), ctx.var(1), name=ctx.name)


@tf_rule("OneHot")
def _one_hot(ctx):
    return ctx.importer.sd._op(
        "one_hot", ctx.var(0), name=ctx.name,
        depth=int(ctx.const_value(1)),
        on_value=float(ctx.const_value(2)),
        off_value=float(ctx.const_value(3)),
        axis=int(ctx.attr["axis"].i) if "axis" in ctx.attr else -1,
    )


@tf_rule("Cast")
def _cast(ctx):
    return ctx.importer.sd._op("cast", ctx.var(0), name=ctx.name,
                               dtype=np.dtype(ctx.np_dtype("DstT")).name)


@tf_rule("Shape")
def _shape(ctx):
    return ctx.importer.sd._op("shape_of", ctx.var(0), name=ctx.name)


@tf_rule("Rank")
def _rank(ctx):
    return ctx.importer.sd._op("rank", ctx.var(0), name=ctx.name)


@tf_rule("Size")
def _size(ctx):
    return ctx.importer.sd._op("size", ctx.var(0), name=ctx.name)


def _reduction(sd_name: str):
    def rule(ctx: _NodeCtx):
        axis = [int(v) for v in np.atleast_1d(ctx.const_value(1))]
        keep = bool(ctx.attr["keep_dims"].b) if "keep_dims" in ctx.attr else False
        return ctx.importer.sd._op(sd_name, ctx.var(0), name=ctx.name,
                                   axis=axis, keepdims=keep)

    return rule


TF_OP_RULES["Sum"] = _reduction("reduce_sum")
TF_OP_RULES["Mean"] = _reduction("reduce_mean")
TF_OP_RULES["Max"] = _reduction("reduce_max")
TF_OP_RULES["Min"] = _reduction("reduce_min")
TF_OP_RULES["Prod"] = _reduction("reduce_prod")
TF_OP_RULES["Any"] = _reduction("reduce_any")
TF_OP_RULES["All"] = _reduction("reduce_all")


@tf_rule("ArgMax")
def _argmax(ctx):
    return ctx.importer.sd._op("argmax", ctx.var(0), name=ctx.name,
                               axis=int(ctx.const_value(1)))


@tf_rule("ArgMin")
def _argmin(ctx):
    return ctx.importer.sd._op("argmin", ctx.var(0), name=ctx.name,
                               axis=int(ctx.const_value(1)))


@tf_rule("Tile")
def _tile(ctx):
    return ctx.importer.sd._op("tile", ctx.var(0), name=ctx.name,
                               reps=[int(v) for v in ctx.const_value(1).reshape(-1)])


@tf_rule("Fill")
def _fill(ctx):
    return ctx.importer.sd._op(
        "fill", name=ctx.name,
        shape=[int(v) for v in ctx.const_value(0).reshape(-1)],
        value=float(ctx.const_value(1)),
    )


@tf_rule("Range")
def _range(ctx):
    return ctx.importer.sd._op(
        "range", name=ctx.name,
        start=int(ctx.const_value(0)), limit=int(ctx.const_value(1)),
        delta=int(ctx.const_value(2)),
    )


@tf_rule("Select", "SelectV2")
def _select(ctx):
    return ctx.importer.sd._op("select", ctx.var(0), ctx.var(1), ctx.var(2), name=ctx.name)


@tf_rule("Pad", "PadV2")
def _pad(ctx):
    pads = [(int(a), int(b)) for a, b in ctx.const_value(1)]
    val = float(ctx.const_value(2)) if ctx.op == "PadV2" else 0.0
    return ctx.importer.sd._op("pad", ctx.var(0), name=ctx.name,
                               paddings=pads, constant_value=val)


@tf_rule("MirrorPad")
def _mirror_pad(ctx):
    pads = [(int(a), int(b)) for a, b in ctx.const_value(1)]
    mode = ctx.attr["mode"].s.decode() if "mode" in ctx.attr else "REFLECT"
    return ctx.importer.sd._op("pad", ctx.var(0), name=ctx.name, paddings=pads, mode=mode)


@tf_rule("L2Loss")
def _l2loss(ctx):
    sd = ctx.importer.sd
    sq = sd._op("square", ctx.var(0))
    s = sd._op("reduce_sum", sq)
    return sd._op("mul", s, sd.constant(np.float32(0.5)), name=ctx.name)


@tf_rule("Cumsum")
def _cumsum(ctx):
    return ctx.importer.sd._op(
        "cumsum", ctx.var(0), name=ctx.name, axis=int(ctx.const_value(1)),
        exclusive=bool(ctx.attr["exclusive"].b) if "exclusive" in ctx.attr else False,
        reverse=bool(ctx.attr["reverse"].b) if "reverse" in ctx.attr else False,
    )


@tf_rule("Einsum")
def _einsum(ctx):
    eq = ctx.attr["equation"].s.decode()
    return ctx.importer.sd._op("einsum", *(ctx.var(i) for i in range(len(ctx.inputs))),
                               name=ctx.name, equation=eq)


@tf_rule("Conv2D")
def _conv2d(ctx):
    strides = list(ctx.attr["strides"].list.i)
    df = ctx.attr["data_format"].s.decode() if "data_format" in ctx.attr else "NHWC"
    if df == "NHWC":
        s = (strides[1], strides[2])
    else:
        s = (strides[2], strides[3])
    dil = (1, 1)
    if "dilations" in ctx.attr:
        d = list(ctx.attr["dilations"].list.i)
        dil = (d[1], d[2]) if df == "NHWC" else (d[2], d[3])
    pad = ctx.attr["padding"].s.decode()
    return ctx.importer.sd._op("conv2d", ctx.var(0), ctx.var(1), name=ctx.name,
                               strides=s, padding=pad, data_format=df, dilations=dil)


@tf_rule("MaxPool", "AvgPool")
def _pool(ctx):
    k = list(ctx.attr["ksize"].list.i)
    strides = list(ctx.attr["strides"].list.i)
    df = ctx.attr["data_format"].s.decode() if "data_format" in ctx.attr else "NHWC"
    if df == "NHWC":
        kernel, s = (k[1], k[2]), (strides[1], strides[2])
    else:
        kernel, s = (k[2], k[3]), (strides[2], strides[3])
    op = "max_pool2d" if ctx.op == "MaxPool" else "avg_pool2d"
    return ctx.importer.sd._op(op, ctx.var(0), name=ctx.name, kernel=kernel,
                               strides=s, padding=ctx.attr["padding"].s.decode(),
                               data_format=df)


@tf_rule("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(ctx):
    eps = float(ctx.attr["epsilon"].f) if "epsilon" in ctx.attr else 1e-3
    df = ctx.attr["data_format"].s.decode() if "data_format" in ctx.attr else "NHWC"
    axis = 3 if df == "NHWC" else 1
    # inputs: x, scale, offset, mean, variance (inference mode)
    return ctx.importer.sd._op(
        "batch_norm", ctx.var(0), ctx.var(3), ctx.var(4),
        ctx.var(1), ctx.var(2), name=ctx.name, eps=eps, axis=axis,
    )


# ---- tranche-3 rule widening (SURVEY §2.2 TF import breadth) ---------------
# Simple 1:1 maps onto ops_extended/ops_tranche3 registrations.
_SIMPLE_T3 = {
    "TruncateDiv": "truncatediv", "TruncateMod": "truncatemod",
    "DivNoNan": "div_no_nan", "MulNoNan": "mul_no_nan",
    "Xlogy": "xlogy", "Xdivy": "xdivy", "Atan2": "atan2",
    "Lgamma": "lgamma", "Digamma": "digamma", "Erfinv": "erfinv",
    "Ndtri": "ndtri", "BesselI0e": "bessel_i0e", "BesselI1e": "bessel_i1e",
    "Rint": "rint", "Inv": "reciprocal", "IsFinite": "isfinite",
    "Betainc": "betainc", "Igamma": "igamma", "Igammac": "igammac",
    "Zeta": "zeta", "Polygamma": "polygamma",
    "LeftShift": "left_shift", "RightShift": "right_shift",
    "PopulationCount": "population_count",
    "InvertPermutation": "invert_permutation",
    "MatrixDeterminant": "matrix_determinant", "Cholesky": "cholesky",
    "Diag": "tensor_diag", "DiagPart": "tensor_diag_part", "Cross": "cross",
    "MatrixDiag": "matrix_diag", "MatrixDiagPart": "matrix_diag_part_v2",
    "MatrixSetDiag": "matrix_set_diag",
    "FFT": "fft", "IFFT": "ifft", "FFT2D": "fft2", "IFFT2D": "ifft2",
    "Real": "real", "Imag": "imag", "Conj": "conj", "Angle": "angle",
    "ComplexAbs": "abs", "Complex": "complex",
    "ClipByValue": "clip_by_value",
    "DepthwiseConv2dNative": None,  # attr rule below
}
for _tf_name, _sd_name in _SIMPLE_T3.items():
    if _sd_name is None or _tf_name in TF_OP_RULES:
        continue
    TF_OP_RULES[_tf_name] = _mk(_sd_name)  # same factory as _SIMPLE


def _register_multi_output(ctx, tup, arity):
    """Expose getitems of a tuple-valued op as the node's :0..:n outputs."""
    for i in range(1, arity):
        out_i = ctx.importer.sd._op("getitem", tup, item=i)
        ctx.importer._multi_outputs.setdefault(ctx.name, {})[i] = out_i
    return ctx.importer.sd._op("getitem", tup, item=0, name=ctx.name)


def _reject_adjoint(ctx):
    if "adjoint" in ctx.attr and bool(ctx.attr["adjoint"].b):
        raise NotImplementedError(
            f"{ctx.op} node {ctx.name!r}: adjoint=True is not supported")


@tf_rule("MatrixSolve")
def _matrix_solve_rule(ctx):
    _reject_adjoint(ctx)
    return ctx.importer.sd._op("solve", ctx.var(0), ctx.var(1),
                               name=ctx.name)


@tf_rule("MatrixInverse")
def _matrix_inverse_rule(ctx):
    _reject_adjoint(ctx)
    return ctx.importer.sd._op("matrix_inverse", ctx.var(0), name=ctx.name)


@tf_rule("Qr")
def _qr_rule(ctx):
    full = "full_matrices" in ctx.attr and bool(ctx.attr["full_matrices"].b)
    tup = ctx.importer.sd._op("qr", ctx.var(0), name=ctx.name + "__tuple",
                              full_matrices=full)
    return _register_multi_output(ctx, tup, 2)


@tf_rule("SelfAdjointEigV2")
def _eigh_rule(ctx):
    tup = ctx.importer.sd._op("self_adjoint_eig", ctx.var(0),
                              name=ctx.name + "__tuple")
    return _register_multi_output(ctx, tup, 2)


@tf_rule("Svd")
def _svd_rule(ctx):
    # TF emits (s, u, v); jnp.linalg.svd returns (u, s, vh) — reorder and
    # transpose vh so consumers of name:0/:1/:2 see TF's layout.
    sd = ctx.importer.sd
    full = "full_matrices" in ctx.attr and bool(ctx.attr["full_matrices"].b)
    if "compute_uv" in ctx.attr and not bool(ctx.attr["compute_uv"].b):
        raise NotImplementedError(
            f"Svd node {ctx.name!r}: compute_uv=False is not supported")
    tup = sd._op("svd", ctx.var(0), name=ctx.name + "__tuple",
                 full_matrices=full)
    u = sd._op("getitem", tup, item=0)
    s = sd._op("getitem", tup, item=1, name=ctx.name)
    vh = sd._op("getitem", tup, item=2)
    v = sd._op("swapaxes", vh, a=-2, b=-1)
    ctx.importer._multi_outputs.setdefault(ctx.name, {})[1] = u
    ctx.importer._multi_outputs.setdefault(ctx.name, {})[2] = v
    return s


@tf_rule("DepthwiseConv2dNative")
def _depthwise_conv(ctx):
    strides = list(ctx.attr["strides"].list.i)
    df = ctx.attr["data_format"].s.decode() if "data_format" in ctx.attr \
        else "NHWC"
    s = (strides[1], strides[2]) if df == "NHWC" else (strides[2], strides[3])
    dil = (1, 1)
    if "dilations" in ctx.attr:
        d = list(ctx.attr["dilations"].list.i)
        if d:
            dil = (d[1], d[2]) if df == "NHWC" else (d[2], d[3])
    return ctx.importer.sd._op(
        "depthwise_conv2d", ctx.var(0), ctx.var(1), name=ctx.name,
        strides=s, padding=ctx.attr["padding"].s.decode(), data_format=df,
        dilations=dil)


@tf_rule("Conv2DBackpropInput")
def _conv2d_backprop_input(ctx):
    # inputs: input_sizes (const), filter [kH, kW, inC, outC], grads.
    # Mapped onto the exact VJP form so odd spatial sizes under SAME/stride>1
    # (where plain conv_transpose is ambiguous) reconstruct correctly.
    strides = list(ctx.attr["strides"].list.i)
    df = ctx.attr["data_format"].s.decode() if "data_format" in ctx.attr \
        else "NHWC"
    s = (strides[1], strides[2]) if df == "NHWC" else (strides[2], strides[3])
    dil = (1, 1)
    if "dilations" in ctx.attr:
        d = list(ctx.attr["dilations"].list.i)
        if d:
            dil = (d[1], d[2]) if df == "NHWC" else (d[2], d[3])
    shape = [int(v) for v in ctx.const_value(0).reshape(-1)]
    return ctx.importer.sd._op(
        "conv2d_backprop_input", ctx.var(2), ctx.var(1), name=ctx.name,
        input_shape=shape, strides=s,
        padding=ctx.attr["padding"].s.decode(), data_format=df,
        dilations=dil)


def _reject_ncdhw(ctx):
    if "data_format" in ctx.attr:
        df = ctx.attr["data_format"].s.decode()
        if df and df != "NDHWC":
            raise NotImplementedError(
                f"{ctx.op} node {ctx.name!r}: data_format={df} not "
                "supported (NDHWC only)")


@tf_rule("Conv3D")
def _conv3d_rule(ctx):
    _reject_ncdhw(ctx)
    strides = list(ctx.attr["strides"].list.i)
    dil = (1, 1, 1)
    if "dilations" in ctx.attr:
        d = list(ctx.attr["dilations"].list.i)
        if d:
            dil = tuple(d[1:4])
    return ctx.importer.sd._op(
        "conv3d", ctx.var(0), ctx.var(1), name=ctx.name,
        strides=tuple(strides[1:4]), padding=ctx.attr["padding"].s.decode(),
        dilations=dil)


@tf_rule("MaxPool3D", "AvgPool3D")
def _pool3d_rule(ctx):
    _reject_ncdhw(ctx)
    k = list(ctx.attr["ksize"].list.i)
    s = list(ctx.attr["strides"].list.i)
    op = "max_pool3d" if ctx.op == "MaxPool3D" else "avg_pool3d"
    return ctx.importer.sd._op(
        op, ctx.var(0), name=ctx.name, kernel=tuple(k[1:4]),
        strides=tuple(s[1:4]), padding=ctx.attr["padding"].s.decode())


@tf_rule("Dilation2D")
def _dilation2d_rule(ctx):
    s = list(ctx.attr["strides"].list.i)
    r = list(ctx.attr["rates"].list.i)
    return ctx.importer.sd._op(
        "dilation2d", ctx.var(0), ctx.var(1), name=ctx.name,
        strides=(s[1], s[2]), rates=(r[1], r[2]),
        padding=ctx.attr["padding"].s.decode())


@tf_rule("ResizeBilinear", "ResizeNearestNeighbor", "ResizeBicubic")
def _resize_rule(ctx):
    # Our resize ops implement the half-pixel convention only. The raw-op
    # DEFAULT is half_pixel_centers=False (corner-origin, TF1): a missing
    # attr means corner-origin, so require the attr present and True, and
    # reject align_corners — loud failure beats silently shifted pixels.
    if "align_corners" in ctx.attr and bool(ctx.attr["align_corners"].b):
        raise NotImplementedError(
            f"{ctx.op} node {ctx.name!r}: align_corners=True has no "
            "half-pixel equivalent here")
    if "half_pixel_centers" not in ctx.attr or \
            not bool(ctx.attr["half_pixel_centers"].b):
        raise NotImplementedError(
            f"{ctx.op} node {ctx.name!r}: corner-origin sampling "
            "(half_pixel_centers absent or False, the TF1 default) is not "
            "supported — re-export with tf.image.resize (TF2 half-pixel)")
    size = [int(v) for v in ctx.const_value(1).reshape(-1)]
    op = {"ResizeBilinear": "resize_bilinear",
          "ResizeNearestNeighbor": "resize_nearest",
          "ResizeBicubic": "resize_bicubic"}[ctx.op]
    return ctx.importer.sd._op(op, ctx.var(0), name=ctx.name, size=size)


@tf_rule("SpaceToDepth", "DepthToSpace")
def _space_depth_rule(ctx):
    op = "space_to_depth" if ctx.op == "SpaceToDepth" else "depth_to_space"
    df = ctx.attr["data_format"].s.decode() if "data_format" in ctx.attr \
        else "NHWC"
    return ctx.importer.sd._op(
        op, ctx.var(0), name=ctx.name,
        block_size=int(ctx.attr["block_size"].i), data_format=df)


@tf_rule("SpaceToBatchND", "BatchToSpaceND")
def _space_batch_nd_rule(ctx):
    block = [int(v) for v in ctx.const_value(1).reshape(-1)]
    pc = [list(int(x) for x in row) for row in
          ctx.const_value(2).reshape(len(block), 2)]
    if ctx.op == "SpaceToBatchND":
        return ctx.importer.sd._op("space_to_batch", ctx.var(0),
                                   name=ctx.name, block_shape=block,
                                   paddings=pc)
    return ctx.importer.sd._op("batch_to_space", ctx.var(0), name=ctx.name,
                               block_shape=block, crops=pc)


@tf_rule("SegmentSum", "SegmentMean", "SegmentMax", "SegmentMin",
         "SegmentProd")
def _segment_rule(ctx):
    ids = ctx.const_value(1).reshape(-1)  # static import needs const ids
    op = {"SegmentSum": "segment_sum", "SegmentMean": "segment_mean",
          "SegmentMax": "segment_max", "SegmentMin": "segment_min",
          "SegmentProd": "segment_prod"}[ctx.op]
    return ctx.importer.sd._op(op, ctx.var(0), ctx.var(1), name=ctx.name,
                               num_segments=int(ids.max()) + 1)


@tf_rule("UnsortedSegmentSum", "UnsortedSegmentMean", "UnsortedSegmentMax",
         "UnsortedSegmentMin", "UnsortedSegmentProd")
def _unsorted_segment_rule(ctx):
    n = int(ctx.const_value(2))
    op = {"UnsortedSegmentSum": "unsorted_segment_sum",
          "UnsortedSegmentMean": "unsorted_segment_mean",
          "UnsortedSegmentMax": "unsorted_segment_max",
          "UnsortedSegmentMin": "unsorted_segment_min",
          "UnsortedSegmentProd": "unsorted_segment_prod"}[ctx.op]
    return ctx.importer.sd._op(op, ctx.var(0), ctx.var(1), name=ctx.name,
                               num_segments=n)


@tf_rule("TopKV2")
def _top_k_rule(ctx):
    tup = ctx.importer.sd._op("top_k", ctx.var(0),
                              name=ctx.name + "__tuple",
                              k=int(ctx.const_value(1)))
    return _register_multi_output(ctx, tup, 2)


@tf_rule("MatrixDiagV2", "MatrixDiagV3")
def _matrix_diag_v23(ctx):
    # inputs: diagonal, k, num_rows, num_cols, padding_value. The static
    # importer supports the main-diagonal square zero-padded case (tf.eye
    # and friends); anything else is rejected loudly.
    if int(ctx.const_value(1)) != 0:
        raise NotImplementedError(f"{ctx.op}: only k=0 supported")
    for i, what in ((2, "num_rows"), (3, "num_cols")):
        if len(ctx.inputs) > i and int(ctx.const_value(i)) != -1:
            raise NotImplementedError(
                f"{ctx.op}: explicit {what} is not supported")
    if len(ctx.inputs) > 4 and float(ctx.const_value(4)) != 0.0:
        raise NotImplementedError(f"{ctx.op}: padding_value != 0")
    return ctx.importer.sd._op("matrix_diag", ctx.var(0), name=ctx.name)


@tf_rule("InTopKV2", "InTopK")
def _in_top_k_rule(ctx):
    if ctx.op == "InTopKV2":
        k = int(ctx.const_value(2))
    else:
        k = int(ctx.attr["k"].i)
    return ctx.importer.sd._op("in_top_k", ctx.var(0), ctx.var(1),
                               name=ctx.name, k=k)


@tf_rule("ScatterNd")
def _scatter_nd_rule(ctx):
    shape = [int(v) for v in ctx.const_value(2).reshape(-1)]
    return ctx.importer.sd._op("scatter_nd", ctx.var(0), ctx.var(1),
                               name=ctx.name, shape=shape)


@tf_rule("TensorScatterAdd", "TensorScatterSub", "TensorScatterUpdate",
         "TensorScatterMax", "TensorScatterMin")
def _tensor_scatter_rule(ctx):
    op = {"TensorScatterAdd": "scatter_nd_add",
          "TensorScatterSub": "scatter_nd_sub",
          "TensorScatterUpdate": "scatter_nd_update",
          "TensorScatterMax": "tensor_scatter_max",
          "TensorScatterMin": "tensor_scatter_min"}[ctx.op]
    return ctx.importer.sd._op(op, ctx.var(0), ctx.var(1), ctx.var(2),
                               name=ctx.name)


@tf_rule("MatrixBandPart")
def _band_part_rule(ctx):
    return ctx.importer.sd._op(
        "matrix_band_part", ctx.var(0), name=ctx.name,
        num_lower=int(ctx.const_value(1)), num_upper=int(ctx.const_value(2)))


@tf_rule("MatrixTriangularSolve")
def _tri_solve_rule(ctx):
    _reject_adjoint(ctx)
    lower = bool(ctx.attr["lower"].b) if "lower" in ctx.attr else True
    return ctx.importer.sd._op("triangular_solve", ctx.var(0), ctx.var(1),
                               name=ctx.name, lower=lower)


@tf_rule("LRN")
def _lrn_rule(ctx):
    # TF: out = in / (bias + alpha * sqr_sum)^beta — alpha passes through
    # unscaled (cuDNN-style alpha/n scaling is the CALLER's convention).
    return ctx.importer.sd._op(
        "local_response_normalization", ctx.var(0), name=ctx.name,
        depth=2 * int(ctx.attr["depth_radius"].i) + 1
        if "depth_radius" in ctx.attr else 11,  # TF default radius is 5
        bias=float(ctx.attr["bias"].f) if "bias" in ctx.attr else 1.0,
        alpha=float(ctx.attr["alpha"].f) if "alpha" in ctx.attr else 1.0,
        beta=float(ctx.attr["beta"].f) if "beta" in ctx.attr else 0.5)


@tf_rule("ReverseV2")
def _reverse_rule(ctx):
    axis = [int(v) for v in ctx.const_value(1).reshape(-1)]
    return ctx.importer.sd._op("reverse", ctx.var(0), name=ctx.name,
                               axis=axis)


@tf_rule("ReverseSequence")
def _reverse_seq_rule(ctx):
    return ctx.importer.sd._op(
        "reverse_sequence", ctx.var(0), ctx.var(1), name=ctx.name,
        seq_axis=int(ctx.attr["seq_dim"].i),
        batch_axis=int(ctx.attr["batch_dim"].i)
        if "batch_dim" in ctx.attr else 0)


@tf_rule("Roll")
def _roll_rule(ctx):
    shifts = [int(v) for v in np.atleast_1d(ctx.const_value(1))]
    axes = [int(v) for v in np.atleast_1d(ctx.const_value(2))]
    out = ctx.var(0)
    sd = ctx.importer.sd
    for i, (sh, ax) in enumerate(zip(shifts, axes)):
        nm = ctx.name if i == len(shifts) - 1 else f"{ctx.name}__roll{i}"
        out = sd._op("roll", out, name=nm, shift=sh, axis=ax)
    return out


@tf_rule("HistogramFixedWidth")
def _hist_rule(ctx):
    vr = [float(v) for v in ctx.const_value(1).reshape(-1)]
    return ctx.importer.sd._op(
        "histogram_fixed_width", ctx.var(0), name=ctx.name,
        value_range=vr, nbins=int(ctx.const_value(2)))


@tf_rule("CumulativeLogsumexp")
def _cumlse_rule(ctx):
    return ctx.importer.sd._op("cumlogsumexp", ctx.var(0), name=ctx.name,
                               axis=int(ctx.const_value(1)))


@tf_rule("Cumprod")
def _cumprod_rule(ctx):
    return ctx.importer.sd._op(
        "cumprod", ctx.var(0), name=ctx.name, axis=int(ctx.const_value(1)),
        exclusive=bool(ctx.attr["exclusive"].b)
        if "exclusive" in ctx.attr else False,
        reverse=bool(ctx.attr["reverse"].b)
        if "reverse" in ctx.attr else False)


@tf_rule("MatrixDiagPartV2", "MatrixDiagPartV3")
def _matrix_diag_part_v23(ctx):
    # inputs: input, k, padding_value — main-diagonal case only.
    k = int(ctx.const_value(1))
    if k != 0:
        raise NotImplementedError(f"{ctx.op}: only k=0 supported")
    return ctx.importer.sd._op("matrix_diag_part_v2", ctx.var(0),
                               name=ctx.name)


@tf_rule("Bincount", "DenseBincount")
def _bincount_rule(ctx):
    # inputs: arr, size (const), weights (an empty const when unweighted)
    binary = "binary_output" in ctx.attr and bool(ctx.attr["binary_output"].b)
    has_weights = True
    try:
        has_weights = ctx.const_value(2).size > 0
    except ValueError:
        pass  # non-const weights tensor: definitely present
    if has_weights:
        if binary:  # TF requires empty weights with binary_output
            raise NotImplementedError(
                f"{ctx.op} node {ctx.name!r}: binary_output with weights")
        return ctx.importer.sd._op(
            "bincount_weighted", ctx.var(0), ctx.var(2), name=ctx.name,
            minlength=int(ctx.const_value(1)))
    return ctx.importer.sd._op(
        "bincount", ctx.var(0), name=ctx.name,
        minlength=int(ctx.const_value(1)), binary_output=binary)


class TFGraphMapper:
    """Reference spelling: TFGraphMapper.importGraph(graphDef)."""

    def __init__(self) -> None:
        self.sd = SameDiff.create()
        self.const_values: Dict[str, np.ndarray] = {}
        self._produced: Dict[str, SDVariable] = {}
        self._multi_outputs: Dict[str, Dict[int, SDVariable]] = {}
        self.graph_def = None  # set in run(); function library lookups
        self._gd_by_name: Dict[str, Any] = {}
        self._branch_of: Dict[str, SDVariable] = {}  # Switch name -> pred

    # ---- public entry points ----------------------------------------------
    @staticmethod
    def import_graph(graph_def_or_path, outputs: Optional[Sequence[str]] = None) -> SameDiff:
        return TFGraphMapper().run(graph_def_or_path, outputs)

    importGraph = import_graph

    def run(self, graph_def_or_path, outputs: Optional[Sequence[str]] = None) -> SameDiff:
        tf = _tf()
        if isinstance(graph_def_or_path, (str, bytes)):
            gd = tf.compat.v1.GraphDef()
            with open(graph_def_or_path, "rb") as f:
                gd.ParseFromString(f.read())
        else:
            gd = graph_def_or_path

        from tensorflow.python.framework import tensor_util

        from .tf_control_flow import has_v1_control_flow, rewrite_v1_loops

        if has_v1_control_flow(gd):
            # V1 while frames -> functional StatelessWhile; frameless
            # Switch/Merge (v1 cond) survive and hit their own rules
            gd = rewrite_v1_loops(gd)
        self.graph_def = gd
        self._gd_by_name = {n.name: n for n in gd.node}

        needed = None
        if outputs:
            needed = self._dependency_closure(gd, outputs)

        for node in self._topo_order(gd):
            if needed is not None and node.name not in needed:
                continue
            self._import_node(node, tensor_util)
        return self.sd

    @staticmethod
    def _topo_order(gd):
        """Dependency-ordered nodes. GraphDef carries no ordering guarantee
        (V1 cond graphs interleave Switch after its consumers); cycles are
        impossible here because V1 while frames were rewritten to functional
        While before this runs."""
        by_name = {n.name: n for n in gd.node}
        deps = {
            n.name: [i.lstrip("^").split(":")[0] for i in n.input]
            for n in gd.node
        }
        order = _iterative_topo(
            [n.name for n in gd.node], deps,
            cycle_msg="GraphDef cycle at {!r} (unrewritten V1 loop?)")
        return [by_name[name] for name in order if name in by_name]

    # ---- internals --------------------------------------------------------
    @staticmethod
    def _canon(inp: str) -> str:
        inp = inp.lstrip("^")
        return inp

    def _dependency_closure(self, gd, outputs: Sequence[str]) -> set:
        by_name = {n.name: n for n in gd.node}
        seen: set = set()
        stack = [o.split(":")[0] for o in outputs]
        while stack:
            name = stack.pop()
            if name in seen or name not in by_name:
                continue
            seen.add(name)
            for i in by_name[name].input:
                if i.startswith("^"):
                    continue  # control deps are ordering-only; execution is functional
                stack.append(self._canon(i).split(":")[0])
        return seen

    def resolve(self, ref: str) -> SDVariable:
        ref = self._canon(ref)
        if ":" in ref:
            base, idx = ref.rsplit(":", 1)
            idx = int(idx)
        else:
            base, idx = ref, 0
        if idx > 0:
            multi = self._multi_outputs.get(base)
            if multi is None or idx not in multi:
                src = self._produced[base]
                out = self.sd._op("getitem", src, item=idx)
                self._multi_outputs.setdefault(base, {})[idx] = out
                return out
            return multi[idx]
        return self._produced[base]

    def _import_node(self, node, tensor_util) -> None:
        name = node.name
        op = node.op
        if op == "NoOp":
            return
        if op == "Const":
            value = tensor_util.MakeNdarray(node.attr["value"].tensor)
            self.const_values[name] = value
            if value.dtype == object:
                return  # string consts (asset paths) are not tensors we carry
            self._produced[name] = self.sd.constant(value, name=name)
            return
        if op in ("Placeholder", "PlaceholderWithDefault"):
            tf = _tf()
            dtype = tf.dtypes.as_dtype(node.attr["dtype"].type).as_numpy_dtype
            shape = None
            if "shape" in node.attr:
                dims = node.attr["shape"].shape.dim
                shape = tuple(d.size if d.size >= 0 else None for d in dims)
            self._produced[name] = self.sd.placeholder(
                name, shape=shape, dtype=np.dtype(dtype).name
            )
            return
        if op in ("VariableV2", "VarHandleOp", "ReadVariableOp", "Variable"):
            raise ValueError(
                f"Node {name!r} is an unfrozen variable ({op}); freeze the graph "
                "first (convert_variables_to_constants_v2)"
            )
        rule = TF_OP_RULES.get(op)
        if rule is None:
            raise NotImplementedError(
                f"TF op {op!r} (node {name!r}) has no import rule; "
                f"{len(TF_OP_RULES)} ops are mapped"
            )
        data_inputs = [self._canon(i) for i in node.input if not i.startswith("^")]
        ctx = _NodeCtx(name=name, op=op, inputs=data_inputs, attr=dict(node.attr),
                       importer=self)
        result = rule(ctx)
        self._produced[name] = result

    def trace_branch(self, ref: str):
        """Walk the GraphDef backwards from ``ref`` to the nearest Switch;
        returns (pred_var, side) where side is True for the :1 output, or
        None if no Switch feeds this ref. Used by the frameless V1
        Switch/Merge conditional rules (tf_control_flow.py)."""
        stack = [self._canon(ref)]
        seen = set()
        while stack:
            r = stack.pop()
            base, _, idx = r.partition(":")
            if base in seen:
                continue
            seen.add(base)
            node = self._gd_by_name.get(base)
            if node is None:
                continue
            if node.op in ("Switch", "RefSwitch"):
                pred = self._branch_of.get(base)
                if pred is not None:
                    return pred, idx == "1"
                continue
            stack.extend(self._canon(i) for i in node.input
                         if not i.startswith("^"))
        return None


from .tf_control_flow import (  # noqa: E402 — rules need TFGraphMapper defined
    register_functional_rules,
    register_v1_cond_rules,
)

register_functional_rules(tf_rule, TF_OP_RULES)
register_v1_cond_rules(tf_rule, TF_OP_RULES)
