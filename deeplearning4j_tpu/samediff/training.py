"""SameDiff training session.

Reference: org.nd4j.autodiff.samediff.internal.TrainingSession +
org.nd4j.autodiff.samediff.TrainingConfig (SURVEY.md §3.3). The reference
interprets the forward+backward graph op-by-op and applies updaters per
variable; here one jitted XLA program does forward, backward and the optax
update — full-graph HLO compile (BASELINE.json:10).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import register_config
from ..train.updaters import Adam, IUpdater, updater_from_any


@register_config
@dataclasses.dataclass(frozen=True)
class TrainingConfig:
    """Reference: TrainingConfig.Builder — updater + placeholder mappings."""

    updater: Optional[IUpdater] = None
    data_set_feature_mapping: tuple = ()
    data_set_label_mapping: tuple = ()
    l1: float = 0.0
    l2: float = 0.0
    minimize: bool = True


@dataclasses.dataclass
class History:
    """Reference: org.nd4j.autodiff.listeners.records.History."""

    loss_curve: List[float] = dataclasses.field(default_factory=list)


class TrainingSession:
    def __init__(self, sd, config: Optional[TrainingConfig],
                 listeners=None) -> None:
        from ..core.listeners import ListenerBus

        self.sd = sd
        self.config = config or TrainingConfig(updater=Adam(1e-3))
        self.updater = updater_from_any(self.config.updater or Adam(1e-3))
        self.tx = self.updater.to_optax()
        # trainable values keyed by node id
        self.var_ids = [
            n.id for n in sd._nodes.values() if n.kind == "variable"
        ]
        self.opt_state = None
        self._step = None
        # the most recent fit()'s History — still holds the flushed loss
        # curve when fit() is interrupted mid-run (robust telemetry)
        self.last_history: Optional[History] = None
        # TrainingListener bus (core/listeners.py): MetricsListener et al.
        # attach here. The per-step score is fetched from device ONLY when
        # some listener declares requires_score — otherwise listeners get
        # NaN and the loss stays on device (one stacked fetch per epoch).
        self.listeners = ListenerBus(listeners)
        self.iteration_count = 0
        self.epoch_count = 0
        self.last_batch_size: Optional[int] = None

    def _build_step(self):
        sd = self.sd
        cfg = self.config
        loss_name = sd._loss_name
        if loss_name is None:
            raise ValueError("SameDiff has no loss variable (set_loss_variables)")
        var_ids = self.var_ids

        def step(var_vals: Dict[int, Any], opt_state, feeds: Dict[str, Any], rng):
            def loss_of(vv):
                all_vals = dict(sd._values)
                all_vals.update(vv)
                out = sd._eval_graph(feeds, all_vals, [loss_name], rng=rng, training=True)
                loss = jnp.sum(out[loss_name])
                if cfg.l2:
                    for v in vv.values():
                        loss = loss + 0.5 * cfg.l2 * jnp.sum(jnp.square(v))
                if cfg.l1:
                    for v in vv.values():
                        loss = loss + cfg.l1 * jnp.sum(jnp.abs(v))
                return loss if cfg.minimize else -loss

            loss, grads = jax.value_and_grad(loss_of)(var_vals)
            updates, new_opt = self.tx.update(grads, opt_state, var_vals)
            import optax

            new_vals = optax.apply_updates(var_vals, updates)
            return new_vals, new_opt, loss

        return jax.jit(step, donate_argnums=(0, 1))

    def fit(self, iterator, epochs: int = 1) -> History:
        sd = self.sd
        cfg = self.config
        var_vals = {i: sd._values[i] for i in self.var_ids}
        if self.opt_state is None:
            self.opt_state = self.tx.init(var_vals)
        if self._step is None:
            self._step = self._build_step()
        history = History()
        self.last_history = history
        from ..data.dataset import DataSet, MultiDataSet

        device_losses = []

        def flush_losses():
            if device_losses:
                # ONE stacked D2H fetch (iterating a jax array would fetch
                # per element — a tunnel round-trip each)
                history.loss_curve.extend(
                    np.asarray(jnp.stack(device_losses), np.float64).tolist())
                device_losses.clear()

        bus = self.listeners
        use_listeners = bool(bus.listeners)
        need_score = use_listeners and bus.requires_score
        try:
            for _ in range(epochs):
                if use_listeners:
                    bus.epoch_start(self)
                for item in iterator:
                    if isinstance(item, MultiDataSet):
                        feats, labs = list(item.features), list(item.labels)
                    elif isinstance(item, DataSet):
                        feats, labs = [item.features], [item.labels]
                    else:
                        feats, labs = [item[0]], [item[1]]
                    feeds = {}
                    feeds.update(zip(cfg.data_set_feature_mapping, feats))
                    feeds.update(zip(cfg.data_set_label_mapping, labs))
                    feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
                    rng = sd._rng.next_key()
                    var_vals, self.opt_state, loss = self._step(var_vals, self.opt_state, feeds, rng)
                    # keep the loss ON DEVICE: a float() here would force a
                    # host sync per step (~64 ms through the axon tunnel —
                    # measured round 5: it tripled the imported-BERT train
                    # step). One stacked fetch per epoch costs one sync.
                    device_losses.append(loss)
                    if use_listeners:
                        self.iteration_count += 1
                        if feats:
                            shp = np.shape(feats[0])
                            self.last_batch_size = int(shp[0]) if shp else None
                        bus.iteration_done(
                            self, self.iteration_count, self.epoch_count,
                            float(loss) if need_score else float("nan"))
                flush_losses()
                if use_listeners:
                    bus.epoch_end(self)
                self.epoch_count += 1
        finally:
            # an exception / KeyboardInterrupt mid-epoch must not lose the
            # curve recorded so far — flush whatever is still on device
            flush_losses()
        sd._values.update(var_vals)
        return history
