"""SameDiff op tranche 3: the remaining libnd4j declarable-op families.

Reference: libnd4j ``ops/declarable/generic`` + nd4j op classes
(SURVEY.md §2.1 "Declarable ops (~500)") — the families beyond
ops.py/ops_extended.py: reverse/no-nan pairwise arithmetic, reduce3
distances, merge/stitch combiners, depthwise/separable/dilation conv,
im2col/col2im, RNN layer ops (lstm_layer/gru/sru — the reference's
recurrent declarables), FFT + window functions, Bessel/special functions,
image geometry (rot90/flips/crops/gamma/sobel/ssim), scatter-nd, the
declarable updater ops (ops/declarable/generic/updaters — sgd/adam/… are
real libnd4j ops, not just JVM updaters), nan-skipping reductions,
statistics (cov/corrcoef/quantile), and quantization.

Same contract as ops.py: pure jnp-thin functions in SD_OPS; XLA fuses.
Dynamic-output-shape reference ops keep the XLA-honest padded/static-attr
form (SURVEY.md §7): ``setdiff1d_padded``, ``ctc_greedy_decoder`` return
fixed-shape results + a count/length, as the TPU compilation model needs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .ops import sd_op, get_sd_op

# ---- pairwise arithmetic long tail ----------------------------------------
sd_op("rsub")(lambda a, b: b - a)
sd_op("rdiv")(lambda a, b: b / a)
sd_op("realdiv")(jnp.true_divide)
sd_op("truncatediv")(lambda a, b: jnp.trunc(a / b).astype(jnp.result_type(a, b)))
sd_op("truncatemod")(lambda a, b: a - b * jnp.trunc(a / b))
# double-where keeps the b==0 branch out of the backward pass too: a single
# where still routes 0/0 = NaN cotangents through the division VJP (TF's
# DivNoNan gradient is 0 there).
sd_op("div_no_nan")(lambda a, b: jnp.where(
    b == 0, jnp.zeros_like(a * b), a / jnp.where(b == 0, 1, b)))
sd_op("mul_no_nan")(lambda a, b: jnp.where(b == 0, jnp.zeros_like(a * b), a * b))
sd_op("floormod")(lambda a, b: a - b * jnp.floor(a / b))
sd_op("remainder")(jnp.remainder)
sd_op("axpy")(lambda x, y, alpha=1.0: alpha * x + y)
sd_op("copy")(lambda x: jnp.asarray(x))
sd_op("assign")(lambda ref, value: jnp.broadcast_to(value, ref.shape).astype(ref.dtype))
sd_op("pow_pairwise")(jnp.float_power)
sd_op("relative_error")(lambda a, b: jnp.where(
    (a == 0) & (b == 0), 0.0, jnp.abs(a - b) / (jnp.abs(a) + jnp.abs(b))))
sd_op("squared_subtract")(lambda a, b: (a - b) ** 2)


# ---- reduce3 distances (reference: nd4j reduce3 ops) -----------------------
def _pair_axis(axis):
    return None if axis is None else tuple(int(a) for a in np.atleast_1d(axis))


sd_op("euclidean_distance")(lambda x, y, axis=None, keepdims=False: jnp.sqrt(
    jnp.sum((x - y) ** 2, axis=_pair_axis(axis), keepdims=bool(keepdims))))
sd_op("manhattan_distance")(lambda x, y, axis=None, keepdims=False: jnp.sum(
    jnp.abs(x - y), axis=_pair_axis(axis), keepdims=bool(keepdims)))


@sd_op("cosine_similarity")
def _cosine_similarity(x, y, axis=None, keepdims=False, eps=1e-12):
    ax = _pair_axis(axis)
    num = jnp.sum(x * y, axis=ax, keepdims=bool(keepdims))
    den = jnp.sqrt(jnp.sum(x * x, axis=ax, keepdims=bool(keepdims))) * \
        jnp.sqrt(jnp.sum(y * y, axis=ax, keepdims=bool(keepdims)))
    return num / jnp.maximum(den, eps)


@sd_op("jaccard_distance")
def _jaccard_distance(x, y, axis=None, keepdims=False, eps=1e-12):
    ax = _pair_axis(axis)
    inter = jnp.sum(jnp.minimum(x, y), axis=ax, keepdims=bool(keepdims))
    union = jnp.sum(jnp.maximum(x, y), axis=ax, keepdims=bool(keepdims))
    return 1.0 - inter / jnp.maximum(union, eps)


sd_op("hamming_distance")(lambda x, y, axis=None, keepdims=False: jnp.sum(
    (x != y).astype(jnp.float32), axis=_pair_axis(axis),
    keepdims=bool(keepdims)))


@sd_op("dot_product_attention")
def _dot_product_attention(q, k, v, mask=None, scale=None):
    """Single-head scaled dot-product attention. q/k/v [..., T, d]."""
    s = (1.0 / jnp.sqrt(q.shape[-1])) if scale is None else scale
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * s
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)
    return jnp.einsum("...qk,...kd->...qd", jax.nn.softmax(logits, axis=-1), v)


# ---- merge / stitch combiners (reference: mergeadd/mergemax/…) -------------
sd_op("mergeadd")(lambda *xs: sum(xs[1:], start=xs[0]))
sd_op("add_n")(lambda *xs: sum(xs[1:], start=xs[0]))
sd_op("accumulate_n")(lambda *xs: sum(xs[1:], start=xs[0]))


@sd_op("mergemax")
def _mergemax(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = jnp.maximum(out, x)
    return out


sd_op("mergeavg")(lambda *xs: sum(xs[1:], start=xs[0]) / float(len(xs)))


@sd_op("mergemaxindex")
def _mergemaxindex(*xs):
    return jnp.argmax(jnp.stack(xs, axis=0), axis=0)


@sd_op("dynamic_stitch")
def _dynamic_stitch(indices, *data, size=None):
    """TF dynamic_stitch with equal-rank parts: result[indices[i][j]] =
    data[i][j]. XLA-honest form: output length is static — with concrete
    index lists it is TF's max(indices)+1 (gaps stay zero, duplicates
    last-wins across inputs); with traced indices pass ``size``
    explicitly. Later lists overwrite earlier ones at duplicate indices,
    matching TF's last-wins across inputs."""
    idx_list = list(indices) if isinstance(indices, (list, tuple)) \
        else [indices]
    ind_ndim = idx_list[0].ndim
    if size is not None:
        n = int(size)
    else:
        # TF semantics are max(indices)+1, computable whenever the indices
        # are concrete (TF-imported graphs legally use gaps and duplicates
        # and the importer cannot pass size=; duplicates keep TF's
        # last-wins because updates apply in list order below).
        try:
            concrete = np.concatenate(
                [np.asarray(i).ravel() for i in idx_list])
        except Exception:  # traced values: cannot compute max(indices)
            concrete = None
        if concrete is not None:
            n = int(concrete.max()) + 1 if len(concrete) else 0
        else:
            # Traced indices: the output length must be static under jit
            # and cannot be derived from traced values — demand size=.
            raise ValueError(
                "dynamic_stitch with traced indices requires size= "
                "(= max(indices)+1): the output length must be static "
                "and cannot be derived from traced index values.")
    rest = data[0].shape[ind_ndim:]
    out = jnp.zeros((n,) + rest, data[0].dtype)
    for i, d in zip(idx_list, data):
        out = out.at[jnp.ravel(i)].set(d.reshape((-1,) + rest))
    return out


# ---- conv extras -----------------------------------------------------------
@sd_op("depthwise_conv2d")
def _depthwise_conv2d(x, w, bias=None, strides=(1, 1), padding="SAME",
                      data_format="NHWC", dilations=(1, 1)):
    """x NHWC/NCHW, w [kH, kW, C, mult] (TF depthwise convention)."""
    df = str(data_format).upper()
    c = x.shape[-1] if df == "NHWC" else x.shape[1]
    kh, kw, _, mult = w.shape
    w2 = w.reshape(kh, kw, 1, c * mult)
    y = lax.conv_general_dilated(
        x, w2, window_strides=tuple(int(s) for s in strides),
        padding=str(padding).upper(), feature_group_count=c,
        rhs_dilation=tuple(int(d) for d in dilations),
        dimension_numbers=(df, "HWIO", df))
    if bias is not None:
        y = y + (bias if df == "NHWC" else bias[:, None, None])
    return y


@sd_op("separable_conv2d")
def _separable_conv2d(x, depthwise_w, pointwise_w, bias=None, strides=(1, 1),
                      padding="SAME", data_format="NHWC"):
    """Depthwise then 1x1 pointwise (reference sconv2d / TF separable_conv2d).
    pointwise_w [1, 1, C*mult, out]."""
    df = str(data_format).upper()
    y = _depthwise_conv2d(x, depthwise_w, None, strides, padding, df)
    y = lax.conv_general_dilated(
        y, pointwise_w, window_strides=(1, 1), padding="VALID",
        dimension_numbers=(df, "HWIO", df))
    if bias is not None:
        y = y + (bias if df == "NHWC" else bias[:, None, None])
    return y


@sd_op("pointwise_conv2d")
def _pointwise_conv2d(x, w, bias=None, data_format="NHWC"):
    df = str(data_format).upper()
    y = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="VALID",
        dimension_numbers=(df, "HWIO", df))
    if bias is not None:
        y = y + (bias if df == "NHWC" else bias[:, None, None])
    return y


@sd_op("conv2d_backprop_input")
def _conv2d_backprop_input(g, w, input_shape=None, strides=(1, 1),
                           padding="SAME", data_format="NHWC",
                           dilations=(1, 1)):
    """TF Conv2DBackpropInput: the exact gradient of the forward conv with
    respect to an input of ``input_shape`` — defined AS that VJP, so odd
    spatial sizes under SAME/stride>1 (where conv_transpose is ambiguous)
    come out right. w [kH, kW, inC, outC] (forward HWIO kernel)."""
    from .ops import get_sd_op as _get
    fwd = _get("conv2d")
    shape = tuple(int(s) for s in input_shape)
    _, vjp = jax.vjp(
        lambda x: fwd(x, w, strides=strides, padding=padding,
                      data_format=data_format, dilations=dilations),
        jnp.zeros(shape, g.dtype))
    return vjp(g)[0]


@sd_op("tensor_diag")
def _tensor_diag(x):
    """TF Diag: output shape = x.shape + x.shape, diagonal holds x."""
    return jnp.diag(jnp.ravel(x)).reshape(x.shape + x.shape)


@sd_op("tensor_diag_part")
def _tensor_diag_part(x):
    """TF DiagPart: input shape s + s -> output shape s."""
    half = x.ndim // 2
    s = x.shape[:half]
    n = int(np.prod(s))
    return jnp.diagonal(x.reshape(n, n)).reshape(s)


@sd_op("deconv3d")
def _deconv3d(x, w, bias=None, strides=(1, 1, 1), padding="SAME"):
    """x NDHWC, w [kD, kH, kW, out, in] (forward-conv kernel, gradient op)."""
    y = lax.conv_transpose(
        x, w, strides=tuple(int(s) for s in strides),
        padding=str(padding).upper(),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"), transpose_kernel=True)
    return y if bias is None else y + bias


@sd_op("dilation2d")
def _dilation2d(x, w, strides=(1, 1), rates=(1, 1), padding="SAME"):
    """Grayscale morphological dilation (TF dilation2d). x NHWC, w [kH,kW,C].
    Unfold windows, add the filter, reduce with max — trace-safe (no value
    inspection of ``w``, which may be a tracer under jit/grad)."""
    kh, kw, _ = w.shape
    pad = str(padding).upper()
    strd = (1, int(strides[0]), int(strides[1]), 1)
    dil = (1, int(rates[0]), int(rates[1]), 1)
    if pad == "SAME":
        eh = (kh - 1) * dil[1] + 1
        ew = (kw - 1) * dil[2] + 1
        ph, pw = max(eh - 1, 0), max(ew - 1, 0)
        pads = ((0, 0), (ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2), (0, 0))
    else:
        pads = ((0, 0),) * 4
    neg = jnp.finfo(x.dtype).min
    xp = jnp.pad(x, pads, constant_values=neg)
    outs = []
    for i in range(kh):
        for j in range(kw):
            hi = i * dil[1]
            wi = j * dil[2]
            sl = xp[:, hi:hi + x.shape[1] + pads[1][0] + pads[1][1] - (kh - 1) * dil[1]:strd[1],
                    wi:wi + x.shape[2] + pads[2][0] + pads[2][1] - (kw - 1) * dil[2]:strd[2], :]
            outs.append(sl + w[i, j])
    return jnp.max(jnp.stack(outs, axis=0), axis=0)


@sd_op("im2col")
def _im2col(x, kernel=(3, 3), strides=(1, 1), padding="SAME"):
    """x NCHW -> [N, C*kH*kW, outH*outW] (reference im2col layout)."""
    n, c, h, w = x.shape
    kh, kw = int(kernel[0]), int(kernel[1])
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(int(s) for s in strides), str(padding).upper(),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches.reshape(n, c * kh * kw, -1)


@sd_op("col2im")
def _col2im(cols, output_size=None, kernel=(3, 3), strides=(1, 1),
            padding="SAME"):
    """Exact adjoint of im2col (sum of overlapping patches): the VJP of the
    forward patch-extraction, so <im2col(x), c> == <x, col2im(c)> by
    construction. cols [N, C*kH*kW, L] -> NCHW at output_size=(H, W)."""
    n = cols.shape[0]
    kh, kw = int(kernel[0]), int(kernel[1])
    h, w = int(output_size[0]), int(output_size[1])
    c = cols.shape[1] // (kh * kw)
    _, vjp = jax.vjp(
        lambda x: _im2col(x, kernel=(kh, kw), strides=strides,
                          padding=padding),
        jnp.zeros((n, c, h, w), cols.dtype))
    return vjp(cols)[0]


@sd_op("upsampling1d")
def _upsampling1d(x, scale=2):
    return jnp.repeat(x, int(scale), axis=1)


@sd_op("upsampling3d")
def _upsampling3d(x, scale=2, data_format="NDHWC"):
    s = int(scale)
    axes = (1, 2, 3) if str(data_format).upper() == "NDHWC" else (2, 3, 4)
    for ax in axes:
        x = jnp.repeat(x, s, axis=ax)
    return x


@sd_op("max_pool_with_argmax")
def _max_pool_with_argmax(x, kernel=(2, 2), strides=(2, 2), padding="VALID"):
    """x NHWC -> (pooled, flat argmax into the input's N*H*W*C index space)."""
    n, h, w, c = x.shape
    flat_idx = jnp.arange(n * h * w * c, dtype=jnp.int32).reshape(x.shape)
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(strides[0]), int(strides[1])
    window, strd = (1, kh, kw, 1), (1, sh, sw, 1)
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min

    def sel(acc, cur):
        av, ai = acc
        cv, ci = cur
        take = cv > av
        return jnp.where(take, cv, av), jnp.where(take, ci, ai)

    pooled, arg = lax.reduce_window(
        (x, flat_idx), (jnp.asarray(neg, x.dtype), jnp.asarray(-1, jnp.int32)),
        sel, window, strd, str(padding).upper())
    return pooled, arg


@sd_op("max_unpooling2d")
def _max_unpooling2d(grad, argmax, input_shape=None):
    """Scatter pooled values back to argmax positions (reference maxpool bp)."""
    flat = jnp.zeros(int(np.prod(input_shape)), grad.dtype)
    flat = flat.at[jnp.ravel(argmax)].add(jnp.ravel(grad))
    return flat.reshape(tuple(int(s) for s in input_shape))


# ---- RNN layer ops (reference: lstm_layer/gru/sru declarables) ------------
@sd_op("lstm_layer")
def _lstm_layer(x, h0, c0, W, R, b=None):
    """Full-sequence LSTM via lax.scan over lstm_cell. x [T, B, in] ->
    (h_seq [T, B, u], h_T, c_T). The scan IS the reference's recurrent
    loop, compiled (SURVEY §7: XLA while replaces the cuDNN RNN helper)."""
    cell = get_sd_op("lstm_cell")

    def step(carry, xt):
        h, c = carry
        h2, c2 = cell(xt, h, c, W, R, b)
        return (h2, c2), h2

    (hT, cT), hs = lax.scan(step, (h0, c0), x)
    return hs, hT, cT


@sd_op("gru")
def _gru(x, h0, W, R, b=None):
    """Full-sequence GRU. x [T, B, in] -> (h_seq, h_T)."""
    cell = get_sd_op("gru_cell")

    def step(h, xt):
        h2 = cell(xt, h, W, R, b)
        return h2, h2

    hT, hs = lax.scan(step, h0, x)
    return hs, hT


@sd_op("rnn_cell")
def _rnn_cell(x, h_prev, W, R, b=None):
    z = x @ W + h_prev @ R
    if b is not None:
        z = z + b
    return jnp.tanh(z)


@sd_op("rnn")
def _rnn(x, h0, W, R, b=None):
    def step(h, xt):
        h2 = _rnn_cell(xt, h, W, R, b)
        return h2, h2

    hT, hs = lax.scan(step, h0, x)
    return hs, hT


@sd_op("sru_cell")
def _sru_cell(x_tilde, f, r, c_prev, x_res):
    """One SRU step (Lei et al.): c = f*c_prev + (1-f)*x_tilde;
    h = r*tanh(c) + (1-r)*x_res."""
    c = f * c_prev + (1.0 - f) * x_tilde
    h = r * jnp.tanh(c) + (1.0 - r) * x_res
    return h, c


@sd_op("sru")
def _sru(x, c0, W, b):
    """Simple Recurrent Unit over a sequence. x [T, B, d], W [d, 3d], b [2d].
    The matmul is time-parallel (one big MXU GEMM); only the cheap
    elementwise recurrence scans — the SRU's whole point, and exactly the
    split the TPU wants."""
    d = x.shape[-1]
    z = x @ W  # [T, B, 3d] — parallel across time
    x_tilde, fz, rz = z[..., :d], z[..., d:2 * d], z[..., 2 * d:]
    f = jax.nn.sigmoid(fz + b[:d])
    r = jax.nn.sigmoid(rz + b[d:])

    def step(c, t):
        xt, ft, rt, xr = t
        h, c2 = _sru_cell(xt, ft, rt, c, xr)
        return c2, h

    cT, hs = lax.scan(step, c0, (x_tilde, f, r, x))
    return hs, cT


@sd_op("bidirectional_lstm")
def _bidirectional_lstm(x, h0f, c0f, h0b, c0b, Wf, Rf, Wb, Rb, bf=None, bb=None):
    """Concatenated forward+backward LSTM over [T, B, in]."""
    hf, _, _ = _lstm_layer(x, h0f, c0f, Wf, Rf, bf)
    hb, _, _ = _lstm_layer(x[::-1], h0b, c0b, Wb, Rb, bb)
    return jnp.concatenate([hf, hb[::-1]], axis=-1)


# ---- FFT family ------------------------------------------------------------
sd_op("fft")(lambda x, n=None, axis=-1: jnp.fft.fft(x, n=n, axis=int(axis)))
sd_op("ifft")(lambda x, n=None, axis=-1: jnp.fft.ifft(x, n=n, axis=int(axis)))
sd_op("rfft")(lambda x, n=None, axis=-1: jnp.fft.rfft(x, n=n, axis=int(axis)))
sd_op("irfft")(lambda x, n=None, axis=-1: jnp.fft.irfft(x, n=n, axis=int(axis)))
sd_op("fft2")(lambda x: jnp.fft.fft2(x))
sd_op("ifft2")(lambda x: jnp.fft.ifft2(x))
sd_op("fftshift")(lambda x, axis=None: jnp.fft.fftshift(
    x, axes=None if axis is None else tuple(int(a) for a in np.atleast_1d(axis))))
sd_op("ifftshift")(lambda x, axis=None: jnp.fft.ifftshift(
    x, axes=None if axis is None else tuple(int(a) for a in np.atleast_1d(axis))))
sd_op("real")(jnp.real)
sd_op("imag")(jnp.imag)
sd_op("conj")(jnp.conj)
sd_op("complex")(lambda re, im: lax.complex(re, im))
sd_op("angle")(jnp.angle)


# ---- window functions (reference/TF signal windows) ------------------------
def _window(n, fn, periodic):
    """TF-signal convention: periodic=True (denominator N, for STFT) is the
    default; periodic=False gives the symmetric numpy windows (N-1)."""
    n = int(n)
    if n == 1:
        return jnp.ones((1,))
    denom = n if periodic else n - 1
    return fn(jnp.arange(n, dtype=jnp.float32), denom)


sd_op("hann_window")(lambda n, periodic=True: _window(
    n, lambda i, m: 0.5 - 0.5 * jnp.cos(2 * jnp.pi * i / m), periodic))
sd_op("hamming_window")(lambda n, periodic=True: _window(
    n, lambda i, m: 0.54 - 0.46 * jnp.cos(2 * jnp.pi * i / m), periodic))
sd_op("blackman_window")(lambda n, periodic=True: _window(
    n, lambda i, m: 0.42 - 0.5 * jnp.cos(2 * jnp.pi * i / m)
    + 0.08 * jnp.cos(4 * jnp.pi * i / m), periodic))
sd_op("bartlett_window")(lambda n, periodic=False: _window(
    n, lambda i, m: 1.0 - jnp.abs(2 * i / m - 1.0), periodic))


@sd_op("stft")
def _stft(x, frame_length=256, frame_step=128, fft_length=None, window="hann"):
    """x [..., T] -> [..., frames, fft_length//2+1] complex."""
    fl, fs = int(frame_length), int(frame_step)
    nfft = fl if fft_length is None else int(fft_length)
    n_frames = 1 + (x.shape[-1] - fl) // fs
    idx = (jnp.arange(n_frames)[:, None] * fs + jnp.arange(fl)[None, :])
    frames = x[..., idx]  # [..., frames, fl]
    if window == "hann":
        frames = frames * get_sd_op("hann_window")(fl)
    return jnp.fft.rfft(frames, n=nfft, axis=-1)


# ---- Bessel / special ------------------------------------------------------
sd_op("bessel_i0")(jax.scipy.special.i0)
sd_op("bessel_i1")(jax.scipy.special.i1)
sd_op("bessel_i0e")(jax.scipy.special.i0e)
sd_op("bessel_i1e")(jax.scipy.special.i1e)
sd_op("sinc")(jnp.sinc)
sd_op("ndtr")(jax.scipy.special.ndtr)
sd_op("ndtri")(jax.scipy.special.ndtri)
sd_op("softmax_temperature")(
    lambda x, temperature=1.0, axis=-1: jax.nn.softmax(
        x / temperature, axis=int(axis)))


# ---- image geometry / photometric -----------------------------------------
sd_op("flip_left_right")(lambda x: x[..., :, ::-1, :])
sd_op("flip_up_down")(lambda x: x[..., ::-1, :, :])


@sd_op("rot90")
def _rot90(x, k=1):
    """Rotate HWC (or NHWC) images 90° CCW k times over the (H, W) axes."""
    h_ax = x.ndim - 3
    return jnp.rot90(x, k=int(k), axes=(h_ax, h_ax + 1))


@sd_op("adjust_gamma")
def _adjust_gamma(x, gamma=1.0, gain=1.0):
    return gain * jnp.power(x, gamma)


@sd_op("central_crop")
def _central_crop(x, fraction=1.0):
    h, w = x.shape[-3], x.shape[-2]
    ch = int(round(h * float(fraction)))
    cw = int(round(w * float(fraction)))
    top, left = (h - ch) // 2, (w - cw) // 2
    return x[..., top:top + ch, left:left + cw, :]


@sd_op("crop_to_bounding_box")
def _crop_to_bounding_box(x, offset_height=0, offset_width=0,
                          target_height=None, target_width=None):
    return x[..., int(offset_height):int(offset_height) + int(target_height),
             int(offset_width):int(offset_width) + int(target_width), :]


@sd_op("pad_to_bounding_box")
def _pad_to_bounding_box(x, offset_height=0, offset_width=0,
                         target_height=None, target_width=None):
    h, w = x.shape[-3], x.shape[-2]
    oh, ow = int(offset_height), int(offset_width)
    pads = [(0, 0)] * (x.ndim - 3) + [
        (oh, int(target_height) - h - oh), (ow, int(target_width) - w - ow),
        (0, 0)]
    return jnp.pad(x, pads)


@sd_op("random_crop")
def _random_crop(x, size=None, rng=None):
    size = tuple(int(s) for s in size)
    starts = [jax.random.randint(k, (), 0, int(d) - int(s) + 1)
              for k, d, s in zip(jax.random.split(rng, len(size)),
                                 x.shape, size)]
    return lax.dynamic_slice(x, starts, size)


@sd_op("mirror_pad")
def _mirror_pad(x, paddings=None, mode="REFLECT"):
    mode = {"REFLECT": "reflect", "SYMMETRIC": "symmetric"}[str(mode).upper()]
    return jnp.pad(x, [tuple(int(v) for v in p) for p in paddings], mode=mode)


@sd_op("resize_bicubic")
def _resize_bicubic(x, size=None, data_format="NHWC"):
    """Half-pixel Keys cubic (a=-0.5, Catmull-Rom) — golden-tested to match
    TF's half_pixel_centers=True ResizeBicubic within 5e-4. (TF's legacy
    a=-0.75 kernel belongs to the corner-origin path the importer rejects.)"""
    size = tuple(int(s) for s in size)
    if str(data_format).upper() == "NHWC":
        shape = (x.shape[0],) + size + (x.shape[3],)
    else:
        shape = x.shape[:2] + size
    return jax.image.resize(x, shape, method="cubic")


@sd_op("image_resize")
def _image_resize(x, size=None, method="bilinear"):
    m = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic",
         "lanczos3": "lanczos3", "lanczos5": "lanczos5"}[str(method)]
    shape = (x.shape[0], int(size[0]), int(size[1]), x.shape[3])
    return jax.image.resize(x, shape, method=m)


@sd_op("sobel_edges")
def _sobel_edges(x):
    """x NHWC -> [N, H, W, C, 2] (dy, dx), TF sobel_edges semantics."""
    ky = jnp.asarray([[-1., -2., -1.], [0., 0., 0.], [1., 2., 1.]], x.dtype)
    kx = ky.T
    c = x.shape[-1]
    k = jnp.stack([ky, kx], axis=-1)  # [3,3,2]
    k = jnp.tile(k[:, :, None, :], (1, 1, c, 1)).reshape(3, 3, c, 2)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), mode="reflect")
    y = lax.conv_general_dilated(
        xp, k, (1, 1), "VALID", feature_group_count=c,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y.reshape(x.shape[0], x.shape[1], x.shape[2], c, 2)


@sd_op("image_gradients")
def _image_gradients(x):
    """dy, dx with zero last row/col (TF image_gradients)."""
    dy = jnp.concatenate([x[:, 1:] - x[:, :-1],
                          jnp.zeros_like(x[:, :1])], axis=1)
    dx = jnp.concatenate([x[:, :, 1:] - x[:, :, :-1],
                          jnp.zeros_like(x[:, :, :1])], axis=2)
    return dy, dx


sd_op("total_variation")(lambda x: jnp.sum(
    jnp.abs(x[:, 1:] - x[:, :-1]), axis=(1, 2, 3))
    + jnp.sum(jnp.abs(x[:, :, 1:] - x[:, :, :-1]), axis=(1, 2, 3)))


@sd_op("psnr")
def _psnr(a, b, max_val=1.0):
    mse = jnp.mean((a - b) ** 2, axis=(-3, -2, -1))
    return 10.0 * jnp.log10(max_val ** 2 / mse)


@sd_op("ssim")
def _ssim(a, b, max_val=1.0, filter_size=11, filter_sigma=1.5,
          k1=0.01, k2=0.03):
    """Mean SSIM over a Gaussian window (Wang et al. 2004 / TF ssim)."""
    size, sigma = int(filter_size), float(filter_sigma)
    g = jnp.arange(size, dtype=jnp.float32) - (size - 1) / 2.0
    g = jnp.exp(-(g ** 2) / (2 * sigma ** 2))
    g = g / jnp.sum(g)
    c = a.shape[-1]
    win = (g[:, None] * g[None, :])[:, :, None, None]
    win = jnp.tile(win, (1, 1, c, 1)).reshape(size, size, c, 1)

    def filt(x):
        return lax.conv_general_dilated(
            x, win, (1, 1), "VALID", feature_group_count=c,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    c1 = (k1 * max_val) ** 2
    c2 = (k2 * max_val) ** 2
    mu_a, mu_b = filt(a), filt(b)
    var_a = filt(a * a) - mu_a ** 2
    var_b = filt(b * b) - mu_b ** 2
    cov = filt(a * b) - mu_a * mu_b
    num = (2 * mu_a * mu_b + c1) * (2 * cov + c2)
    den = (mu_a ** 2 + mu_b ** 2 + c1) * (var_a + var_b + c2)
    return jnp.mean(num / den, axis=(-3, -2, -1))


sd_op("rgb_to_yiq")(lambda x: x @ jnp.asarray(
    [[0.299, 0.595716, 0.211456],
     [0.587, -0.274453, -0.522591],
     [0.114, -0.321263, 0.311135]], x.dtype))
sd_op("yiq_to_rgb")(lambda x: x @ jnp.asarray(
    [[1.0, 1.0, 1.0],
     [0.9562957197589482, -0.2721220993185104, -1.1069890167364901],
     [0.6210244164652610, -0.6473805968256950, 1.7046149983646786]], x.dtype))
sd_op("yuv_to_rgb")(lambda x: x @ jnp.asarray(
    [[1.0, 1.0, 1.0],
     [0.0, -0.394642334, 2.03206185],
     [1.13988303, -0.58062185, 0.0]], x.dtype))


# ---- scatter-nd family -----------------------------------------------------
@sd_op("scatter_nd")
def _scatter_nd(indices, updates, shape=None):
    out = jnp.zeros(tuple(int(s) for s in shape), updates.dtype)
    return out.at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


sd_op("scatter_nd_add")(lambda ref, indices, updates: ref.at[
    tuple(jnp.moveaxis(indices, -1, 0))].add(updates))
sd_op("scatter_nd_sub")(lambda ref, indices, updates: ref.at[
    tuple(jnp.moveaxis(indices, -1, 0))].add(-updates))
sd_op("scatter_nd_update")(lambda ref, indices, updates: ref.at[
    tuple(jnp.moveaxis(indices, -1, 0))].set(updates))
sd_op("tensor_scatter_max")(lambda ref, indices, updates: ref.at[
    tuple(jnp.moveaxis(indices, -1, 0))].max(updates))
sd_op("tensor_scatter_min")(lambda ref, indices, updates: ref.at[
    tuple(jnp.moveaxis(indices, -1, 0))].min(updates))


# ---- declarable updater ops (libnd4j ops/declarable/generic/updaters) ------
@sd_op("sgd_updater")
def _sgd_updater(grad, lr=0.01):
    return grad * lr


@sd_op("momentum_updater")
def _momentum_updater(grad, v, lr=0.01, momentum=0.9):
    v2 = momentum * v + grad
    return lr * v2, v2


@sd_op("nesterovs_updater")
def _nesterovs_updater(grad, v, lr=0.01, momentum=0.9):
    v2 = momentum * v - lr * grad
    return momentum * v - (1 + momentum) * v2, v2


@sd_op("adagrad_updater")
def _adagrad_updater(grad, state, lr=0.01, eps=1e-6):
    s2 = state + grad ** 2
    return lr * grad / (jnp.sqrt(s2) + eps), s2


@sd_op("rmsprop_updater")
def _rmsprop_updater(grad, state, lr=0.01, decay=0.95, eps=1e-8):
    s2 = decay * state + (1 - decay) * grad ** 2
    return lr * grad / jnp.sqrt(s2 + eps), s2


@sd_op("adadelta_updater")
def _adadelta_updater(grad, msg, msdx, rho=0.95, eps=1e-6):
    msg2 = rho * msg + (1 - rho) * grad ** 2
    upd = grad * jnp.sqrt(msdx + eps) / jnp.sqrt(msg2 + eps)
    msdx2 = rho * msdx + (1 - rho) * upd ** 2
    return upd, msg2, msdx2


@sd_op("adam_updater")
def _adam_updater(grad, m, v, step, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8):
    m2 = beta1 * m + (1 - beta1) * grad
    v2 = beta2 * v + (1 - beta2) * grad ** 2
    t = step + 1
    mhat = m2 / (1 - beta1 ** t)
    vhat = v2 / (1 - beta2 ** t)
    return lr * mhat / (jnp.sqrt(vhat) + eps), m2, v2


@sd_op("adamax_updater")
def _adamax_updater(grad, m, u, step, lr=2e-3, beta1=0.9, beta2=0.999,
                    eps=1e-8):
    m2 = beta1 * m + (1 - beta1) * grad
    u2 = jnp.maximum(beta2 * u, jnp.abs(grad))
    t = step + 1
    return lr * m2 / ((1 - beta1 ** t) * (u2 + eps)), m2, u2


@sd_op("amsgrad_updater")
def _amsgrad_updater(grad, m, v, vhat, step, lr=1e-3, beta1=0.9, beta2=0.999,
                     eps=1e-8):
    m2 = beta1 * m + (1 - beta1) * grad
    v2 = beta2 * v + (1 - beta2) * grad ** 2
    vh2 = jnp.maximum(vhat, v2)
    t = step + 1
    mhat = m2 / (1 - beta1 ** t)
    return lr * mhat / (jnp.sqrt(vh2 / (1 - beta2 ** t)) + eps), m2, v2, vh2


@sd_op("nadam_updater")
def _nadam_updater(grad, m, v, step, lr=1e-3, beta1=0.9, beta2=0.999,
                   eps=1e-8):
    m2 = beta1 * m + (1 - beta1) * grad
    v2 = beta2 * v + (1 - beta2) * grad ** 2
    t = step + 1
    mhat = m2 / (1 - beta1 ** t)
    vhat = v2 / (1 - beta2 ** t)
    return lr * (beta1 * mhat + (1 - beta1) * grad / (1 - beta1 ** t)) \
        / (jnp.sqrt(vhat) + eps), m2, v2


# ---- nan-skipping reductions ----------------------------------------------
for _n, _f in {"nansum": jnp.nansum, "nanmean": jnp.nanmean,
               "nanmax": jnp.nanmax, "nanmin": jnp.nanmin,
               "nanvar": jnp.nanvar, "nanstd": jnp.nanstd,
               "nanprod": jnp.nanprod}.items():
    sd_op(_n)(lambda x, axis=None, keepdims=False, _f=_f: _f(
        x, axis=None if axis is None else tuple(int(a) for a in np.atleast_1d(axis)),
        keepdims=bool(keepdims)))


# ---- statistics ------------------------------------------------------------
sd_op("cov")(lambda x, rowvar=True, bias=False: jnp.cov(
    x, rowvar=bool(rowvar), bias=bool(bias)))
sd_op("corrcoef")(lambda x, rowvar=True: jnp.corrcoef(x, rowvar=bool(rowvar)))
sd_op("quantile")(lambda x, q, axis=None, method="linear": jnp.quantile(
    x, q, axis=None if axis is None else int(axis), method=str(method)))
sd_op("ptp")(lambda x, axis=None: jnp.ptp(
    x, axis=None if axis is None else int(axis)))
sd_op("diff")(lambda x, n=1, axis=-1: jnp.diff(x, n=int(n), axis=int(axis)))
sd_op("ediff1d")(lambda x: jnp.diff(jnp.ravel(x)))
sd_op("trapz")(lambda y, x=None, dx=1.0, axis=-1: jnp.trapezoid(
    y, x=x, dx=dx, axis=int(axis)))
sd_op("allclose")(lambda a, b, rtol=1e-5, atol=1e-8: jnp.all(
    jnp.isclose(a, b, rtol=rtol, atol=atol)))
sd_op("zero_fraction")(lambda x: jnp.mean((x == 0).astype(jnp.float32)))


@sd_op("sufficient_statistics")
def _sufficient_statistics(x, axis=None, shift=None):
    ax = _pair_axis(axis)
    if ax is None:
        ax = tuple(range(x.ndim))
    count = jnp.asarray(np.prod([x.shape[a] for a in ax]), x.dtype)
    xs = x if shift is None else x - shift
    return count, jnp.sum(xs, axis=ax), jnp.sum(xs * xs, axis=ax), shift


@sd_op("weighted_moments")
def _weighted_moments(x, weights, axis=None, keepdims=False):
    ax = _pair_axis(axis)
    if ax is None:
        ax = tuple(range(x.ndim))
    wsum = jnp.sum(weights * jnp.ones_like(x), axis=ax, keepdims=bool(keepdims))
    mean = jnp.sum(weights * x, axis=ax, keepdims=bool(keepdims)) / wsum
    mk = mean if keepdims else jnp.expand_dims(mean, ax)
    var = jnp.sum(weights * (x - mk) ** 2, axis=ax,
                  keepdims=bool(keepdims)) / wsum
    return mean, var


# ---- indexing / conditional ------------------------------------------------
@sd_op("first_index")
def _first_index(x, condition_value, axis=-1):
    """Index of the first element equal to condition_value; -1 if none."""
    hit = x == condition_value
    idx = jnp.argmax(hit, axis=int(axis))
    any_ = jnp.any(hit, axis=int(axis))
    return jnp.where(any_, idx, -1)


@sd_op("last_index")
def _last_index(x, condition_value, axis=-1):
    ax = int(axis)
    hit = x == condition_value
    n = x.shape[ax]
    rev_idx = jnp.argmax(jnp.flip(hit, axis=ax), axis=ax)
    any_ = jnp.any(hit, axis=ax)
    return jnp.where(any_, n - 1 - rev_idx, -1)


@sd_op("ismax")
def _ismax(x, axis=None):
    if axis is None:
        return (x == jnp.max(x)).astype(x.dtype)
    m = jnp.max(x, axis=int(axis), keepdims=True)
    return (x == m).astype(x.dtype)


@sd_op("nth_element")
def _nth_element(x, n, reverse=False):
    s = jnp.sort(x, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., int(n)]


@sd_op("choose")
def _choose(x, condition="gt", value=0.0):
    """Reference 'choose' filter in padded form: elements satisfying the
    comparison, compacted to the front, plus the count."""
    cmp = {"gt": x > value, "lt": x < value, "gte": x >= value,
           "lte": x <= value, "eq": x == value, "neq": x != value}[condition]
    flat = jnp.ravel(x)
    mask = jnp.ravel(cmp)
    order = jnp.argsort(~mask, stable=True)
    return jnp.where(jnp.arange(flat.shape[0]) < jnp.sum(mask),
                     flat[order], 0), jnp.sum(mask)


sd_op("compare_and_set")(lambda x, compare, set_value=0.0, eps=1e-9:
                         jnp.where(jnp.abs(x - compare) < eps, set_value, x))
sd_op("compare_and_replace")(lambda x, y, condition="lt", value=0.0:
                             jnp.where({"lt": x < value, "gt": x > value,
                                        "eq": x == value}[condition], y, x))


@sd_op("invert_permutation")
def _invert_permutation(p):
    return jnp.zeros_like(p).at[p].set(jnp.arange(p.shape[0], dtype=p.dtype))


@sd_op("setdiff1d_padded")
def _setdiff1d_padded(x, y):
    """Elements of x not in y, compacted front, zero-padded, plus count
    (XLA-honest form of TF setdiff1d)."""
    keep = ~jnp.isin(x, y)
    order = jnp.argsort(~keep, stable=True)
    n = jnp.sum(keep)
    return jnp.where(jnp.arange(x.shape[0]) < n, x[order], 0), n


sd_op("take")(lambda x, indices, axis=None: jnp.take(
    x, indices, axis=None if axis is None else int(axis)))
sd_op("take_along_axis")(lambda x, indices, axis=-1: jnp.take_along_axis(
    x, indices, axis=int(axis)))


# ---- bitwise extras --------------------------------------------------------
sd_op("toggle_bits")(jnp.invert)
sd_op("population_count")(lax.population_count)
sd_op("shift_bits")(jnp.left_shift)
sd_op("rshift_bits")(jnp.right_shift)


_UNSIGNED_OF = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


@sd_op("cyclic_shift_bits")
def _cyclic_shift_bits(x, shift):
    # rotate in the SAME-WIDTH unsigned domain: arithmetic right-shift on a
    # signed dtype would smear the sign bit into the rotated bits
    nbits = x.dtype.itemsize * 8
    shift = shift % nbits
    ux = x.astype(_UNSIGNED_OF[x.dtype.itemsize])
    out = (ux << shift) | (ux >> (nbits - shift))
    return out.astype(x.dtype)


@sd_op("cyclic_rshift_bits")
def _cyclic_rshift_bits(x, shift):
    nbits = x.dtype.itemsize * 8
    shift = shift % nbits
    ux = x.astype(_UNSIGNED_OF[x.dtype.itemsize])
    out = (ux >> shift) | (ux << (nbits - shift))
    return out.astype(x.dtype)


@sd_op("bits_hamming_distance")
def _bits_hamming_distance(x, y):
    v = jnp.bitwise_xor(x, y)
    return jnp.sum(jax.lax.population_count(v))


sd_op("bitcast")(lambda x, dtype=None: lax.bitcast_convert_type(
    x, jnp.dtype(dtype)))


# ---- losses / nn extras ----------------------------------------------------
def _apply_loss_reduction(per_elem, weights, reduction):
    w = jnp.ones_like(per_elem) if weights is None \
        else jnp.broadcast_to(weights, per_elem.shape)
    lw = per_elem * w
    if reduction == "none":
        return lw
    if reduction == "sum":
        return jnp.sum(lw)
    if reduction == "mean_by_weight":
        return jnp.sum(lw) / jnp.maximum(jnp.sum(w), 1e-12)
    return jnp.mean(lw)  # mean_by_count


sd_op("absolute_difference_loss")(
    lambda labels, predictions, weights=None, reduction="mean_by_count":
    _apply_loss_reduction(jnp.abs(predictions - labels), weights, reduction))


@sd_op("cosine_distance_loss")
def _cosine_distance_loss(labels, predictions, weights=None, axis=-1,
                          reduction="mean_by_count"):
    per = 1.0 - jnp.sum(labels * predictions, axis=int(axis), keepdims=True)
    return _apply_loss_reduction(per, weights, reduction)


sd_op("l2_loss")(lambda x: 0.5 * jnp.sum(x * x))
sd_op("log_poisson_loss")(
    lambda targets, log_input, full=False:
    jnp.exp(log_input) - targets * log_input
    + (targets * jnp.log(jnp.maximum(targets, 1e-12)) - targets
       + 0.5 * jnp.log(2 * jnp.pi * jnp.maximum(targets, 1e-12))
       if full else 0.0))
sd_op("xw_plus_b")(lambda x, w, b: x @ w + b)
sd_op("relu_layer")(lambda x, w, b: jax.nn.relu(x @ w + b))


@sd_op("fused_batch_norm")
def _fused_batch_norm(x, scale, offset, mean=None, variance=None,
                      epsilon=1e-3, training=True):
    """NHWC fused BN returning (y, batch_mean, batch_var)."""
    if training or mean is None:
        mean = jnp.mean(x, axis=(0, 1, 2))
        variance = jnp.var(x, axis=(0, 1, 2))
    y = (x - mean) * lax.rsqrt(variance + epsilon) * scale + offset
    return y, mean, variance


@sd_op("ctc_greedy_decoder")
def _ctc_greedy_decoder(logits, sequence_length=None, blank_index=0):
    """Greedy CTC decode, padded form: logits [B, T, C] ->
    (decoded [B, T] zero-padded, lengths [B])."""
    ids = jnp.argmax(logits, axis=-1)  # [B, T]
    b, t = ids.shape
    prev = jnp.concatenate([jnp.full((b, 1), -1, ids.dtype), ids[:, :-1]],
                           axis=1)
    valid = (ids != blank_index) & (ids != prev)
    if sequence_length is not None:
        valid = valid & (jnp.arange(t)[None, :] < sequence_length[:, None])
    order = jnp.argsort(~valid, axis=1, stable=True)
    compact = jnp.take_along_axis(ids, order, axis=1)
    lengths = jnp.sum(valid, axis=1)
    return jnp.where(jnp.arange(t)[None, :] < lengths[:, None], compact, 0), \
        lengths


# ---- activations long tail -------------------------------------------------
sd_op("celu")(lambda x, alpha=1.0: jax.nn.celu(x, alpha=alpha))
sd_op("glu")(lambda x, axis=-1: jax.nn.glu(x, axis=int(axis)))
sd_op("hard_swish")(lambda x: x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))
sd_op("hardshrink")(lambda x, lambd=0.5: jnp.where(jnp.abs(x) > lambd, x, 0.0))
sd_op("softshrink")(lambda x, lambd=0.5: jnp.sign(x) * jnp.maximum(
    jnp.abs(x) - lambd, 0.0))
sd_op("tanhshrink")(lambda x: x - jnp.tanh(x))
sd_op("threshold_activation")(lambda x, theta=0.0: jnp.where(x > theta, x, 0.0))
sd_op("crelu")(lambda x, axis=-1: jax.nn.relu(
    jnp.concatenate([x, -x], axis=int(axis))))
sd_op("gelu_precise")(lambda x: jax.nn.gelu(x, approximate=False))


# ---- quantization ----------------------------------------------------------
@sd_op("fake_quant_with_min_max_args")
def _fake_quant_args(x, min=-6.0, max=6.0, num_bits=8):
    qmin, qmax = 0.0, float(2 ** int(num_bits) - 1)
    scale = (max - min) / (qmax - qmin)
    zero = qmin - min / scale
    zero = jnp.clip(jnp.round(zero), qmin, qmax)
    q = jnp.clip(jnp.round(x / scale + zero), qmin, qmax)
    return (q - zero) * scale


@sd_op("fake_quant_with_min_max_vars")
def _fake_quant_vars(x, min, max, num_bits=8):
    # min/max stay arrays: they arrive as tracers under jit, and the
    # arithmetic in _fake_quant_args is elementwise anyway
    return _fake_quant_args(x, min, max, num_bits)


@sd_op("quantize")
def _quantize(x, scale=1.0, zero_point=0, num_bits=8, signed=False):
    if signed:
        qmin = -(2 ** (int(num_bits) - 1))
        qmax = 2 ** (int(num_bits) - 1) - 1
    else:
        qmin, qmax = 0, 2 ** int(num_bits) - 1
    return jnp.clip(jnp.round(x / scale) + zero_point, qmin, qmax).astype(
        jnp.int32)


sd_op("dequantize")(lambda q, scale=1.0, zero_point=0:
                    (q.astype(jnp.float32) - zero_point) * scale)


# ---- linalg extras ---------------------------------------------------------
sd_op("self_adjoint_eig")(jnp.linalg.eigh)
sd_op("eigvalsh")(jnp.linalg.eigvalsh)
sd_op("matrix_power")(lambda x, n: jnp.linalg.matrix_power(x, int(n)))
sd_op("cholesky_solve")(lambda chol, rhs: jax.scipy.linalg.cho_solve(
    (chol, True), rhs))
sd_op("tensormmul")(lambda a, b, axes_a=None, axes_b=None: jnp.tensordot(
    a, b, axes=(tuple(int(i) for i in axes_a), tuple(int(i) for i in axes_b))))
sd_op("mmul_transpose")(lambda a, b, transpose_a=False, transpose_b=False:
                        jnp.matmul(a.T if transpose_a else a,
                                   b.T if transpose_b else b))
sd_op("matrix_diag_part_v2")(lambda x, k=0: jnp.diagonal(
    x, offset=int(k), axis1=-2, axis2=-1))
sd_op("tri")(lambda n, m=None, k=0: jnp.tri(
    int(n), None if m is None else int(m), int(k)))


# ---- creation / ranges -----------------------------------------------------
sd_op("zeros")(lambda shape=None, dtype=jnp.float32: jnp.zeros(
    [int(s) for s in shape], dtype))
sd_op("ones")(lambda shape=None, dtype=jnp.float32: jnp.ones(
    [int(s) for s in shape], dtype))
sd_op("logspace")(lambda start, stop, num=50, base=10.0: jnp.logspace(
    float(start), float(stop), int(num), base=float(base)))
sd_op("geomspace")(lambda start, stop, num=50: jnp.geomspace(
    float(start), float(stop), int(num)))


@sd_op("unique_padded")
def _unique_padded(x):
    vals, counts = get_sd_op("unique_with_counts_padded")(x)
    return vals, jnp.sum(counts > 0)


# ---- random extras ---------------------------------------------------------
@sd_op("random_binomial")
def _random_binomial(shape=None, n=1, p=0.5, rng=None):
    draws = jax.random.bernoulli(
        rng, p, (int(n),) + tuple(int(s) for s in shape))
    return jnp.sum(draws.astype(jnp.float32), axis=0)


@sd_op("random_multinomial")
def _random_multinomial(logits, num_samples=1, rng=None):
    draws = jax.random.categorical(
        rng, logits, axis=-1, shape=(int(num_samples), logits.shape[0]))
    return draws.T


@sd_op("random_laplace")
def _random_laplace(shape=None, mu=0.0, beta=1.0, rng=None):
    return mu + beta * jax.random.laplace(rng, [int(s) for s in shape])


@sd_op("random_cauchy")
def _random_cauchy(shape=None, loc=0.0, scale=1.0, rng=None):
    return loc + scale * jax.random.cauchy(rng, [int(s) for s in shape])


@sd_op("bincount_weighted")
def _bincount_weighted(x, weights, minlength=0):
    """Weighted bincount with static length (XLA-honest, like bincount).
    Rank-2 input follows TF DenseBincount per-row semantics."""
    from .ops_extended import _bincount
    return _bincount(x, minlength=minlength, weights=weights)


# ---- cumulative extras -----------------------------------------------------
sd_op("cumlogsumexp")(lambda x, axis=0: jax.lax.associative_scan(
    jnp.logaddexp, x, axis=int(axis)))
sd_op("cummax")(lambda x, axis=0: lax.associative_scan(
    jnp.maximum, x, axis=int(axis)))
sd_op("cummin")(lambda x, axis=0: lax.associative_scan(
    jnp.minimum, x, axis=int(axis)))
