"""TF control-flow import: While/If and the legacy V1 dataflow ops.

Reference: the reference's Kotlin import framework maps TF control flow onto
its SameDiff ControlFlow ops (Switch/Merge/Enter/Exit execution frames in an
op-by-op interpreter — SURVEY.md §2.2 "SameDiff core", §7 "THE thing XLA
while replaces"). Here both TF encodings land on the SameDiff structured
``while_loop``/``cond`` nodes (samediff.py), which compile to single
``lax.while_loop``/``lax.cond`` HLO ops — resident on device, no
per-iteration host round trips.

Two encodings are handled:

* **Functional** (TF2 / frozen ``tf.function``): ``While``/``StatelessWhile``
  and ``If``/``StatelessIf`` nodes whose ``cond``/``body``/branch attrs name
  FunctionDefs in the GraphDef library. Each FunctionDef is imported into a
  sub-SameDiff through the same TF_OP_RULES registry.
* **V1 dataflow** (``tf.compat.v1.while_loop`` / ``tf.compat.v1.cond``):
  - while: ``Enter -> Merge -> [LoopCond gate] -> Switch -> body ->
    NextIteration`` frames are reconstructed into a structured loop: Merges
    are the carry, the LoopCond input subexpression becomes the cond
    subgraph, Switch:1 ... NextIteration becomes the body subgraph, Exits
    are the loop outputs. Loop-invariant Enters are appended to the carry.
  - cond (no frame): Switch/Merge without LoopCond. Both branches are
    imported (they are side-effect free tensors) and Merge selects with
    ``where(pred, true_val, false_val)`` — the XLA-friendly formulation of
    the reference's dead/alive branch propagation.

Nested V1 frames (loop-in-loop) are rejected with a clear error; the
functional encoding nests fine (sub-SameDiffs recurse).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _fn_ref(ref: str) -> str:
    """FunctionDef node_def input refs are ``node:out_name:idx`` or
    ``node:out_name`` (idx 0) or a bare arg name; GraphDef refs are
    ``node:idx``. Canonicalize to ``node`` / ``node:idx``."""
    parts = ref.split(":")
    if len(parts) == 1:
        return ref
    if len(parts) == 3:
        return parts[0] if parts[2] == "0" else f"{parts[0]}:{parts[2]}"
    # two parts: numeric suffix = graphdef index form, else function out name
    return ref if parts[1].isdigit() else parts[0]


def import_tf_function(importer, fname: str):
    """Import GraphDef-library FunctionDef ``fname`` into a sub-SameDiff.
    Returns (sub_sd, output_names); placeholders are ``arg0..argN`` in
    signature order (the structured-node calling convention)."""
    from tensorflow.python.framework import tensor_util

    lib = {f.signature.name: f for f in importer.graph_def.library.function}
    if fname not in lib:
        raise ValueError(f"GraphDef library has no function {fname!r}")
    fdef = lib[fname]

    sub = importer.__class__()
    sub.graph_def = importer.graph_def  # nested functions resolve here
    for i, arg in enumerate(fdef.signature.input_arg):
        ph = sub.sd.placeholder(f"arg{i}")
        sub._produced[arg.name] = ph

    # FunctionDef.node_def carries no ordering guarantee — topo-sort first
    from .tf_import import _iterative_topo

    by_name = {n.name: n for n in fdef.node_def}
    deps = {
        n.name: [_fn_ref(i.lstrip("^")).split(":")[0] for i in n.input]
        for n in fdef.node_def
    }
    order = _iterative_topo(
        [n.name for n in fdef.node_def], deps,
        cycle_msg=f"function {fname!r}: cyclic node {{!r}}")

    for name in order:
        node = by_name[name]
        rewritten = type(node).FromString(node.SerializeToString())
        del rewritten.input[:]
        rewritten.input.extend(
            ("^" + _fn_ref(i[1:])) if i.startswith("^") else _fn_ref(i)
            for i in node.input
        )
        sub._import_node(rewritten, tensor_util)

    out_names = []
    for arg in fdef.signature.output_arg:
        ref = _fn_ref(fdef.ret[arg.name])
        var = sub.resolve(ref)
        out_names.append(var.name)
    return sub.sd, out_names


def register_functional_rules(tf_rule, TF_OP_RULES):
    """Install While/StatelessWhile and If/StatelessIf rules."""

    @tf_rule("While", "StatelessWhile")
    def _while(ctx):
        imp = ctx.importer
        cond_sd, cond_outs = import_tf_function(imp, ctx.attr["cond"].func.name)
        body_sd, body_outs = import_tf_function(imp, ctx.attr["body"].func.name)
        n = len(ctx.inputs)
        node_var = imp.sd._op(
            "while_loop", *(ctx.var(i) for i in range(n)), name=ctx.name,
            cond_graph=cond_sd, cond_outputs=cond_outs,
            body_graph=body_sd, body_outputs=body_outs, n_vars=n,
        )
        node_var.node.n_outputs = n
        outs = {i: imp.sd._op("getitem", node_var, item=i) for i in range(n)}
        imp._multi_outputs[ctx.name] = outs
        return outs[0]

    @tf_rule("If", "StatelessIf")
    def _if(ctx):
        imp = ctx.importer
        t_sd, t_outs = import_tf_function(imp, ctx.attr["then_branch"].func.name)
        f_sd, f_outs = import_tf_function(imp, ctx.attr["else_branch"].func.name)
        node_var = imp.sd._op(
            "cond", *(ctx.var(i) for i in range(len(ctx.inputs))), name=ctx.name,
            true_graph=t_sd, true_outputs=t_outs,
            false_graph=f_sd, false_outputs=f_outs, n_vars=len(ctx.inputs) - 1,
        )
        node_var.node.n_outputs = len(t_outs)
        outs = {i: imp.sd._op("getitem", node_var, item=i)
                for i in range(len(t_outs))}
        imp._multi_outputs[ctx.name] = outs
        return outs[0]


# ---------------------------------------------------------------------------
# V1 dataflow reconstruction
# ---------------------------------------------------------------------------

_V1_OPS = ("Enter", "Merge", "Switch", "Exit", "NextIteration", "LoopCond",
           "RefEnter", "RefMerge", "RefSwitch", "RefExit", "RefNextIteration")


def has_v1_control_flow(gd) -> bool:
    return any(n.op in _V1_OPS for n in gd.node)


class _Frame:
    def __init__(self, name: str):
        self.name = name
        self.enters: List = []       # Enter nodes
        self.merges: List = []       # Merge nodes (loop carry)
        self.loop_cond = None        # LoopCond node
        self.switches: Dict[str, object] = {}  # merge name -> Switch node
        self.exits: Dict[str, object] = {}     # switch name -> Exit node
        self.next_iters: Dict[str, object] = {}  # merge name -> NextIteration


def rewrite_v1_loops(gd):
    """Rewrite every V1 while frame in ``gd`` into a functional
    ``StatelessWhile`` node + library functions, so the main import path
    only ever sees functional control flow. Returns a NEW GraphDef.

    The reconstruction (canonical tf.compat.v1.while_loop layout):
      Enter(init_i) -> Merge_i <- NextIteration_i
      pred = subexpr(Merge_*) -> LoopCond
      Switch_i(Merge_i, LoopCond): :0 -> Exit_i (loop output),
                                   :1 -> body -> NextIteration_i
    Loop-invariant ``Enter``s (no Merge consumer) become extra carry slots
    returned unchanged by the body.
    """
    import tensorflow as tf
    from tensorflow.core.framework import (attr_value_pb2, function_pb2,
                                           node_def_pb2, op_def_pb2)

    by_name = {n.name: n for n in gd.node}
    consumers: Dict[str, List] = {}
    for n in gd.node:
        for i in n.input:
            src = i.lstrip("^").split(":")[0]
            consumers.setdefault(src, []).append(n)

    frames: Dict[str, _Frame] = {}
    for n in gd.node:
        if n.op in ("Enter", "RefEnter"):
            fname = n.attr["frame_name"].s.decode()
            frames.setdefault(fname, _Frame(fname)).enters.append(n)

    if not frames:
        return gd
    # frame nesting check: an Enter whose input chain passes through another
    # frame's non-Exit member means nesting
    for f in frames.values():
        for e in f.enters:
            src = by_name.get(e.input[0].split(":")[0])
            if src is not None and src.op in ("Enter", "Merge", "Switch",
                                              "NextIteration"):
                raise NotImplementedError(
                    "nested V1 while frames are not supported; re-export with "
                    "tf.function (functional While) instead")

    out = tf.compat.v1.GraphDef()
    out.versions.CopyFrom(gd.versions)
    out.library.CopyFrom(gd.library)

    removed: set = set()
    replacements: Dict[str, str] = {}  # old ref -> new ref
    new_nodes: List = []
    fn_counter = [0]

    for fname, fr in frames.items():
        # ---- gather structure ------------------------------------------
        for e in fr.enters:
            for c in consumers.get(e.name, []):
                if c.op in ("Merge", "RefMerge"):
                    if c not in fr.merges:
                        fr.merges.append(c)
        loop_conds = [n for n in gd.node if n.op == "LoopCond" and any(
            m.name in _ancestors(n, by_name, stop_ops=("Enter", "Merge"))
            for m in fr.merges)]
        if not fr.merges or not loop_conds:
            raise NotImplementedError(
                f"V1 frame {fname!r}: unrecognized loop structure "
                "(no Merge/LoopCond)")
        fr.loop_cond = loop_conds[0]
        for m in fr.merges:
            sw = [c for c in consumers.get(m.name, []) if c.op in ("Switch", "RefSwitch")]
            if len(sw) != 1:
                raise NotImplementedError(
                    f"V1 frame {fname!r}: loop var {m.name} has {len(sw)} "
                    "Switches (expected 1)")
            fr.switches[m.name] = sw[0]
            for c in consumers.get(sw[0].name, []):
                if c.op in ("Exit", "RefExit"):
                    fr.exits[m.name] = c
        # NextIteration per merge: merge.input[1]
        for m in fr.merges:
            ni_name = m.input[1].split(":")[0]
            ni = by_name.get(ni_name)
            if ni is None or ni.op not in ("NextIteration", "RefNextIteration"):
                raise NotImplementedError(
                    f"V1 frame {fname!r}: Merge {m.name} second input is not "
                    "NextIteration")
            fr.next_iters[m.name] = ni

        n_vars = len(fr.merges)
        # loop-invariant enters (referenced by body, not via a Merge)
        invariant = [e for e in fr.enters
                     if not any(m.input[0].split(":")[0] == e.name for m in fr.merges)]

        # ---- member sets ------------------------------------------------
        cond_members = _between(
            {m.name for m in fr.merges} | {e.name for e in invariant},
            {fr.loop_cond.input[0].split(":")[0]}, by_name)
        body_targets = {fr.next_iters[m.name].input[0].split(":")[0]
                        for m in fr.merges}
        body_members = _between(
            {fr.switches[m.name].name for m in fr.merges} | {e.name for e in invariant},
            body_targets, by_name)

        # ---- build FunctionDefs ----------------------------------------
        carry_refs = [f"arg_lv{i}" for i in range(n_vars)] + \
                     [f"arg_inv{j}" for j in range(len(invariant))]
        # boundary: inside cond, Merge_i reads arg i; inside body, Switch_i:1
        # reads arg i; invariant Enter j reads arg n_vars+j
        cond_bound = {m.name: carry_refs[i] for i, m in enumerate(fr.merges)}
        body_bound = {fr.switches[m.name].name: carry_refs[i]
                      for i, m in enumerate(fr.merges)}
        for j, e in enumerate(invariant):
            cond_bound[e.name] = carry_refs[n_vars + j]
            body_bound[e.name] = carry_refs[n_vars + j]

        idx = fn_counter[0]
        fn_counter[0] += 1
        cond_fn_name = f"__v1_loop_cond_{idx}"
        body_fn_name = f"__v1_loop_body_{idx}"

        cond_ret = [fr.loop_cond.input[0]]
        _make_function(
            out.library, cond_fn_name, carry_refs, cond_members, cond_bound,
            cond_ret, by_name, n_outputs=1)
        body_ret = [fr.next_iters[m.name].input[0] for m in fr.merges] + \
                   [carry_refs[n_vars + j] for j in range(len(invariant))]
        _make_function(
            out.library, body_fn_name, carry_refs, body_members, body_bound,
            body_ret, by_name, n_outputs=n_vars + len(invariant))

        # ---- the functional While node ---------------------------------
        wnode = node_def_pb2.NodeDef()
        wnode.name = f"__v1_while_{idx}"
        wnode.op = "StatelessWhile"
        for m in fr.merges:
            wnode.input.append(by_name[m.input[0].split(":")[0]].input[0])
        for e in invariant:
            wnode.input.append(e.input[0])
        wnode.attr["cond"].func.name = cond_fn_name
        wnode.attr["body"].func.name = body_fn_name
        # splice the While where the frame's LAST Enter sat: all its inputs
        # (the Enter inits) are already imported by then, and every Exit
        # consumer comes later — preserving the GraphDef's topological order
        frame_node_names = {e.name for e in fr.enters}
        last = [n.name for n in gd.node if n.name in frame_node_names][-1]
        new_nodes.append((last, wnode))

        # Exit_i -> while:i
        for i, m in enumerate(fr.merges):
            ex = fr.exits.get(m.name)
            if ex is not None:
                replacements[ex.name] = f"{wnode.name}:{i}" if i else wnode.name

        removed |= {n for n in cond_members} | {n for n in body_members}
        removed |= {e.name for e in fr.enters}
        removed |= {m.name for m in fr.merges}
        removed |= {fr.loop_cond.name}
        removed |= {s.name for s in fr.switches.values()}
        removed |= {x.name for x in fr.exits.values()}
        removed |= {ni.name for ni in fr.next_iters.values()}

    # a member (e.g. a Const shared by the loop body and outer graph) may be
    # consumed outside the frame: keep such nodes in the outer graph too
    changed = True
    while changed:
        changed = False
        for n in gd.node:
            if n.name in removed and n.name not in replacements:
                continue  # only surviving nodes pin dependencies
            survivors = [n] if n.name not in removed else []
            for s in survivors:
                for i in s.input:
                    base = i.lstrip("^").split(":")[0]
                    if base in removed and base not in replacements and \
                            base in by_name and by_name[base].op not in _V1_OPS:
                        removed.discard(base)
                        changed = True

    splice_at = {}
    for anchor, wnode in new_nodes:
        splice_at.setdefault(anchor, []).append(wnode)
    for n in gd.node:
        for wnode in splice_at.get(n.name, ()):  # anchors are removed nodes
            out.node.append(wnode)
        if n.name in removed:
            continue
        copied = node_def_pb2.NodeDef()
        copied.CopyFrom(n)
        del copied.input[:]
        for i in n.input:
            ctrl = i.startswith("^")
            base = i.lstrip("^").split(":")[0]
            if base in replacements:
                i = replacements[base] if not ctrl else "^" + replacements[base].split(":")[0]
            copied.input.append(i)
        out.node.append(copied)
    return out


def _ancestors(node, by_name, stop_ops=()):
    seen = set()
    stack = [i.lstrip("^").split(":")[0] for i in node.input]
    while stack:
        name = stack.pop()
        if name in seen or name not in by_name:
            continue
        seen.add(name)
        n = by_name[name]
        if n.op in stop_ops:
            continue
        stack.extend(i.lstrip("^").split(":")[0] for i in n.input)
    return seen


def _between(sources: set, targets: set, by_name) -> set:
    """Node names on paths from (exclusive) sources to (inclusive) targets."""
    members = set()
    stack = list(targets)
    while stack:
        name = stack.pop()
        if name in members or name in sources or name not in by_name:
            continue
        members.add(name)
        stack.extend(i.lstrip("^").split(":")[0] for i in by_name[name].input)
    return members


def _make_function(library, fn_name: str, arg_names: Sequence[str],
                   members: set, boundary: Dict[str, str],
                   ret_refs: Sequence[str], by_name, n_outputs: int) -> None:
    """Emit a FunctionDef with inputs ``arg_names``, body = copies of
    ``members`` with boundary refs rewritten to args, outputs = ret_refs."""
    from tensorflow.core.framework import function_pb2, node_def_pb2, types_pb2

    fdef = function_pb2.FunctionDef()
    fdef.signature.name = fn_name
    for a in arg_names:
        arg = fdef.signature.input_arg.add()
        arg.name = a
        arg.type = types_pb2.DT_FLOAT  # informational; import is dtype-agnostic

    def rewrite_ref(ref: str) -> str:
        ctrl = ref.startswith("^")
        body = ref.lstrip("^")
        base, _, idx = body.partition(":")
        if base in boundary:
            # Switch:1 / Merge:0 / Enter outputs all alias the carry arg
            new = boundary[base]
        else:
            new = base if not idx or idx == "0" else f"{base}:output:{idx}"
        if ctrl:
            return "^" + new.split(":")[0]
        return new

    for name in sorted(members):
        n = by_name[name]
        copied = fdef.node_def.add()
        copied.CopyFrom(n)
        del copied.input[:]
        for i in n.input:
            copied.input.append(rewrite_ref(i))

    for k in range(n_outputs):
        arg = fdef.signature.output_arg.add()
        arg.name = f"out{k}"
        arg.type = types_pb2.DT_FLOAT
        ref = ret_refs[k]
        base, _, idx = ref.partition(":")
        if base in boundary:
            fdef.ret[f"out{k}"] = boundary[base]
        elif ref.startswith("arg_"):
            fdef.ret[f"out{k}"] = ref
        else:
            fdef.ret[f"out{k}"] = f"{base}:output:{idx or '0'}"
    library.function.append(fdef)


def register_v1_cond_rules(tf_rule, TF_OP_RULES):
    """Frameless Switch/Merge (tf.compat.v1.cond): both branches are
    imported; Merge selects with where(pred, t, f)."""

    @tf_rule("Switch", "RefSwitch")
    def _switch(ctx):
        imp = ctx.importer
        data, pred = ctx.var(0), ctx.var(1)
        # both outputs carry the data; branch identity lives in _branch_of
        outs = {0: data, 1: data}
        imp._multi_outputs[ctx.name] = outs
        imp._branch_of[ctx.name] = pred
        return data

    @tf_rule("Merge", "RefMerge")
    def _merge(ctx):
        imp = ctx.importer
        pred = None
        sides: Dict[bool, object] = {}
        for i, ref in enumerate(ctx.inputs):
            info = imp.trace_branch(ref)
            if info is None:
                continue
            p, side = info
            pred = p
            sides[side] = ctx.var(i)
        if pred is None or len(sides) != 2:
            raise NotImplementedError(
                f"Merge {ctx.name!r}: could not associate inputs with a "
                "Switch predicate (only frameless tf.cond graphs supported)")
        out = imp.sd._op("select", pred, sides[True], sides[False], name=ctx.name)
        imp._multi_outputs[ctx.name] = {0: out}
        return out
