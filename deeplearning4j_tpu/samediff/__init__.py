from .ops import SD_OPS, get_sd_op
from .samediff import SDVariable, SameDiff
from .training import History, TrainingConfig

__all__ = ["History", "SDVariable", "SD_OPS", "SameDiff", "TrainingConfig", "get_sd_op"]
