"""SameDiff — the graph/autodiff engine.

Reference: org.nd4j.autodiff.samediff.SameDiff + SDVariable +
InferenceSession/TrainingSession (SURVEY.md §2.2/§3.3). The reference is an
op-by-op interpreter with per-op JNI dispatch; its own fast path exports to
the native graph executor. Here the DAG IS a jax-traceable program: execution,
gradients and training all compile to single XLA programs ("full-graph HLO
compile" — exactly the north star's ask for the BERT path, BASELINE.json:10).

Structure:
* a SameDiff holds nodes: placeholders, variables (trainable), constants and
  op nodes (op name from samediff/ops.py + attrs).
* SDVariable wraps a node id with numpy-style operators and .eval().
* ``sd.output(feeds, names)`` topologically evaluates — under jit.
* ``sd.calculate_gradients(feeds, wrt)`` = jax.grad over the traced program.
* ``sd.fit(iterator, TrainingConfig)`` = jitted train step (loss variable +
  optax updater), mirroring TrainingSession semantics.
* save/load: npz of variable arrays + JSON of graph topology (the FlatBuffers
  role); ``compile()`` returns an AOT-lowered XLA executable (the libnd4j
  graph-executor role).
"""

from __future__ import annotations

import dataclasses
import io
import json
import zipfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import RngState
from .ops import SD_OPS, get_sd_op


@dataclasses.dataclass
class Node:
    id: int
    name: str
    kind: str  # placeholder | variable | constant | op
    op: Optional[str] = None
    inputs: Tuple[int, ...] = ()
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    shape: Optional[Tuple[Optional[int], ...]] = None
    dtype: Optional[str] = None
    out_index: int = 0  # for multi-output ops: which output this node is
    n_outputs: int = 1


class SDVariable:
    def __init__(self, sd: "SameDiff", node: Node) -> None:
        self.sd = sd
        self.node = node

    @property
    def name(self) -> str:
        return self.node.name

    def rename(self, name: str) -> "SDVariable":
        old = self.node.name
        self.node.name = name
        self.sd._names.pop(old, None)
        self.sd._names[name] = self.node.id
        return self

    # ---- evaluation --------------------------------------------------------
    def eval(self, feeds: Optional[Dict[str, Any]] = None) -> np.ndarray:
        return np.asarray(self.sd.output(feeds or {}, [self.name])[self.name])

    # ---- operators ---------------------------------------------------------
    def _bin(self, op: str, other, reverse=False) -> "SDVariable":
        o = self.sd._lift(other)
        a, b = (o, self) if reverse else (self, o)
        return self.sd._op(op, a, b)

    def __add__(self, o):
        return self._bin("add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._bin("sub", o)

    def __rsub__(self, o):
        return self._bin("sub", o, reverse=True)

    def __mul__(self, o):
        return self._bin("mul", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._bin("div", o)

    def __rtruediv__(self, o):
        return self._bin("div", o, reverse=True)

    def __pow__(self, o):
        return self._bin("pow", o)

    def __neg__(self):
        return self.sd._op("neg", self)

    def __matmul__(self, o):
        return self._bin("matmul", o)

    def __getitem__(self, item):
        return self.sd._op("getitem", self, item=item)

    # comparison producing bool tensors (reference: SDVariable.gt etc.)
    def gt(self, o):
        return self._bin("gt", o)

    def lt(self, o):
        return self._bin("lt", o)

    def eq(self, o):
        return self._bin("eq", o)

    # common methods (reference SDVariable surface)
    def add(self, o):
        return self.__add__(o)

    def mul(self, o):
        return self.__mul__(o)

    def mmul(self, o):
        return self.__matmul__(o)

    def sum(self, *axis, keepdims=False):
        return self.sd._op("reduce_sum", self, axis=list(axis) or None, keepdims=keepdims)

    def mean(self, *axis, keepdims=False):
        return self.sd._op("reduce_mean", self, axis=list(axis) or None, keepdims=keepdims)

    def max(self, *axis, keepdims=False):
        return self.sd._op("reduce_max", self, axis=list(axis) or None, keepdims=keepdims)

    def min(self, *axis, keepdims=False):
        return self.sd._op("reduce_min", self, axis=list(axis) or None, keepdims=keepdims)

    def std(self, *axis, keepdims=False):
        return self.sd._op("reduce_std", self, axis=list(axis) or None, keepdims=keepdims)

    def norm2(self, *axis):
        return self.sd._op("norm2", self, axis=list(axis) or None)

    def reshape(self, *shape):
        return self.sd._op("reshape", self, shape=list(shape))

    def transpose(self, *perm):
        return self.sd._op("transpose", self, perm=list(perm) or None)

    def shape(self):
        return self.sd._op("shape_of", self)


class _Namespace:
    """Op-factory namespace (reference: sd.math(), sd.nn(), ...)."""

    def __init__(self, sd: "SameDiff", ops: Sequence[str]) -> None:
        self._sd = sd
        self._ops = set(ops)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._ops and name not in SD_OPS:
            raise AttributeError(f"No op {name!r} in this namespace")

        def call(*args, **kwargs):
            vars_, rest = [], []
            for a in args:
                if isinstance(a, SDVariable):
                    vars_.append(a)
                else:
                    rest.append(a)
            if rest:
                raise TypeError(
                    f"{name}: positional args must be SDVariables; pass attrs by keyword"
                )
            return self._sd._op(name, *vars_, **kwargs)

        return call


_MATH_OPS = [n for n in SD_OPS]


class SameDiff:
    def __init__(self) -> None:
        self._nodes: Dict[int, Node] = {}
        self._names: Dict[str, int] = {}
        self._values: Dict[int, jnp.ndarray] = {}  # variables + constants
        self._next_id = 0
        self._loss_name: Optional[str] = None
        self._rng = RngState(0)
        self._training = None  # TrainingSession
        self.math = _Namespace(self, _MATH_OPS)
        self.nn = _Namespace(self, _MATH_OPS)
        self.cnn = _Namespace(self, _MATH_OPS)
        self.rnn = _Namespace(self, _MATH_OPS)
        self.loss = _Namespace(self, _MATH_OPS)
        self.bitwise = _Namespace(self, _MATH_OPS)
        self.image = _Namespace(self, _MATH_OPS)
        self.linalg = _Namespace(self, _MATH_OPS)
        self.random = _Namespace(self, _MATH_OPS)

    # ------------------------------------------------------------- creation
    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    def _new_node(self, name: Optional[str], kind: str, **kw) -> Node:
        nid = self._next_id
        self._next_id += 1
        if name is None:
            name = f"{kw.get('op', kind)}_{nid}"
        if name in self._names:
            raise ValueError(f"Duplicate variable name {name!r}")
        node = Node(id=nid, name=name, kind=kind, **kw)
        self._nodes[nid] = node
        self._names[name] = nid
        return node

    def placeholder(self, name: str, shape: Sequence[Optional[int]] = None,
                    dtype: str = "float32") -> SDVariable:
        node = self._new_node(name, "placeholder",
                              shape=None if shape is None else tuple(shape), dtype=dtype)
        return SDVariable(self, node)

    # reference spelling
    def ph(self, name, shape=None, dtype="float32"):
        return self.placeholder(name, shape, dtype)

    def var(self, name: str, value=None, shape: Sequence[int] = None,
            dtype: str = "float32") -> SDVariable:
        """Trainable variable (reference: sd.var)."""
        if value is None:
            if shape is None:
                raise ValueError("var needs value or shape")
            value = 0.01 * jax.random.normal(self._rng.next_key(), tuple(shape), jnp.dtype(dtype))
        value = jnp.asarray(value)
        node = self._new_node(name, "variable", shape=tuple(value.shape), dtype=str(value.dtype))
        self._values[node.id] = value
        return SDVariable(self, node)

    def constant(self, value, name: Optional[str] = None) -> SDVariable:
        value = jnp.asarray(value)
        node = self._new_node(name, "constant", shape=tuple(value.shape), dtype=str(value.dtype))
        self._values[node.id] = value
        return SDVariable(self, node)

    def _lift(self, x) -> SDVariable:
        if isinstance(x, SDVariable):
            return x
        return self.constant(x)

    # ops evaluated structurally by _eval_graph, not via the op registry
    _STRUCTURAL_OPS = ("getitem", "while_loop", "cond")

    def _op(self, op: str, *inputs: SDVariable, name: Optional[str] = None, **attrs) -> Union[SDVariable, Tuple[SDVariable, ...]]:
        if op not in self._STRUCTURAL_OPS:
            get_sd_op(op)  # validate early
        for v in inputs:
            if v.sd is not self:
                # node ids are per-graph; a foreign id would silently bind
                # to an unrelated node (classic footgun: outer-graph vars
                # inside a while_loop/cond subgraph builder)
                raise ValueError(
                    f"{op}: input {v.name!r} belongs to a different SameDiff "
                    "graph. Control-flow subgraphs are closed: thread outer "
                    "values through loop_vars/operands, or recreate "
                    "constants on the subgraph handle."
                )
        node = self._new_node(name, "op", op=op, inputs=tuple(v.node.id for v in inputs),
                              attrs=attrs)
        # multi-output ops (split/unstack/svd/qr) produce view nodes lazily via
        # n_outputs attr when known
        return SDVariable(self, node)

    # ------------------------------------------------------- control flow
    def while_loop(self, loop_vars: Sequence[SDVariable], cond_fn, body_fn,
                   name: Optional[str] = None,
                   max_iters: Optional[int] = None) -> List[SDVariable]:
        """Structured while loop (reference: SameDiff.whileLoop; SURVEY.md
        §2.2 "THE thing XLA while replaces"): compiles to ONE
        ``lax.while_loop`` HLO instead of the reference's
        Switch/Merge/Enter/Exit interpreter frames.

        ``cond_fn(sub_sd, *args) -> SDVariable`` builds the scalar-bool
        predicate; ``body_fn(sub_sd, *args) -> sequence`` builds the next
        carry (same arity/dtypes as ``loop_vars``). Both receive a fresh
        sub-SameDiff whose placeholders ``arg0..argN`` are the loop carry.
        Returns one SDVariable per loop var (the final carry).

        ``max_iters``: when set, lowers to a BOUNDED ``lax.scan`` of that
        many steps with the condition applied as a pass-through select —
        identical forward values when the loop exits within the bound, and
        REVERSE-MODE DIFFERENTIABLE (``lax.while_loop`` is not; training
        through imported/authored loops needs this form).
        """
        n = len(loop_vars)
        cond_sd, cond_outs = self._build_subgraph(cond_fn, n)
        body_sd, body_outs = self._build_subgraph(body_fn, n)
        if len(cond_outs) != 1:
            raise ValueError("while_loop cond must produce exactly one value")
        if len(body_outs) != n:
            raise ValueError(
                f"while_loop body must return {n} values (the carry), got {len(body_outs)}")
        node_var = self._op(
            "while_loop", *loop_vars, name=name,
            cond_graph=cond_sd, cond_outputs=cond_outs,
            body_graph=body_sd, body_outputs=body_outs, n_vars=n,
            max_iters=max_iters,
        )
        node_var.node.n_outputs = n
        return [self._op("getitem", node_var, item=i) for i in range(n)]

    whileLoop = while_loop

    def ifCond(self, pred: SDVariable, operands: Sequence[SDVariable],
               true_fn, false_fn, name: Optional[str] = None) -> List[SDVariable]:
        """Structured conditional (reference: SameDiff.ifCond) compiling to
        ``lax.cond``. ``true_fn/false_fn(sub_sd, *args) -> sequence`` must
        return the same structure."""
        n = len(operands)
        t_sd, t_outs = self._build_subgraph(true_fn, n)
        f_sd, f_outs = self._build_subgraph(false_fn, n)
        if len(t_outs) != len(f_outs):
            raise ValueError("ifCond branches must return the same arity")
        node_var = self._op(
            "cond", pred, *operands, name=name,
            true_graph=t_sd, true_outputs=t_outs,
            false_graph=f_sd, false_outputs=f_outs, n_vars=n,
        )
        node_var.node.n_outputs = len(t_outs)
        return [self._op("getitem", node_var, item=i) for i in range(len(t_outs))]

    if_cond = ifCond

    @staticmethod
    def _build_subgraph(fn, n_args: int):
        sub = SameDiff()
        args = [sub.placeholder(f"arg{i}") for i in range(n_args)]
        outs = fn(sub, *args)
        if isinstance(outs, SDVariable):
            outs = [outs]
        return sub, [o.name for o in outs]

    def _subgraph_call(self, sub: "SameDiff", out_names: Sequence[str], args,
                      rng, training: bool):
        feeds = {f"arg{i}": v for i, v in enumerate(args)}
        res = sub._eval_graph(feeds, dict(sub._values), list(out_names),
                              rng=rng, training=training)
        return [res[o] for o in out_names]

    # ------------------------------------------------------------ accessors
    def get_variable(self, name: str) -> SDVariable:
        return SDVariable(self, self._nodes[self._names[name]])

    def variables(self) -> List[str]:
        return [n.name for n in self._nodes.values() if n.kind == "variable"]

    def placeholders(self) -> List[str]:
        return [n.name for n in self._nodes.values() if n.kind == "placeholder"]

    def set_loss_variables(self, *names: str) -> None:
        self._loss_name = names[0] if names else None

    def convert_to_variables(self, names: Optional[Sequence[str]] = None,
                             min_size: int = 2) -> List[str]:
        """Make constants trainable (reference: convertToVariable(s) — the
        import-then-finetune step: frozen-graph weights arrive as constants
        and must become variables before ``fit`` will update them).

        With ``names`` None, every float constant with at least ``min_size``
        elements converts (weights), leaving scalars and small shape-like
        constants frozen. Returns the converted names.
        """
        converted: List[str] = []
        if names is not None:
            targets = [self._nodes[self._names[n]] for n in names]
            # validate BEFORE mutating anything: a mid-loop raise would
            # leave the graph half-converted. Already-variable names are
            # idempotent no-ops (matching the reference's convertToVariable).
            for node in targets:
                if node.kind not in ("constant", "variable"):
                    raise ValueError(
                        f"{node.name!r} is {node.kind}, not constant")
            targets = [n for n in targets if n.kind == "constant"]
        else:
            targets = [n for n in self._nodes.values() if n.kind == "constant"]
        for node in targets:
            value = self._values.get(node.id)
            if names is None:
                if value is None or value.size < min_size or \
                        not jnp.issubdtype(jnp.asarray(value).dtype, jnp.floating):
                    continue
            node.kind = "variable"
            converted.append(node.name)
        if converted:
            # a cached TrainingSession snapshotted var_ids before the
            # conversion — it would silently keep the new variables frozen
            self._training = None
        return converted

    convertToVariables = convert_to_variables

    # ------------------------------------------------------------ execution
    def _eval_graph(
        self,
        feeds: Dict[str, Any],
        var_values: Dict[int, Any],
        targets: Sequence[str],
        rng: Optional[jax.Array] = None,
        training: bool = False,
    ) -> Dict[str, Any]:
        """Topological interpretation — runs under jax tracing, so jitting
        this IS full-graph compilation."""
        cache: Dict[int, Any] = {}

        def value_of(nid: int):
            if nid in cache:
                return cache[nid]
            node = self._nodes[nid]
            if node.kind == "placeholder":
                if node.name not in feeds:
                    raise KeyError(f"Missing placeholder feed: {node.name}")
                out = jnp.asarray(feeds[node.name])
            elif node.kind in ("variable", "constant"):
                out = var_values.get(nid, self._values.get(nid))
                if out is None:
                    raise KeyError(f"No value for {node.name}")
            else:
                ins = [value_of(i) for i in node.inputs]
                if node.op == "getitem":
                    out = ins[0][node.attrs["item"]]
                elif node.op == "while_loop":
                    out = self._eval_while(node, ins, rng, training)
                elif node.op == "cond":
                    out = self._eval_cond(node, ins, rng, training)
                else:
                    fn = get_sd_op(node.op)
                    attrs = dict(node.attrs)
                    if node.op in ("dropout", "random_normal", "random_uniform", "random_bernoulli"):
                        attrs["rng"] = (jax.random.fold_in(rng, nid) if rng is not None else None)
                        if node.op == "dropout":
                            attrs["deterministic"] = not training
                    out = fn(*ins, **attrs)
            cache[nid] = out
            return out

        return {t: value_of(self._names[t]) for t in targets}

    def _eval_while(self, node: Node, ins, rng, training: bool):
        """Compile a while_loop node to ``lax.while_loop``. The carry is the
        loop-var tuple; dtypes/shapes must be loop-invariant (XLA's rule —
        and the reason this beats an interpreter: one HLO While, resident on
        device, no per-iteration host round-trips)."""
        cond_sd, cond_outs = node.attrs["cond_graph"], node.attrs["cond_outputs"]
        body_sd, body_outs = node.attrs["body_graph"], node.attrs["body_outputs"]

        def cond(carry):
            res = self._subgraph_call(cond_sd, cond_outs, carry, rng, training)
            return jnp.reshape(jnp.asarray(res[0], jnp.bool_), ())

        def body(carry):
            res = self._subgraph_call(body_sd, body_outs, carry, rng, training)
            # lax requires carry-structure (incl. dtype) invariance
            return tuple(
                jnp.asarray(r, jnp.asarray(c).dtype) for r, c in zip(res, carry))

        init = tuple(jnp.asarray(v) for v in ins)
        max_iters = node.attrs.get("max_iters")
        if max_iters is not None:
            # bounded, reverse-differentiable form: scan max_iters steps.
            # lax.cond (not a both-branches select) so the body is NOT
            # evaluated on the frozen carry after exit — a where-based
            # select would poison gradients (0 * inf in the dead branch's
            # VJP) for bodies like sqrt/division whose domain the loop
            # condition guards.
            def scan_step(carry, _):
                out = jax.lax.cond(cond(carry), body,
                                   lambda c: tuple(c), carry)
                return out, None

            final, _ = jax.lax.scan(scan_step, init, None,
                                    length=int(max_iters))
            return final
        return jax.lax.while_loop(cond, body, init)

    def _eval_cond(self, node: Node, ins, rng, training: bool):
        """Compile a cond node to ``lax.cond`` (both branches traced, one
        executed — XLA's conditional HLO)."""
        t_sd, t_outs = node.attrs["true_graph"], node.attrs["true_outputs"]
        f_sd, f_outs = node.attrs["false_graph"], node.attrs["false_outputs"]
        pred, operands = ins[0], tuple(jnp.asarray(v) for v in ins[1:])

        def true_fn(args):
            return tuple(self._subgraph_call(t_sd, t_outs, args, rng, training))

        def false_fn(args):
            res = tuple(self._subgraph_call(f_sd, f_outs, args, rng, training))
            # unify branch output dtypes (lax.cond requires identical pytrees)
            return res

        t_shapes = jax.eval_shape(true_fn, operands)
        f_fn = false_fn

        def false_cast(args):
            return tuple(
                jnp.asarray(r, s.dtype) for r, s in zip(f_fn(args), t_shapes))

        p = jnp.reshape(jnp.asarray(pred, jnp.bool_), ())
        return jax.lax.cond(p, true_fn, false_cast, operands)

    def output(self, feeds: Dict[str, Any], outputs: Sequence[str],
               training: bool = False) -> Dict[str, np.ndarray]:
        """Execute (reference: SameDiff.output). Jitted per output-set."""
        var_values = dict(self._values)
        res = self._eval_graph(feeds, var_values, list(outputs), training=training)
        return res

    def batch_output(self, feeds, outputs):
        return self.output(feeds, outputs)

    def calculate_gradients(self, feeds: Dict[str, Any],
                            wrt: Sequence[str]) -> Dict[str, np.ndarray]:
        """Reverse-mode gradients of the loss variable w.r.t. named variables
        (reference: SameDiff.calculateGradients via createGradFunction)."""
        if self._loss_name is None:
            raise ValueError("No loss variable set (set_loss_variables)")
        wrt_ids = [self._names[w] for w in wrt]

        def loss_of(wrt_vals: List[Any]):
            var_values = dict(self._values)
            var_values.update(dict(zip(wrt_ids, wrt_vals)))
            out = self._eval_graph(feeds, var_values, [self._loss_name], training=True)
            loss = out[self._loss_name]
            return jnp.sum(loss)

        grads = jax.grad(loss_of)([self._values[i] for i in wrt_ids])
        return dict(zip(wrt, grads))

    # ------------------------------------------------------------- training
    def fit(self, iterator, training_config=None, epochs: int = 1,
            listeners=None):
        from .training import TrainingSession

        if self._training is None:
            self._training = TrainingSession(self, training_config,
                                             listeners=listeners)
        elif listeners:
            for l in listeners:
                self._training.listeners.add(l)
        return self._training.fit(iterator, epochs=epochs)

    # ---------------------------------------------------- AOT / serialization
    def compile(self, example_feeds: Dict[str, Any], outputs: Sequence[str]):
        """AOT full-graph compile (the libnd4j GraphExecutioner role):
        returns a compiled XLA executable over (variables, feeds)."""

        def fn(var_values, feeds):
            return self._eval_graph(feeds, var_values, list(outputs))

        lowered = jax.jit(fn).lower(dict(self._values), example_feeds)
        return lowered.compile()

    def save(self, path: str, with_updater: bool = False) -> None:
        """Reference: sd.save(file, withUpdaterState) — FlatBuffers role."""
        graph = {
            "nodes": [
                {
                    "id": n.id, "name": n.name, "kind": n.kind, "op": n.op,
                    "inputs": list(n.inputs),
                    "attrs": _jsonable_attrs(n.attrs),
                    "shape": n.shape, "dtype": n.dtype,
                }
                for n in self._nodes.values()
            ],
            "loss": self._loss_name,
        }
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("graph.json", json.dumps(graph))
            buf = io.BytesIO()
            np.savez(buf, **{str(nid): np.asarray(v) for nid, v in self._values.items()})
            zf.writestr("values.npz", buf.getvalue())

    @staticmethod
    def load(path: str) -> "SameDiff":
        sd = SameDiff()
        with zipfile.ZipFile(path) as zf:
            graph = json.loads(zf.read("graph.json"))
            z = np.load(io.BytesIO(zf.read("values.npz")))
            values = {int(k): jnp.asarray(z[k]) for k in z.files}
        for nd in graph["nodes"]:
            node = Node(
                id=nd["id"], name=nd["name"], kind=nd["kind"], op=nd.get("op"),
                inputs=tuple(nd.get("inputs", ())),
                attrs=_restore_attrs(nd.get("attrs", {})),
                shape=None if nd.get("shape") is None else tuple(nd["shape"]),
                dtype=nd.get("dtype"),
            )
            sd._nodes[node.id] = node
            sd._names[node.name] = node.id
            sd._next_id = max(sd._next_id, node.id + 1)
        sd._values = values
        sd._loss_name = graph.get("loss")
        return sd

    def summary(self) -> str:
        lines = [f"{'name':<32}{'kind':<12}{'op':<24}inputs"]
        for n in self._nodes.values():
            ins = ",".join(self._nodes[i].name for i in n.inputs)
            lines.append(f"{n.name:<32}{n.kind:<12}{n.op or '':<24}{ins}")
        return "\n".join(lines)


def _jsonable_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (np.ndarray, jnp.ndarray)):
            out[k] = {"@array": np.asarray(v).tolist(), "dtype": str(np.asarray(v).dtype)}
        elif isinstance(v, slice):
            out[k] = {"@slice": [v.start, v.stop, v.step]}
        elif isinstance(v, tuple):
            out[k] = {"@tuple": list(v)}
        elif isinstance(v, SameDiff):  # control-flow subgraph
            out[k] = {"@subgraph": _sd_to_dict(v)}
        else:
            out[k] = v
    return out


def _restore_attrs(attrs: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "@array" in v:
            out[k] = np.array(v["@array"], dtype=v["dtype"])
        elif isinstance(v, dict) and "@slice" in v:
            out[k] = slice(*v["@slice"])
        elif isinstance(v, dict) and "@tuple" in v:
            out[k] = tuple(v["@tuple"])
        elif isinstance(v, dict) and "@subgraph" in v:
            out[k] = _sd_from_dict(v["@subgraph"])
        else:
            out[k] = v
    return out


def _sd_to_dict(sd: SameDiff) -> Dict[str, Any]:
    """Inline-JSON form of a (sub)graph, values included — used for
    control-flow subgraphs stored in node attrs."""
    return {
        "nodes": [
            {
                "id": n.id, "name": n.name, "kind": n.kind, "op": n.op,
                "inputs": list(n.inputs), "attrs": _jsonable_attrs(n.attrs),
                "shape": n.shape, "dtype": n.dtype,
            }
            for n in sd._nodes.values()
        ],
        "loss": sd._loss_name,
        # binary npz in base64 (~1.33x raw bytes) — loop bodies can carry
        # weight-sized constants, which JSON float lists would blow up ~10x
        "values_npz_b64": _values_to_b64(sd._values),
    }


def _sd_from_dict(d: Dict[str, Any]) -> SameDiff:
    sd = SameDiff()
    for nd in d["nodes"]:
        node = Node(
            id=nd["id"], name=nd["name"], kind=nd["kind"], op=nd.get("op"),
            inputs=tuple(nd.get("inputs", ())),
            attrs=_restore_attrs(nd.get("attrs", {})),
            shape=None if nd.get("shape") is None else tuple(nd["shape"]),
            dtype=nd.get("dtype"),
        )
        sd._nodes[node.id] = node
        sd._names[node.name] = node.id
        sd._next_id = max(sd._next_id, node.id + 1)
    if "values_npz_b64" in d:
        sd._values = _values_from_b64(d["values_npz_b64"])
    else:  # graphs saved by earlier revisions used inline JSON lists
        sd._values = {
            int(k): jnp.asarray(np.array(v["data"], dtype=v["dtype"]))
            for k, v in d.get("values", {}).items()
        }
    sd._loss_name = d.get("loss")
    return sd


def _values_to_b64(values: Dict[int, Any]) -> str:
    import base64

    buf = io.BytesIO()
    np.savez(buf, **{str(nid): np.asarray(v) for nid, v in values.items()})
    return base64.b64encode(buf.getvalue()).decode("ascii")


def _values_from_b64(payload: str) -> Dict[int, Any]:
    import base64

    z = np.load(io.BytesIO(base64.b64decode(payload)))
    return {int(k): jnp.asarray(z[k]) for k in z.files}
